"""Flight recorder + per-phase latency attribution (ISSUE 5 tentpole).

The acceptance claims:

- one bounded-memory ring record per scheduled batch, whose tiled phase
  timings (featurize/device/commit/snapshot/other) sum to the batch's
  wall time;
- `scheduler_phase_duration_seconds{phase}` (and the sampled
  `scheduler_plugin_duration_seconds{plugin,extension_point}`)
  histograms appear in the registry exposition;
- dumps fire automatically on quarantine/engine fault and are readable
  via the `flight` frame, `GET /debug/flight`, and the `flight` CLI
  subcommand — all serving the same document;
- FailedScheduling/Preempted events carry the originating trace_id so
  they join their batch's flight record;
- /healthz tells degraded-but-serving from healthy (breaker/degraded
  state + journal-armed status), and a HOST-side HTTP listener keeps
  answering /metrics and /events while the breaker is open (the PR 2
  in-process guarantee, now covered over HTTP).
"""

import json
import os
import subprocess
import sys
import tempfile
import urllib.request

import pytest

from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.faults import FaultPlan
from kubernetes_tpu.framework.config import fit_only_profile
from kubernetes_tpu.framework.flight import FlightRecorder
from kubernetes_tpu.scheduler import TPUScheduler
from kubernetes_tpu.sidecar.host import ResyncingClient
from kubernetes_tpu.sidecar.metrics_http import ObservabilityHTTPServer
from kubernetes_tpu.sidecar.server import SidecarClient, SidecarServer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _node(name, cpu="8"):
    return make_node(name).capacity(
        {"cpu": cpu, "memory": "16Gi", "pods": 110}
    ).obj()


def _pod(name, cpu="100m"):
    return make_pod(name).req({"cpu": cpu, "memory": "64Mi"}).obj()


def _mk_sched(**kw):
    kw.setdefault("profile", fit_only_profile())
    kw.setdefault("batch_size", 8)
    return TPUScheduler(**kw)


def _serve(**kw):
    path = tempfile.mktemp(suffix=".sock")
    srv = SidecarServer(path, scheduler=_mk_sched(), **kw)
    srv.serve_background()
    return path, srv


def _http_get(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}") as r:
        return r.status, r.read()


# ---------------------------------------------------------------------------
# The ring itself


def test_ring_is_bounded_and_orders_markers_with_batches():
    fr = FlightRecorder(capacity=4, component="t")
    for i in range(9):
        fr.record_batch({"pods": i})
    fr.record_marker("breaker_trip", consecutive_failures=3)
    recs = fr.records()
    assert len(recs) == 4  # bounded: newest 4 of 10
    assert fr.snapshot()["recorded"] == 10
    assert recs[-1]["kind"] == "marker"
    assert recs[-1]["event"] == "breaker_trip"
    assert recs[-1]["consecutive_failures"] == 3
    # seq is monotonic across kinds — the ring reads as one timeline.
    assert [r["seq"] for r in recs] == sorted(r["seq"] for r in recs)
    assert fr.records(limit=2) == recs[-2:]


def test_batch_record_phases_tile_the_batch_wall_time():
    s = _mk_sched()
    for i in range(3):
        s.add_node(_node(f"n{i}"))
    for i in range(6):
        s.add_pod(_pod(f"p{i}"))
    out = s.schedule_batch()
    assert sum(1 for o in out if o.node_name) == 6
    (rec,) = s.flight.records()
    assert rec["kind"] == "batch"
    assert rec["pods"] == 6 and rec["scheduled"] == 6
    assert rec["trace_id"] and rec["span_id"]
    phases = rec["phases"]
    for phase in ("featurize", "device", "commit", "other"):
        assert phase in phases
    # The tiling contract: segments share boundary timestamps, so they
    # sum to the batch wall time (within rounding).
    assert abs(sum(phases.values()) - rec["wall_s"]) < 5e-3
    assert phases["device"] > 0


def test_phase_and_plugin_histograms_render_in_the_registry():
    s = _mk_sched()
    for i in range(3):
        s.add_node(_node(f"n{i}"))
    # Enough single-pod batches to pass the 1-in-10 per-site plugin
    # sampling gate at least once; distinct labels defeat the featurize
    # memo (a memo hit skips the per-op loop the sampler times).
    for i in range(12):
        s.add_pod(
            make_pod(f"p{i}")
            .req({"cpu": "100m", "memory": "64Mi"})
            .label("uniq", f"u{i}")
            .obj()
        )
        s.schedule_batch()
    text = s.metrics.registry.render_text()
    assert 'scheduler_phase_duration_seconds_bucket{le=' not in text  # labeled
    assert 'scheduler_phase_duration_seconds_bucket{' in text
    assert 'phase="device"' in text
    assert 'phase="featurize"' in text
    assert 'scheduler_plugin_duration_seconds_bucket{' in text
    assert 'extension_point="Featurize"' in text
    # The summary carries the same families (the dump/bench surface).
    summ = s.metrics.registry.summary()
    assert "scheduler_phase_duration_seconds" in summ["histograms"]


def test_quarantine_auto_dumps_and_event_joins_by_trace_id(tmp_path):
    s = _mk_sched()
    s.flight.dump_dir = str(tmp_path)
    FaultPlan().add_rule("engine", pod="default/bad").install_engine(s)
    for i in range(2):
        s.add_node(_node(f"n{i}"))
    s.add_pod(_pod("good"))
    s.add_pod(_pod("bad"))
    out = s.schedule_batch()
    by_uid = {o.pod.uid: o for o in out}
    assert by_uid["default/good"].node_name
    assert by_uid["default/bad"].node_name is None
    # Markers on the ring: the engine fault and the quarantine decision.
    events = [r["event"] for r in s.flight.records() if r["kind"] == "marker"]
    assert "engine_fault" in events and "quarantine" in events
    # ONE auto-dump per incident (written at the outermost recovery
    # exit, so it carries the quarantine markers too) — not a file per
    # bisect halving or per poison pod.
    dumps = sorted(os.listdir(tmp_path))
    assert len(dumps) == 1 and "engine_fault" in dumps[0]
    with open(tmp_path / dumps[0]) as f:
        doc = json.load(f)
    marks = [r for r in doc["records"] if r.get("event") == "quarantine"]
    assert marks and marks[0]["pod"] == "default/bad"
    # The FailedScheduling event carries the originating trace id, which
    # matches the quarantine marker's — event ↔ flight-record join.
    ev = [
        e for e in s.events.list()
        if e["reason"] == "FailedScheduling" and e["object"] == "default/bad"
    ]
    assert ev and ev[0]["trace_id"] == marks[0]["trace_id"]


def test_preempted_event_carries_trace_id():
    s = TPUScheduler(profile=fit_only_profile(), batch_size=4)
    s.add_node(_node("n0", cpu="2"))
    s.add_pod(make_pod("low").req({"cpu": "2"}).priority(1).obj())
    s.schedule_all_pending()
    s.add_pod(make_pod("vip").req({"cpu": "2"}).priority(1000).obj())
    s.schedule_all_pending(wait_backoff=True)
    ev = [e for e in s.events.list() if e["reason"] == "Preempted"]
    assert ev and ev[0]["trace_id"]


# ---------------------------------------------------------------------------
# The three read surfaces serve one document


def test_flight_frame_http_and_cli_agree(capsys):
    path, srv = _serve(http_port=0)
    client = SidecarClient(path)
    try:
        client.add("Node", _node("n0"))
        client.schedule([_pod("p0")], drain=True)
        frame = client.flight()
        assert frame["count"] == 1
        (rec,) = frame["records"]
        assert rec["phases"]["device"] > 0
        status, body = _http_get(srv.http.port, "/debug/flight")
        assert status == 200
        http_doc = json.loads(body)
        assert http_doc["records"] == frame["records"]
        # ?limit= keeps the newest N.
        status, body = _http_get(srv.http.port, "/debug/flight?limit=1")
        assert json.loads(body)["count"] == 1
        # CLI subcommand prints the same document.
        from kubernetes_tpu.__main__ import main as cli_main

        assert cli_main(["flight", "--socket", path]) == 0
        cli_doc = json.loads(capsys.readouterr().out)
        assert cli_doc["records"] == frame["records"]
    finally:
        client.close()
        srv.close()


def test_host_flight_merges_wire_ring_and_round_trip_series():
    path, srv = _serve()
    client = ResyncingClient(path, deadline_s=30.0)
    try:
        client.add("Node", _node("n0"))
        client.schedule([_pod("p0")], drain=True)
        doc = client.flight()
        assert doc["component"] == "scheduler" and doc["count"] >= 1
        host = doc["host"]
        assert host["component"] == "host"
        (rec,) = host["records"]
        assert rec["phases"]["wire"] > 0 and rec["bound"] == 1
        text = client.registry.render_text()
        assert "scheduler_sidecar_round_trip_duration_seconds_bucket" in text
        assert 'call="schedule"' in text
    finally:
        client.close()
        srv.close()


# ---------------------------------------------------------------------------
# /healthz: degraded-but-serving vs healthy; journal-armed status


def test_healthz_reports_journal_armed_both_ways(tmp_path):
    from kubernetes_tpu.journal import Journal
    from kubernetes_tpu.sidecar.metrics_http import health_state

    s = _mk_sched()
    assert health_state(s)["journal_armed"] is False
    s.attach_journal(Journal(str(tmp_path), epoch=1))
    state = health_state(s)
    assert state["journal_armed"] is True
    assert state["journal"]["epoch"] == 1


def test_degraded_host_serves_http_metrics_events_healthz_and_flight():
    """Satellite: the HTTP path of the PR 2 degraded-observability
    guarantee — /metrics and /events keep answering while the breaker is
    open, and /healthz says degraded-but-serving."""
    plan = (
        FaultPlan(seed=1)
        .add_rule("hang", op="schedule", every=True)
        .add_rule("hang", op="health", every=True)
    )
    path, srv = _serve()
    client = ResyncingClient(
        path,
        deadline_s=0.4,
        retry_interval_s=0.01,
        probe_interval_s=0.05,
        breaker_threshold=3,
        socket_wrapper=plan.wrap,
        fallback_factory=_mk_sched,
    )
    http = ObservabilityHTTPServer(client=client)
    http.serve_background()
    try:
        client.add("Node", _node("n0"))
        res = client.schedule([make_pod("p0").req({"cpu": "2"}).obj()])
        assert client.degraded and res[0].node_name  # degraded, serving
        # /healthz: degraded-but-serving, with the breaker counters.
        status, body = _http_get(http.port, "/healthz")
        assert status == 200
        state = json.loads(body)
        assert state["healthy"] is True
        assert state["host"]["sidecar_state"] == "degraded"
        assert state["host"]["breaker"]["trips"] == 1
        assert state["host"]["journal_armed"] is False
        # /metrics: the host registry (outage series) answers.
        status, body = _http_get(http.port, "/metrics")
        assert status == 200
        text = body.decode()
        assert 'scheduler_sidecar_state{state="degraded"} 1' in text
        assert "scheduler_degraded_dispatches_total 1" in text
        # /events: the fallback engine's ring answers.
        status, body = _http_get(http.port, "/events")
        assert status == 200
        events = json.loads(body)
        assert any(e["reason"] == "Scheduled" for e in events)
        # /debug/flight: the host ring, with the breaker-trip marker.
        status, body = _http_get(http.port, "/debug/flight")
        assert status == 200
        doc = json.loads(body)
        marks = [
            r for r in doc["host"]["records"] if r.get("kind") == "marker"
        ]
        assert any(m["event"] == "breaker_trip" for m in marks)
    finally:
        http.close()
        client.close()
        srv.close()


def test_breaker_trip_auto_dumps_host_ring(tmp_path):
    plan = (
        FaultPlan(seed=3)
        .add_rule("hang", op="schedule", every=True)
        .add_rule("hang", op="health", every=True)
    )
    path, srv = _serve()
    client = ResyncingClient(
        path,
        deadline_s=0.3,
        retry_interval_s=0.01,
        probe_interval_s=0.05,
        breaker_threshold=3,
        socket_wrapper=plan.wrap,
        fallback_factory=_mk_sched,
    )
    client.flight_recorder.dump_dir = str(tmp_path)
    try:
        client.add("Node", _node("n0"))
        client.schedule([_pod("p0")])
        assert client.degraded
        dumps = [d for d in os.listdir(tmp_path) if "breaker_trip" in d]
        assert dumps
        with open(tmp_path / dumps[0]) as f:
            doc = json.load(f)
        assert any(
            r.get("event") == "breaker_trip" for r in doc["records"]
        )
    finally:
        client.close()
        srv.close()


# ---------------------------------------------------------------------------
# profile_report.py


def test_profile_report_renders_phase_attribution_table(tmp_path):
    s = _mk_sched()
    for i in range(2):
        s.add_node(_node(f"n{i}"))
    for i in range(6):
        s.add_pod(_pod(f"p{i}"))
    s.schedule_all_pending()
    dump = s.flight.dump("manual", path=str(tmp_path / "dump.json"))
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "profile_report.py"), dump],
        capture_output=True,
        text=True,
        timeout=60,
    )
    assert proc.returncode == 0, proc.stderr
    assert "phase" in proc.stdout and "device" in proc.stdout
    assert "share" in proc.stdout


# ---------------------------------------------------------------------------
# bench surface


def test_run_workload_reports_phase_attribution_coverage():
    """The bench acceptance bar in miniature: the tiled phases cover
    >= 95% of the measured wall time on a real (small) workload."""
    from kubernetes_tpu.benchmarks.harness import Workload, run_workload

    w = Workload(
        name="flight_mini",
        baseline_pods_per_sec=0.0,
        build=lambda: _mk_sched(batch_size=32),
        nodes=lambda s: [s.add_node(_node(f"n{i}")) for i in range(8)],
        warmup=lambda s: [s.add_pod(_pod(f"w{i}")) for i in range(32)],
        measured=lambda s: [s.add_pod(_pod(f"m{i}")) for i in range(96)]
        and 96,
    )
    r = run_workload(w)
    assert r["scheduled"] == 96
    pa = r["phase_attribution"]
    assert pa["phases"]["device"] > 0
    assert pa["coverage"] >= 0.95


def test_live_registry_families_are_all_cataloged(tmp_path):
    """The README catalog (generated statically) must cover every family
    the LIVE registry renders — scheduler, journal, and host-side series
    alike (the catalog going stale fails here, not in production)."""
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    import check_lint

    tp = check_lint.load_tpulint()
    cataloged = {e["name"] for e in tp.collect_catalog(REPO)}

    from kubernetes_tpu.journal import Journal

    s = _mk_sched()
    s.attach_journal(Journal(str(tmp_path), epoch=1))
    for i in range(2):
        s.add_node(_node(f"n{i}"))
    for i in range(12):
        s.add_pod(
            make_pod(f"p{i}")
            .req({"cpu": "100m", "memory": "64Mi"})
            .label("uniq", f"u{i}")
            .obj()
        )
        s.schedule_batch()
    path, srv = _serve()
    client = ResyncingClient(path, deadline_s=30.0)
    try:
        client.add("Node", _node("h0"))
        client.schedule([_pod("hp0")], drain=True)
        rendered = s.metrics.registry.render_text()
        rendered += client.registry.render_text()
    finally:
        client.close()
        srv.close()
    live = {
        line.split()[2]
        for line in rendered.splitlines()
        if line.startswith("# TYPE ")
    }
    missing = live - cataloged
    assert not missing, (
        f"live registry families missing from the catalog: {sorted(missing)}"
        " — regenerate README's section with scripts/check_lint.py --catalog"
    )
