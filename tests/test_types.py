"""Quantity parsing, pod resource computation, scalar selector semantics."""

from kubernetes_tpu.api import types as t
from kubernetes_tpu.api.wrappers import make_node, make_pod


def test_parse_quantity_cpu():
    assert t.parse_quantity("100m", t.CPU) == 100
    assert t.parse_quantity("2", t.CPU) == 2000
    assert t.parse_quantity("1.5", t.CPU) == 1500
    assert t.parse_quantity(2, t.CPU) == 2000


def test_parse_quantity_memory():
    assert t.parse_quantity("1Gi", t.MEMORY) == 1024**3
    assert t.parse_quantity("500Mi", t.MEMORY) == 500 * 1024**2
    assert t.parse_quantity("1G", t.MEMORY) == 10**9
    assert t.parse_quantity("128", t.MEMORY) == 128
    # Fractions round up.
    assert t.parse_quantity("1.5", t.MEMORY) == 2


def test_pod_resource_request_containers_sum():
    pod = make_pod().req({"cpu": "100m", "memory": "1Gi"}).obj()
    pod.spec.containers.append(
        t.Container(name="c1", requests={"cpu": 200, "memory": 1024})
    )
    req = pod.resource_request()
    assert req[t.CPU] == 300
    assert req[t.MEMORY] == 1024**3 + 1024


def test_pod_resource_request_init_peak():
    pod = (
        make_pod()
        .req({"cpu": "100m"})
        .init_req({"cpu": "500m"})
        .obj()
    )
    assert pod.resource_request()[t.CPU] == 500


def test_pod_resource_request_sidecar():
    pod = (
        make_pod()
        .req({"cpu": "100m"})
        .init_req({"cpu": "50m"}, restart_policy=t.RESTART_POLICY_ALWAYS)
        .obj()
    )
    # Sidecar adds to the running total.
    assert pod.resource_request()[t.CPU] == 150


def test_pod_resource_request_overhead():
    pod = make_pod().req({"cpu": "100m"}).overhead({"cpu": "10m"}).obj()
    assert pod.resource_request()[t.CPU] == 110


def test_non_zero_request_defaults():
    pod = make_pod().obj()  # no requests at all
    cpu, mem = pod.non_zero_request()
    assert cpu == t.DEFAULT_MILLI_CPU_REQUEST
    assert mem == t.DEFAULT_MEMORY_REQUEST


def test_non_zero_request_partial():
    pod = make_pod().req({"cpu": "250m"}).obj()
    cpu, mem = pod.non_zero_request()
    assert cpu == 250
    assert mem == t.DEFAULT_MEMORY_REQUEST


def test_label_selector():
    sel = t.LabelSelector(match_labels=(("app", "web"),))
    assert t.label_selector_matches(sel, {"app": "web", "x": "y"})
    assert not t.label_selector_matches(sel, {"app": "db"})
    assert not t.label_selector_matches(None, {"app": "web"})
    # Empty selector matches everything.
    assert t.label_selector_matches(t.LabelSelector(), {})


def test_node_selector_ops():
    labels = {"zone": "a", "mem": "64"}
    r = t.NodeSelectorRequirement
    assert t.node_selector_requirement_matches(r("zone", t.OP_IN, ("a", "b")), labels)
    assert not t.node_selector_requirement_matches(r("zone", t.OP_IN, ("c",)), labels)
    assert t.node_selector_requirement_matches(r("zone", t.OP_NOT_IN, ("c",)), labels)
    assert t.node_selector_requirement_matches(r("missing", t.OP_NOT_IN, ("c",)), labels)
    assert t.node_selector_requirement_matches(r("zone", t.OP_EXISTS, ()), labels)
    assert t.node_selector_requirement_matches(r("missing", t.OP_DOES_NOT_EXIST, ()), labels)
    assert t.node_selector_requirement_matches(r("mem", t.OP_GT, ("32",)), labels)
    assert not t.node_selector_requirement_matches(r("mem", t.OP_GT, ("64",)), labels)
    assert t.node_selector_requirement_matches(r("mem", t.OP_LT, ("128",)), labels)
    # Non-integer values never match Gt/Lt.
    assert not t.node_selector_requirement_matches(r("zone", t.OP_GT, ("1",)), labels)


def test_toleration_tolerates():
    taint = t.Taint("dedicated", "gpu", t.EFFECT_NO_SCHEDULE)
    assert t.Toleration("dedicated", t.TOLERATION_OP_EQUAL, "gpu").tolerates(taint)
    assert not t.Toleration("dedicated", t.TOLERATION_OP_EQUAL, "cpu").tolerates(taint)
    assert t.Toleration("dedicated", t.TOLERATION_OP_EXISTS).tolerates(taint)
    assert t.Toleration(operator=t.TOLERATION_OP_EXISTS).tolerates(taint)  # empty key + Exists
    assert not t.Toleration(
        "dedicated", t.TOLERATION_OP_EXISTS, effect=t.EFFECT_NO_EXECUTE
    ).tolerates(taint)


def test_wrappers_node():
    node = make_node("n1").capacity({"cpu": "4", "memory": "8Gi", "pods": 110}).zone("z1").obj()
    assert node.status.allocatable[t.CPU] == 4000
    assert node.metadata.labels["topology.kubernetes.io/zone"] == "z1"
    assert node.metadata.labels["kubernetes.io/hostname"] == "n1"
