"""TaintToleration + NodePorts vectorized ops vs scalar reference semantics."""

import numpy as np

from kubernetes_tpu.api import types as t
from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.framework.config import Profile
from kubernetes_tpu.scheduler import TPUScheduler

from reference_impl import (
    taint_toleration_filter,
    taint_toleration_score_raw,
    node_ports_filter,
)


def taint_profile():
    return Profile(
        name="taints",
        filters=("TaintToleration",),
        scorers=(("TaintToleration", 3),),
    )


def ports_profile():
    return Profile(name="ports", filters=("NodePorts", "NodeResourcesFit"), scorers=())


def test_untolerated_noschedule_taint_filters_node():
    s = TPUScheduler(profile=taint_profile(), batch_size=8)
    s.add_node(make_node("tainted").capacity({"cpu": "4", "pods": 110}).taint("dedicated", "gpu").obj())
    s.add_node(make_node("clean").capacity({"cpu": "4", "pods": 110}).obj())
    s.add_pod(make_pod("p").req({"cpu": "1"}).obj())
    out = s.schedule_all_pending()
    assert out[0].node_name == "clean"


def test_toleration_admits_tainted_node():
    s = TPUScheduler(profile=taint_profile(), batch_size=8)
    s.add_node(make_node("tainted").capacity({"cpu": "4", "pods": 110}).taint("dedicated", "gpu").obj())
    s.add_pod(
        make_pod("p")
        .req({"cpu": "1"})
        .toleration(key="dedicated", value="gpu", effect=t.EFFECT_NO_SCHEDULE)
        .obj()
    )
    out = s.schedule_all_pending()
    assert out[0].node_name == "tainted"


def test_exists_toleration_any_effect():
    s = TPUScheduler(profile=taint_profile(), batch_size=8)
    s.add_node(
        make_node("t1").capacity({"cpu": "4", "pods": 110})
        .taint("k1", "v1", t.EFFECT_NO_EXECUTE).obj()
    )
    s.add_pod(make_pod("p").req({"cpu": "1"}).toleration(key="k1", op=t.TOLERATION_OP_EXISTS).obj())
    out = s.schedule_all_pending()
    assert out[0].node_name == "t1"


def test_prefer_no_schedule_scores_lower():
    """Node with an intolerable PreferNoSchedule taint loses to a clean one."""
    s = TPUScheduler(profile=taint_profile(), batch_size=8)
    s.add_node(
        make_node("soft-tainted").capacity({"cpu": "4", "pods": 110})
        .taint("soft", "x", t.EFFECT_PREFER_NO_SCHEDULE).obj()
    )
    s.add_node(make_node("clean").capacity({"cpu": "4", "pods": 110}).obj())
    s.add_pod(make_pod("p").req({"cpu": "1"}).obj())
    out = s.schedule_all_pending()
    assert out[0].node_name == "clean"


def test_taint_filter_matches_reference_randomized():
    rng = np.random.default_rng(7)
    effects = [t.EFFECT_NO_SCHEDULE, t.EFFECT_NO_EXECUTE, t.EFFECT_PREFER_NO_SCHEDULE]
    nodes = []
    for i in range(24):
        w = make_node(f"n{i}").capacity({"cpu": "64", "pods": 110})
        for j in range(int(rng.integers(0, 4))):
            w = w.taint(f"k{rng.integers(0, 5)}", f"v{rng.integers(0, 3)}", effects[int(rng.integers(0, 3))])
        nodes.append(w.obj())

    pods = []
    for i in range(30):
        w = make_pod(f"p{i}").req({"cpu": "1m"})
        for j in range(int(rng.integers(0, 4))):
            op = t.TOLERATION_OP_EXISTS if rng.integers(0, 2) else t.TOLERATION_OP_EQUAL
            eff = "" if rng.integers(0, 3) == 0 else effects[int(rng.integers(0, 3))]
            w = w.toleration(key=f"k{rng.integers(0, 5)}", op=op, value=f"v{rng.integers(0, 3)}", effect=eff)
        pods.append(w.obj())

    s = TPUScheduler(profile=taint_profile(), batch_size=32)
    for n in nodes:
        s.add_node(n)
    for p in pods:
        s.add_pod(p)
    out = {o.pod.name: o for o in s.schedule_all_pending()}

    for p in pods:
        feas_ref = [n for n in nodes if taint_toleration_filter(p, n)]
        o = out[p.name]
        assert (o.node_name is not None) == bool(feas_ref), p.name
        assert o.feasible_nodes == len(feas_ref), (p.name, o.feasible_nodes, len(feas_ref))
        if feas_ref:
            # winner must be among the reference's min-intolerable-count nodes
            # (weight 3 × normalized reverse score → max total ⇔ min raw count).
            counts = {n.name: taint_toleration_score_raw(p, n) for n in feas_ref}
            best = min(counts.values())
            assert counts[o.node_name] == best, (p.name, o.node_name, counts)


def test_host_port_conflict():
    s = TPUScheduler(profile=ports_profile(), batch_size=8)
    s.add_node(make_node("n1").capacity({"cpu": "8", "pods": 110}).obj())
    s.add_node(make_node("n2").capacity({"cpu": "8", "pods": 110}).obj())
    s.add_pod(make_pod("p1").req({"cpu": "1"}).host_port(8080).obj())
    s.add_pod(make_pod("p2").req({"cpu": "1"}).host_port(8080).obj())
    s.add_pod(make_pod("p3").req({"cpu": "1"}).host_port(8080).obj())
    out = {o.pod.name: o.node_name for o in s.schedule_all_pending()}
    # Two nodes, one 8080 each; third pod unschedulable.
    assert {out["p1"], out["p2"]} == {"n1", "n2"}
    assert out["p3"] is None


def test_host_port_wildcard_vs_specific_ip():
    s = TPUScheduler(profile=ports_profile(), batch_size=8)
    s.add_node(make_node("n1").capacity({"cpu": "8", "pods": 110}).obj())
    # Specific-IP use of 9090.
    s.add_pod(make_pod("p1").req({"cpu": "1"}).host_port(9090, host_ip="10.0.0.1").obj())
    out1 = s.schedule_all_pending()
    assert out1[0].node_name == "n1"
    # A different specific IP does not conflict.
    s.add_pod(make_pod("p2").req({"cpu": "1"}).host_port(9090, host_ip="10.0.0.2").obj())
    out2 = s.schedule_all_pending()
    assert out2[0].node_name == "n1"
    # A wildcard use conflicts with any same (proto, port).
    s.add_pod(make_pod("p3").req({"cpu": "1"}).host_port(9090).obj())
    out3 = s.schedule_all_pending()
    assert out3[0].node_name is None


def test_different_protocols_do_not_conflict():
    s = TPUScheduler(profile=ports_profile(), batch_size=8)
    s.add_node(make_node("n1").capacity({"cpu": "8", "pods": 110}).obj())
    s.add_pod(make_pod("p1").req({"cpu": "1"}).host_port(53, protocol="UDP").obj())
    s.add_pod(make_pod("p2").req({"cpu": "1"}).host_port(53, protocol="TCP").obj())
    out = [o.node_name for o in s.schedule_all_pending()]
    assert out == ["n1", "n1"]


def test_ports_match_reference_randomized():
    rng = np.random.default_rng(11)
    nodes = [make_node(f"n{i}").capacity({"cpu": "64", "pods": 110}).obj() for i in range(6)]
    pods = []
    for i in range(40):
        w = make_pod(f"p{i}").req({"cpu": "1m"})
        for _ in range(int(rng.integers(0, 3))):
            ip = ["", "10.0.0.1", "10.0.0.2"][int(rng.integers(0, 3))]
            w = w.host_port(int(rng.integers(8000, 8004)), host_ip=ip)
        pods.append(w.obj())

    s = TPUScheduler(profile=ports_profile(), batch_size=64)
    for n in nodes:
        s.add_node(n)
    for p in pods:
        s.add_pod(p)
    got = {o.pod.name: o.node_name for o in s.schedule_all_pending()}

    # Replay sequentially with the scalar oracle, honoring the device's picks
    # (decisions interact through committed state; verify each pick was legal
    # and that "unschedulable" pods truly had no feasible node).
    on_node: dict[str, list] = {n.name: [] for n in nodes}
    for p in pods:
        pick = got[p.name]
        feas = [n.name for n in nodes if node_ports_filter(p, on_node[n.name])]
        if pick is None:
            assert not feas, (p.name, feas)
        else:
            assert pick in feas, (p.name, pick, feas)
            on_node[pick].append(p)


def test_mirror_consistency_with_ports_and_taints():
    s = TPUScheduler(
        profile=Profile(
            name="mix",
            filters=("NodeResourcesFit", "TaintToleration", "NodePorts"),
            scorers=(("NodeResourcesFit", 1), ("TaintToleration", 3)),
        ),
        batch_size=16,
    )
    for i in range(4):
        w = make_node(f"n{i}").capacity({"cpu": "8", "memory": "16Gi", "pods": 64})
        if i % 2:
            w = w.taint("soft", "x", t.EFFECT_PREFER_NO_SCHEDULE)
        s.add_node(w.obj())
    for i in range(12):
        w = make_pod(f"p{i}").req({"cpu": "500m"})
        if i % 3 == 0:
            w = w.host_port(7000 + i)
        s.add_pod(w.obj())
    s.schedule_all_pending()
    assert s.builder.host_mirror_equal()
