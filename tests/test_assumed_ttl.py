"""Assumed-pod TTL cleanup (cache.go:730 cleanupAssumedPods, ticked from
cache.go:42).  The batch loop sweeps expired assumes at the top of each
batch; permit-room gang waiters are exempt (their expiry is the gang
timeout, scheduler.expire_waiting_gangs)."""

from kubernetes_tpu.api import types as t
from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.scheduler import TPUScheduler


def test_expired_assume_is_forgotten_and_requeued():
    s = TPUScheduler(batch_size=4)
    s.add_node(
        make_node("n1").capacity({"cpu": "4", "memory": "8Gi", "pods": 10}).obj()
    )
    # Simulate a bind confirmation that never arrived: assume directly,
    # never finish_binding, and age the record past the TTL.
    ghost = make_pod("ghost").req({"cpu": "1"}).obj()
    s.cache.assume_pod(ghost, "n1", device_already=False)
    s.cache.pods[ghost.uid].assumed_at -= 31.0
    assert s.cache.pods[ghost.uid].assumed

    # A fresh assumed pod under the TTL must survive the sweep.
    fresh = make_pod("fresh").req({"cpu": "1"}).obj()
    s.cache.assume_pod(fresh, "n1", device_already=False)

    out = s.schedule_all_pending()
    # The ghost was forgotten (resources released) and requeued — the batch
    # loop then scheduled it for real.
    assert any(o.pod.name == "ghost" and o.node_name == "n1" for o in out)
    pr = s.cache.pods[ghost.uid]
    assert pr.bound and not pr.assumed
    # The fresh assume is untouched.
    assert s.cache.pods[fresh.uid].assumed
    assert s.builder.host_mirror_equal()


def test_permit_waiters_survive_ttl_sweep():
    # batch_size=1 with a 2-member gang: the first member schedules alone and
    # parks in the WaitOnPermit room as assumed-not-bound.
    s = TPUScheduler(batch_size=1)
    s.add_node(
        make_node("n1").capacity({"cpu": "16", "memory": "64Gi", "pods": 110}).obj()
    )
    s.add_pod_group(t.PodGroup(name="g1", min_member=2))
    s.add_pod(make_pod("w0").req({"cpu": "1"}).pod_group("g1").obj())
    s.add_pod(make_pod("w1").req({"cpu": "1"}).pod_group("g1").obj())
    out0 = s.schedule_batch()
    assert out0 == [] or all(o.node_name is None for o in out0)
    assert len(s.permit_waiting.get("g1", ())) == 1
    waiter_uid = s.permit_waiting["g1"][0][0].pod.uid
    # Age the waiter's assume far past the TTL, then force a sweep with an
    # empty queue so only the sweep runs.
    s.cache.pods[waiter_uid].assumed_at -= 3600.0
    s._next_assumed_sweep = 0.0
    saved_pre = s._prefetched
    s._prefetched = None
    drained = s.queue.pop_batch(64)
    s.schedule_batch()
    # Still assumed, still waiting — the TTL sweep skipped it.
    assert s.cache.pods[waiter_uid].assumed
    assert len(s.permit_waiting.get("g1", ())) == 1
    # Restore and finish: the second member completes the gang.
    s._prefetched = saved_pre
    for qp in drained:
        s.queue.add(qp.pod)
    out = s.schedule_all_pending()
    bound = sorted(o.pod.name for o in out if o.node_name)
    assert "w0" in bound and "w1" in bound
    assert s.builder.host_mirror_equal()
