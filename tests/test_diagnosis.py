"""Per-pod failure diagnosis and precise requeue hints.

The device pass returns a per-op fail bitmask (the batch analog of
Diagnosis.UnschedulablePlugins, framework/types.go); the scheduler turns it
into narrow requeue hints, and update_node diffs the node record to emit
NODE_TAINT/NODE_LABEL (eventhandlers.go nodeSchedulingPropertiesChange)."""

from kubernetes_tpu.api import types as t
from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.scheduler import TPUScheduler


def tainted_node(name: str, cpu: str = "8"):
    return (
        make_node(name)
        .capacity({"cpu": cpu, "memory": "16Gi", "pods": 110})
        .taint("dedicated", "gpu", t.EFFECT_NO_SCHEDULE)
        .obj()
    )


def test_taint_rejection_diagnosis_and_requeue_on_taint_removal():
    s = TPUScheduler(batch_size=8)
    s.add_node(tainted_node("n1"))
    s.add_pod(make_pod("p").req({"cpu": "1"}).obj())
    out = s.schedule_all_pending()
    assert out[0].node_name is None
    assert out[0].diagnosis is not None
    assert out[0].diagnosis.unschedulable_plugins == {"TaintToleration"}
    uid = out[0].pod.uid
    assert uid in s.queue._unschedulable

    # A capacity-only change emits NODE_UPDATE — TaintToleration does not
    # care, so the pod must NOT wake.
    s.update_node(
        make_node("n1")
        .capacity({"cpu": "16", "memory": "16Gi", "pods": 110})
        .taint("dedicated", "gpu", t.EFFECT_NO_SCHEDULE)
        .obj()
    )
    assert uid in s.queue._unschedulable

    # Removing the taint emits NODE_TAINT → the pod wakes and schedules.
    s.update_node(
        make_node("n1").capacity({"cpu": "16", "memory": "16Gi", "pods": 110}).obj()
    )
    assert uid not in s.queue._unschedulable
    s.queue.flush_backoff()  # backoff may not have expired under real clock
    for qp in list(s.queue._info.values()):
        s.queue._push_active(qp)
    out2 = s.schedule_all_pending()
    assert out2 and out2[0].node_name == "n1"


def test_label_change_wakes_node_affinity_rejection():
    s = TPUScheduler(batch_size=8)
    s.add_node(make_node("n1").capacity({"cpu": "8", "pods": 110}).obj())
    s.add_pod(
        make_pod("p").req({"cpu": "1"}).node_affinity_in("disk", ["ssd"]).obj()
    )
    out = s.schedule_all_pending()
    assert out[0].node_name is None
    assert out[0].diagnosis.unschedulable_plugins == {"NodeAffinity"}
    uid = out[0].pod.uid
    assert uid in s.queue._unschedulable

    s.update_node(
        make_node("n1").capacity({"cpu": "8", "pods": 110}).label("disk", "ssd").obj()
    )
    assert uid not in s.queue._unschedulable


def test_mixed_failures_report_both_plugins():
    """One node fails on taints, the other on resources → both plugins in
    the diagnosis (each rejected a node that passed everything earlier)."""
    s = TPUScheduler(batch_size=8)
    s.add_node(tainted_node("big", cpu="64"))
    s.add_node(make_node("small").capacity({"cpu": "1", "pods": 110}).obj())
    s.add_pod(make_pod("p").req({"cpu": "8"}).obj())
    out = s.schedule_all_pending()
    assert out[0].node_name is None
    assert out[0].diagnosis.unschedulable_plugins == {
        "TaintToleration",
        "NodeResourcesFit",
    }


def test_scheduled_pod_has_no_diagnosis():
    s = TPUScheduler(batch_size=8)
    s.add_node(make_node("n1").capacity({"cpu": "8", "pods": 110}).obj())
    s.add_pod(make_pod("p").req({"cpu": "1"}).obj())
    out = s.schedule_all_pending()
    assert out[0].node_name == "n1"
    assert out[0].diagnosis is None
