"""Reflector — the client-go list/watch/resync slice
(tools/cache/reflector.go ListAndWatch; shared_informer.go resync;
DeltaFIFO Replace semantics for relists)."""

from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.framework.config import fit_only_profile
from kubernetes_tpu.informers import FakeSource, Reflector
from kubernetes_tpu.scheduler import TPUScheduler


def sched():
    return TPUScheduler(profile=fit_only_profile(), batch_size=8)


def _node(name, cpu="8"):
    return make_node(name).capacity({"cpu": cpu, "pods": 110}).obj()


def test_list_then_watch_feeds_scheduler():
    s = sched()
    src = FakeSource()
    src.add("n1", _node("n1"))
    nodes = Reflector(s, "Node", src.lister, src.watcher)
    pods = None
    assert nodes.step() == 1  # initial LIST
    assert "n1" in s.cache.nodes
    # Watch events resume from the established version.
    src.add("n2", _node("n2"))
    psrc = FakeSource()
    pods = Reflector(s, "Pod", psrc.lister, psrc.watcher)
    pods.step()
    psrc.add("default/p1", make_pod("p1").req({"cpu": "1"}).obj())
    assert nodes.step() == 1 and "n2" in s.cache.nodes
    assert pods.step() == 1
    out = s.schedule_all_pending()
    assert [(o.pod.name, bool(o.node_name)) for o in out] == [("p1", True)]


def test_watch_delete_and_update_route_correctly():
    s = sched()
    src = FakeSource()
    src.add("n1", _node("n1"))
    r = Reflector(s, "Node", src.lister, src.watcher)
    r.step()
    psrc = FakeSource()
    pr = Reflector(s, "Pod", psrc.lister, psrc.watcher)
    pr.step()
    bound = make_pod("p1").req({"cpu": "1"}).node("n1").obj()
    psrc.add("default/p1", bound)
    pr.step()
    assert "default/p1" in s.cache.pods
    psrc.delete("default/p1")
    pr.step()
    assert "default/p1" not in s.cache.pods
    # Node update flows through the diffing update path.
    src.update("n1", _node("n1", cpu="16"))
    r.step()
    assert s.cache.nodes["n1"].node.status.allocatable["cpu"] > 0


def test_stale_watch_relists_and_repairs_missed_delete():
    s = sched()
    src = FakeSource()
    src.add("n1", _node("n1"))
    src.add("n2", _node("n2"))
    r = Reflector(s, "Node", src.lister, src.watcher)
    r.step()
    assert set(s.cache.nodes) == {"n1", "n2"}
    # The watch gap: n2 deleted and history compacted — the resume point
    # is gone, so the next step relists and the REPLACE repairs the
    # missed delete.
    src.delete("n2")
    src.add("n3", _node("n3"))
    src.compact()
    r.step()
    assert r.relists == 1
    assert set(s.cache.nodes) == {"n1", "n3"}


def test_list_replace_deletes_vanished_pods():
    s = sched()
    nsrc = FakeSource()
    nsrc.add("n1", _node("n1"))
    Reflector(s, "Node", nsrc.lister, nsrc.watcher).step()
    psrc = FakeSource()
    pr = Reflector(s, "Pod", psrc.lister, psrc.watcher)
    psrc.add("default/gone", make_pod("gone").req({"cpu": "1"}).node("n1").obj())
    pr.step()  # initial list delivers the bound pod
    assert "default/gone" in s.cache.pods
    psrc.delete("default/gone")
    psrc.compact()
    pr.step()  # stale → relist → replace issues the delete
    assert "default/gone" not in s.cache.pods


def test_resync_redelivers_as_updates():
    ticks = [0.0]
    s = sched()
    src = FakeSource()
    src.add("n1", _node("n1"))
    r = Reflector(
        s, "Node", src.lister, src.watcher, resync_s=10.0,
        clock=lambda: ticks[0],
    )
    r.step()
    assert r.step() == 0  # nothing new, timer not due
    ticks[0] = 11.0
    assert r.step() == 1  # the stored node re-delivered as an update
    assert "n1" in s.cache.nodes


def test_replace_diffs_against_scheduler_not_just_store():
    # Regression (r5 review): objects seeded directly on the scheduler
    # before the Reflector attached are still repaired by LIST-as-replace.
    s = sched()
    s.add_node(_node("pre-seeded"))
    src = FakeSource()
    src.add("n1", _node("n1"))
    r = Reflector(s, "Node", src.lister, src.watcher)
    r.step()
    assert "pre-seeded" not in s.cache.nodes  # absent from the list: deleted
    assert "n1" in s.cache.nodes


def test_step_counts_relist_deliveries():
    # Regression (r5 review): the relist path returns delivered events,
    # not the surviving store size — deletes count.
    s = sched()
    src = FakeSource()
    src.add("n1", _node("n1"))
    src.add("n2", _node("n2"))
    r = Reflector(s, "Node", src.lister, src.watcher)
    r.step()
    src.delete("n1")
    src.delete("n2")
    src.compact()
    assert r.step() == 2  # two DELETED deliveries, store now empty
    assert not s.cache.nodes


# ---------------------------------------------------------------------------
# Generalized Reflector (ISSUE 9): the full object surface the plugins
# consume — per-kind stores, relist-replace, stale retry, recovery.
# ---------------------------------------------------------------------------

from kubernetes_tpu.api import types as t  # noqa: E402
from kubernetes_tpu.informers import (  # noqa: E402
    KIND_HANDLERS,
    ReflectorSet,
    reconcile_after_recovery,
)


def _pv(name, cap=10):
    return t.PersistentVolume(
        name=name, capacity=cap, storage_class="standard"
    )


def _pvc(name, ns="default"):
    return t.PersistentVolumeClaim(
        name=name, namespace=ns, storage_class="standard", request=1
    )


def _pdb(name, allowed=2):
    return t.PodDisruptionBudget(
        name=name,
        selector=t.LabelSelector(match_labels=(("app", "db"),)),
        disruptions_allowed=allowed,
    )


def test_generalized_reflector_feeds_every_kind():
    s = sched()
    sources = {}
    objs = {
        "PersistentVolume": ("pv1", _pv("pv1")),
        "PersistentVolumeClaim": ("default/c1", _pvc("c1")),
        "StorageClass": ("standard", t.StorageClass(name="standard")),
        "CSINode": ("n1", t.CSINode("n1", {"ebs": 4})),
        "PodDisruptionBudget": ("db", _pdb("db")),
        "ResourceClaim": (
            "default/rc1", t.ResourceClaim(name="rc1", device_class="tpu")
        ),
        "ResourceSlice": (
            "n1/tpu", t.ResourceSlice(node_name="n1", device_class="tpu",
                                      count=4)
        ),
    }
    for kind, (uid, obj) in objs.items():
        src = FakeSource()
        src.add(uid, obj)
        sources[kind] = (src.lister, src.watcher)
    # A pod referencing a PVC rides along: the set must deliver it LAST
    # (a cold-start pod judged against empty catalogs would mis-classify
    # its claims), with Node first.
    nsrc, psrc = FakeSource(), FakeSource()
    nsrc.add("n1", _node("n1"))
    psrc.add(
        "default/vp",
        make_pod("vp").req({"cpu": "1"}).pvc_volume("c1").node("n1").obj(),
    )
    sources["Node"] = (nsrc.lister, nsrc.watcher)
    sources["Pod"] = (psrc.lister, psrc.watcher)
    rset = ReflectorSet(s, sources)
    kinds_in_order = list(rset.reflectors)
    assert kinds_in_order[0] == "Node" and kinds_in_order[-1] == "Pod"
    assert rset.run_once() == len(objs) + 2
    assert "default/vp" in s.cache.pods
    vols = s.builder.volumes
    assert "pv1" in vols.pvs and "default/c1" in vols.pvcs
    assert "standard" in vols.classes and "n1" in vols.csinodes
    assert "db" in s.pdbs
    assert "default/rc1" in s.builder.dra.claims
    assert ("n1", "tpu") in s.builder.dra.slices


def test_pv_relist_replace_repairs_missed_delete():
    s = sched()
    src = FakeSource()
    src.add("pv1", _pv("pv1"))
    src.add("pv2", _pv("pv2"))
    r = Reflector(s, "PersistentVolume", src.lister, src.watcher)
    r.step()
    assert set(s.builder.volumes.pvs) == {"pv1", "pv2"}
    # Watch gap: pv2 deleted, pv3 added, history compacted — the stale
    # resume point forces a relist and the REPLACE repairs the delete.
    src.delete("pv2")
    src.add("pv3", _pv("pv3"))
    src.compact()
    r.step()
    assert r.relists == 1
    assert set(s.builder.volumes.pvs) == {"pv1", "pv3"}
    # The unbound index followed the delete (candidates_for reads it).
    assert "pv2" not in s.builder.volumes.unbound.get("standard", {})


def test_pvc_and_pdb_relist_replace():
    s = sched()
    csrc, bsrc = FakeSource(), FakeSource()
    csrc.add("default/c1", _pvc("c1"))
    csrc.add("default/c2", _pvc("c2"))
    bsrc.add("db", _pdb("db"))
    cr = Reflector(s, "PersistentVolumeClaim", csrc.lister, csrc.watcher)
    br = Reflector(s, "PodDisruptionBudget", bsrc.lister, bsrc.watcher)
    cr.step()
    br.step()
    assert set(s.builder.volumes.pvcs) == {"default/c1", "default/c2"}
    assert "db" in s.pdbs
    csrc.delete("default/c2")
    csrc.compact()  # StaleResourceVersion → relist-and-replace
    bsrc.delete("db")
    cr.step()
    br.step()
    assert set(s.builder.volumes.pvcs) == {"default/c1"}
    assert "db" not in s.pdbs


def test_object_reflector_stale_version_retries():
    s = sched()
    src = FakeSource()
    src.add("db", _pdb("db", allowed=1))
    r = Reflector(s, "PodDisruptionBudget", src.lister, src.watcher)
    r.step()
    src.update("db", _pdb("db", allowed=5))
    src.compact()
    n = r.step()  # stale → relist delivers the update
    assert n >= 1 and r.relists == 1
    assert s.pdbs["db"].disruptions_allowed == 5


def test_reconcile_after_recovery_relists_object_catalogs():
    # A recovered scheduler reconciles PV/PVC/PDB alongside nodes/pods:
    # catalogs repopulate from the LIST, pre-seeded strays are replaced.
    s = sched()
    s.add_pv(_pv("stale-pv"))  # pre-crash stray absent from host truth
    s.add_pdb(_pdb("stale-db"))
    nsrc, psrc = FakeSource(), FakeSource()
    nsrc.add("n1", _node("n1"))
    pvsrc, pvcsrc, pdbsrc = FakeSource(), FakeSource(), FakeSource()
    pvsrc.add("pv1", _pv("pv1"))
    pvcsrc.add("default/c1", _pvc("c1"))
    pdbsrc.add("db", _pdb("db"))
    stats = reconcile_after_recovery(
        s,
        Reflector(s, "Node", nsrc.lister, nsrc.watcher),
        Reflector(s, "Pod", psrc.lister, psrc.watcher),
        object_reflectors=(
            Reflector(s, "PersistentVolume", pvsrc.lister, pvsrc.watcher),
            Reflector(
                s, "PersistentVolumeClaim", pvcsrc.lister, pvcsrc.watcher
            ),
            Reflector(
                s, "PodDisruptionBudget", pdbsrc.lister, pdbsrc.watcher
            ),
        ),
    )
    assert stats["objects:PersistentVolume"] == 2  # stray delete + add
    assert set(s.builder.volumes.pvs) == {"pv1"}
    assert set(s.builder.volumes.pvcs) == {"default/c1"}
    assert set(s.pdbs) == {"db"}


def test_kind_handlers_cover_the_plugin_surface():
    # The generalized surface must carry every catalog the plugins read,
    # plus Lease (ISSUE 14's takeover rung: heartbeat state relists from
    # host truth instead of re-deriving from a re-fed schedule).
    assert set(KIND_HANDLERS) == {
        "PersistentVolume", "PersistentVolumeClaim", "StorageClass",
        "CSINode", "PodDisruptionBudget", "ResourceClaim", "ResourceSlice",
        "Lease",
    }


def test_relist_restarts_resync_period():
    # Regression (r5 review): a relist re-delivered everything; the
    # resync timer restarts so the next step doesn't double-deliver.
    ticks = [0.0]
    s = sched()
    src = FakeSource()
    src.add("n1", _node("n1"))
    r = Reflector(s, "Node", src.lister, src.watcher, resync_s=10.0,
                  clock=lambda: ticks[0])
    r.step()
    ticks[0] = 9.9
    src.compact()
    src.add("n2", _node("n2"))  # post-compaction event: resume point is gone
    assert r.step() >= 1  # relist (stale) delivered n2 + survivor update
    assert r.step() == 0  # timer restarted at 9.9+10: not due at 9.9
    ticks[0] = 21.0
    assert r.step() == 2  # resync re-delivers both stored nodes
