"""Reflector — the client-go list/watch/resync slice
(tools/cache/reflector.go ListAndWatch; shared_informer.go resync;
DeltaFIFO Replace semantics for relists)."""

from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.framework.config import fit_only_profile
from kubernetes_tpu.informers import FakeSource, Reflector
from kubernetes_tpu.scheduler import TPUScheduler


def sched():
    return TPUScheduler(profile=fit_only_profile(), batch_size=8)


def _node(name, cpu="8"):
    return make_node(name).capacity({"cpu": cpu, "pods": 110}).obj()


def test_list_then_watch_feeds_scheduler():
    s = sched()
    src = FakeSource()
    src.add("n1", _node("n1"))
    nodes = Reflector(s, "Node", src.lister, src.watcher)
    pods = None
    assert nodes.step() == 1  # initial LIST
    assert "n1" in s.cache.nodes
    # Watch events resume from the established version.
    src.add("n2", _node("n2"))
    psrc = FakeSource()
    pods = Reflector(s, "Pod", psrc.lister, psrc.watcher)
    pods.step()
    psrc.add("default/p1", make_pod("p1").req({"cpu": "1"}).obj())
    assert nodes.step() == 1 and "n2" in s.cache.nodes
    assert pods.step() == 1
    out = s.schedule_all_pending()
    assert [(o.pod.name, bool(o.node_name)) for o in out] == [("p1", True)]


def test_watch_delete_and_update_route_correctly():
    s = sched()
    src = FakeSource()
    src.add("n1", _node("n1"))
    r = Reflector(s, "Node", src.lister, src.watcher)
    r.step()
    psrc = FakeSource()
    pr = Reflector(s, "Pod", psrc.lister, psrc.watcher)
    pr.step()
    bound = make_pod("p1").req({"cpu": "1"}).node("n1").obj()
    psrc.add("default/p1", bound)
    pr.step()
    assert "default/p1" in s.cache.pods
    psrc.delete("default/p1")
    pr.step()
    assert "default/p1" not in s.cache.pods
    # Node update flows through the diffing update path.
    src.update("n1", _node("n1", cpu="16"))
    r.step()
    assert s.cache.nodes["n1"].node.status.allocatable["cpu"] > 0


def test_stale_watch_relists_and_repairs_missed_delete():
    s = sched()
    src = FakeSource()
    src.add("n1", _node("n1"))
    src.add("n2", _node("n2"))
    r = Reflector(s, "Node", src.lister, src.watcher)
    r.step()
    assert set(s.cache.nodes) == {"n1", "n2"}
    # The watch gap: n2 deleted and history compacted — the resume point
    # is gone, so the next step relists and the REPLACE repairs the
    # missed delete.
    src.delete("n2")
    src.add("n3", _node("n3"))
    src.compact()
    r.step()
    assert r.relists == 1
    assert set(s.cache.nodes) == {"n1", "n3"}


def test_list_replace_deletes_vanished_pods():
    s = sched()
    nsrc = FakeSource()
    nsrc.add("n1", _node("n1"))
    Reflector(s, "Node", nsrc.lister, nsrc.watcher).step()
    psrc = FakeSource()
    pr = Reflector(s, "Pod", psrc.lister, psrc.watcher)
    psrc.add("default/gone", make_pod("gone").req({"cpu": "1"}).node("n1").obj())
    pr.step()  # initial list delivers the bound pod
    assert "default/gone" in s.cache.pods
    psrc.delete("default/gone")
    psrc.compact()
    pr.step()  # stale → relist → replace issues the delete
    assert "default/gone" not in s.cache.pods


def test_resync_redelivers_as_updates():
    ticks = [0.0]
    s = sched()
    src = FakeSource()
    src.add("n1", _node("n1"))
    r = Reflector(
        s, "Node", src.lister, src.watcher, resync_s=10.0,
        clock=lambda: ticks[0],
    )
    r.step()
    assert r.step() == 0  # nothing new, timer not due
    ticks[0] = 11.0
    assert r.step() == 1  # the stored node re-delivered as an update
    assert "n1" in s.cache.nodes


def test_replace_diffs_against_scheduler_not_just_store():
    # Regression (r5 review): objects seeded directly on the scheduler
    # before the Reflector attached are still repaired by LIST-as-replace.
    s = sched()
    s.add_node(_node("pre-seeded"))
    src = FakeSource()
    src.add("n1", _node("n1"))
    r = Reflector(s, "Node", src.lister, src.watcher)
    r.step()
    assert "pre-seeded" not in s.cache.nodes  # absent from the list: deleted
    assert "n1" in s.cache.nodes


def test_step_counts_relist_deliveries():
    # Regression (r5 review): the relist path returns delivered events,
    # not the surviving store size — deletes count.
    s = sched()
    src = FakeSource()
    src.add("n1", _node("n1"))
    src.add("n2", _node("n2"))
    r = Reflector(s, "Node", src.lister, src.watcher)
    r.step()
    src.delete("n1")
    src.delete("n2")
    src.compact()
    assert r.step() == 2  # two DELETED deliveries, store now empty
    assert not s.cache.nodes


def test_relist_restarts_resync_period():
    # Regression (r5 review): a relist re-delivered everything; the
    # resync timer restarts so the next step doesn't double-deliver.
    ticks = [0.0]
    s = sched()
    src = FakeSource()
    src.add("n1", _node("n1"))
    r = Reflector(s, "Node", src.lister, src.watcher, resync_s=10.0,
                  clock=lambda: ticks[0])
    r.step()
    ticks[0] = 9.9
    src.compact()
    src.add("n2", _node("n2"))  # post-compaction event: resume point is gone
    assert r.step() >= 1  # relist (stale) delivered n2 + survivor update
    assert r.step() == 0  # timer restarted at 9.9+10: not due at 9.9
    ticks[0] = 21.0
    assert r.step() == 2  # resync re-delivers both stored nodes
