"""Speculative batching frontend (sidecar/speculate.py): the integrated
one-pod-per-call path answered from batch-computed decisions.

The Go plugin's PreFilter asks for one pod per wire call (the reference's
serialized ScheduleOne loop, scheduler.go:470).  With PendingPod hints
streamed ahead, the sidecar schedules whole batches speculatively and
serves the per-pod calls from cache — these tests pin the cache's hit,
invalidation, confirmation, and parity behavior."""

import tempfile

from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.scheduler import TPUScheduler
from kubernetes_tpu.sidecar.server import SidecarClient, SidecarServer


def node(name: str, cpu: str = "8"):
    return make_node(name).capacity(
        {"cpu": cpu, "memory": "32Gi", "pods": 110}
    ).obj()


def pod(name: str, cpu: str = "1", priority: int = 0):
    p = make_pod(name).req({"cpu": cpu})
    if priority:
        p = p.priority(priority)
    return p.obj()


def _spec_server(batch_size=8, lookahead=None):
    path = tempfile.mktemp(suffix=".sock")
    srv = SidecarServer(
        path,
        scheduler=TPUScheduler(batch_size=batch_size),
        speculate=True,
        lookahead=lookahead,
    )
    srv.serve_background()
    return srv, SidecarClient(path)


def test_hints_turn_per_pod_calls_into_cache_hits():
    srv, client = _spec_server()
    try:
        for i in range(4):
            client.add("Node", node(f"n{i}"))
        pods = [pod(f"p{i}") for i in range(8)]
        for p in pods:
            client.add("PendingPod", p)
        # The integrated pattern: one pod per Schedule call, serialized.
        bound = {}
        for p in pods:
            (r,) = client.schedule([p], drain=False)
            assert r.pod_uid == p.uid
            assert r.node_name
            bound[r.pod_uid] = r.node_name
        stats = client.dump()["speculation"]
        assert stats["misses"] == 1  # one device batch served all 8 calls
        assert stats["hits"] == 7
        assert stats["speculated"] == 7
        # Capacity respected: 8 one-cpu pods over 4 eight-cpu nodes.
        per_node = {}
        for n in bound.values():
            per_node[n] = per_node.get(n, 0) + 1
        assert sum(per_node.values()) == 8
    finally:
        client.close()
        srv.close()


def test_speculative_decisions_match_drain_batch():
    """Same arrival order ⇒ the speculative per-pod path and a plain drain
    batch commit identical assignments (the QueueSort-order contract)."""
    pods = [pod(f"p{i}", priority=i % 3) for i in range(12)]

    path = tempfile.mktemp(suffix=".sock")
    plain = SidecarServer(path, scheduler=TPUScheduler(batch_size=16))
    plain.serve_background()
    c1 = SidecarClient(path)
    for i in range(4):
        c1.add("Node", node(f"n{i}"))
    want = {r.pod_uid: r.node_name for r in c1.schedule(pods, drain=True)}
    c1.close()
    plain.close()

    srv, client = _spec_server(batch_size=16)
    try:
        for i in range(4):
            client.add("Node", node(f"n{i}"))
        for p in pods:
            client.add("PendingPod", p)
        got = {}
        for p in sorted(pods, key=lambda p: -p.spec.priority):
            (r,) = client.schedule([p], drain=False)
            got[r.pod_uid] = r.node_name
        assert got == want
    finally:
        client.close()
        srv.close()


def test_mutation_invalidates_and_rolls_back():
    srv, client = _spec_server()
    try:
        for i in range(2):
            client.add("Node", node(f"n{i}", cpu="4"))
        pods = [pod(f"p{i}", cpu="1") for i in range(6)]
        for p in pods:
            client.add("PendingPod", p)
        (r0,) = client.schedule([pods[0]], drain=False)
        assert r0.node_name
        # A NEW node appearing does NOT stale committed bind decisions
        # (upstream pods scheduled against a pre-add snapshot keep their
        # bindings too) — the cache survives, scoped invalidation.
        client.add("Node", node("n-new", cpu="4"))
        (r1,) = client.schedule([pods[1]], drain=False)
        assert r1.node_name
        stats = client.dump()["speculation"]
        assert stats["invalidations"] == 0
        # A label change on a chosen node remaps topology domains —
        # THAT is a global mutation and rolls the cache back.
        n0 = node("n0", cpu="4")
        n0.metadata.labels["pool"] = "tainted"
        client.add("Node", n0)
        stats = client.dump()["speculation"]
        assert stats["invalidations"] >= 1
        assert stats["rolled_back"] >= 1
        # Remaining pods still schedule, against the post-mutation state.
        for p in pods[2:]:
            (r,) = client.schedule([p], drain=False)
            assert r.node_name
        dump = client.dump()
        assert dump["mirror_equal"]
        # Every pod is bound exactly once; per-node cpu stays within 4.
        per_node = {}
        for uid, rec in dump["pods"].items():
            per_node[rec["node"]] = per_node.get(rec["node"], 0) + 1
        assert sum(per_node.values()) == 6
        assert all(c <= 4 for c in per_node.values())
    finally:
        client.close()
        srv.close()


def test_bind_confirmation_preserves_cache():
    srv, client = _spec_server()
    try:
        client.add("Node", node("n0"))
        pods = [pod(f"p{i}") for i in range(4)]
        for p in pods:
            client.add("PendingPod", p)
        (r0,) = client.schedule([pods[0]], drain=False)
        # The host binds the pick and the informer echoes the bound pod —
        # a confirmation, not a mutation.
        pods[0].spec.node_name = r0.node_name
        client.add("Pod", pods[0])
        (r1,) = client.schedule([pods[1]], drain=False)
        assert r1.node_name
        stats = client.dump()["speculation"]
        assert stats["invalidations"] == 0
        assert stats["hits"] >= 1
    finally:
        client.close()
        srv.close()


def test_node_heartbeat_preserves_cache():
    srv, client = _spec_server()
    try:
        client.add("Node", node("n0"))
        pods = [pod(f"p{i}") for i in range(4)]
        for p in pods:
            client.add("PendingPod", p)
        client.schedule([pods[0]], drain=False)
        client.add("Node", node("n0"))  # status-only re-delivery
        client.schedule([pods[1]], drain=False)
        stats = client.dump()["speculation"]
        assert stats["invalidations"] == 0
        assert stats["hits"] >= 1
    finally:
        client.close()
        srv.close()


def test_pod_delete_drops_undelivered_decision():
    srv, client = _spec_server()
    try:
        client.add("Node", node("n0"))
        pods = [pod(f"p{i}") for i in range(4)]
        for p in pods:
            client.add("PendingPod", p)
        client.schedule([pods[0]], drain=False)
        # p2 is deleted before the host ever asks about it.
        client.remove("Pod", pods[2].uid)
        for p in (pods[1], pods[3]):
            (r,) = client.schedule([p], drain=False)
            assert r.node_name
        dump = client.dump()
        assert pods[2].uid not in dump["pods"]
        assert dump["mirror_equal"]
    finally:
        client.close()
        srv.close()


def test_without_speculation_hints_are_dropped():
    path = tempfile.mktemp(suffix=".sock")
    srv = SidecarServer(path, scheduler=TPUScheduler(batch_size=8))
    srv.serve_background()
    client = SidecarClient(path)
    try:
        client.add("Node", node("n0"))
        p = pod("p0")
        client.add("PendingPod", p)  # no-op without the frontend
        (r,) = client.schedule([p], drain=False)
        assert r.node_name
        assert "speculation" not in client.dump()
    finally:
        client.close()
        srv.close()


def test_stale_hint_for_scheduled_pod_not_readmitted():
    """A pod that rode in via a plain informer add AND a hint must not be
    double-committed when its stale hint is admitted later (review
    finding: _admit_hints re-checks committed state at admit time)."""
    from kubernetes_tpu.scheduler import TPUScheduler
    from kubernetes_tpu.sidecar.speculate import SpeculativeFrontend

    s = TPUScheduler(batch_size=4)
    f = SpeculativeFrontend(s)
    s.add_node(node("n0"))
    p = pod("p0")
    f.add_hint(p)
    # The pod gets scheduled through the plain queue path meanwhile.
    s.add_pod(p)
    outs = s.schedule_all_pending()
    assert outs and outs[0].node_name
    assert p.uid in s.cache.pods
    # Admitting the stale hint must drop it, not requeue the bound pod.
    f._admit_hints(10)
    assert len(s.queue) == 0
    assert not f.hints


def test_uid_fallback_matches_dataclass_default():
    """Raw pod JSON without metadata.namespace must key the cache under the
    same uid t.Pod.uid computes ('default/<name>'), or hits become
    permanent misses and outcomes are lost."""
    import json

    from kubernetes_tpu.scheduler import TPUScheduler
    from kubernetes_tpu.sidecar.speculate import SpeculativeFrontend

    s = TPUScheduler(batch_size=4)
    f = SpeculativeFrontend(s)
    s.add_node(node("n0"))
    raw = json.dumps(
        {"metadata": {"name": "bare"}, "spec": {"requests": {"cpu": "1"}}}
    ).encode()
    f.add_hint_raw(raw)
    (r,) = f.schedule_raw([raw])
    assert r.node_name
    assert r.pod.uid == "default/bare"


def test_spec_change_invalidates_cached_decision():
    srv, client = _spec_server()
    try:
        client.add("Node", node("n0", cpu="8"))
        pods = [pod(f"p{i}", cpu="1") for i in range(4)]
        for p in pods:
            client.add("PendingPod", p)
        client.schedule([pods[0]], drain=False)  # batch commits all 4
        # p2's resources change while its decision is still cached.
        bigger = pod("p2", cpu="2")
        client.add("Pod", bigger)
        (r,) = client.schedule([bigger], drain=False)
        assert r.node_name
        stats = client.dump()["speculation"]
        assert stats["invalidations"] >= 1
        dump = client.dump()
        assert dump["mirror_equal"]
        assert len(dump["pods"]) == 4
    finally:
        client.close()
        srv.close()


def test_invalidation_rollback_refilters_not_rebinds():
    """Rolled-back pods must re-enter as UNASSIGNED (review finding: the
    commit stamps spec.node_name on the cached pod; a stale stamp would
    re-bind without filtering and double-commit resources)."""
    srv, client = _spec_server(batch_size=4)
    try:
        client.add("Node", node("n0", cpu="4"))
        pods = [pod(f"p{i}", cpu="1") for i in range(3)]
        for p in pods:
            client.add("PendingPod", p)
        (r0,) = client.schedule([pods[0]], drain=False)  # commits all 3
        assert r0.node_name == "node-n0" or r0.node_name  # placed
        # Any non-Pod mutation invalidates (PDB here); undelivered p1/p2
        # roll back and must re-filter on the recompute.
        from kubernetes_tpu.api import types as t

        client.add(
            "PodDisruptionBudget",
            t.PodDisruptionBudget(name="pdb"),
        )
        for p in pods[1:]:
            (r,) = client.schedule([p], drain=False)
            assert r.node_name
        # No double-commit: a 4th 1-cpu pod still fits the 4-cpu node.
        p3 = pod("p3", cpu="1")
        (r3,) = client.schedule([p3], drain=False)
        assert r3.node_name
        dump = client.dump()
        assert len(dump["pods"]) == 4
        assert dump["mirror_equal"]
    finally:
        client.close()
        srv.close()


def test_unassigned_relist_of_cached_pod_is_noop():
    """An identical unassigned re-delivery (watch relist) of a pod with a
    committed decision must not invalidate (the comparison ignores the
    node_name the commit stamped on the sidecar's copy)."""
    srv, client = _spec_server()
    try:
        client.add("Node", node("n0"))
        pods = [pod(f"p{i}") for i in range(4)]
        for p in pods:
            client.add("PendingPod", p)
        client.schedule([pods[0]], drain=False)  # commits all 4
        client.add("Pod", pod("p2"))  # relist: identical, unassigned
        client.schedule([pods[1]], drain=False)
        stats = client.dump()["speculation"]
        assert stats["invalidations"] == 0
        assert stats["hits"] >= 1
    finally:
        client.close()
        srv.close()


def test_delete_of_plain_hint_keeps_cache():
    """Deleting a pod known only as a hint must not discard the decision
    cache (review finding: note_remove over-invalidation)."""
    srv, client = _spec_server(batch_size=4, lookahead=3)
    try:
        client.add("Node", node("n0"))
        pods = [pod(f"p{i}") for i in range(6)]
        for p in pods:
            client.add("PendingPod", p)
        client.schedule([pods[0]], drain=False)  # admits 4, two hints left
        client.remove("Pod", pods[5].uid)  # still a pure hint
        client.schedule([pods[1]], drain=False)
        stats = client.dump()["speculation"]
        assert stats["invalidations"] == 0
        assert stats["hits"] >= 1
    finally:
        client.close()
        srv.close()


def test_unparsed_blob_duplicate_of_inflight_pod_does_not_strand_queue():
    """Soak-found regression (PR 6): a pod arriving BOTH via the queue
    (informer add / direct Schedule) and in a still-unparsed PendingPods
    blob must not be re-admitted to the active queue by the mid-batch
    incremental parse (post_dispatch_hook) while its batch is in flight.
    With a deep backlog the prefetch pop does not re-absorb the re-added
    (newest-timestamp) entry, so the commit's queue.done() strands a
    stale active uid and the NEXT pop_batch KeyErrors into the
    poison-batch machinery (the KeyError('default/lg-2650') engine
    fault the first r06 soak recorded)."""
    from kubernetes_tpu.api import serialize
    from kubernetes_tpu.sidecar.speculate import SpeculativeFrontend

    sched = TPUScheduler(batch_size=8)
    front = SpeculativeFrontend(sched)
    sched.add_node(node("n0", cpu="64"))
    x = pod("x")
    # x is queued FIRST (oldest timestamp — it leads the next batch),
    # then a backlog deep enough that the prefetch pop fills without
    # ever reaching a re-added x.
    sched.add_pod(x)
    for i in range(16):
        sched.add_pod(pod(f"f{i}"))
    # The duplicate of x rides a SECOND coalesced blob: the first blob
    # satisfies the pre-dispatch admission budget (lookahead = 7), so
    # the incremental parse only reaches x's blob mid-flight, inside
    # the post-dispatch hook.
    front.add_hint_blob(
        b"[" + b",".join(
            serialize.to_json(pod(f"h{i}")) for i in range(7)
        ) + b"]"
    )
    front.add_hint_blob(b"[" + serialize.to_json(pod("x")) + b"]")
    out = front._serve_one(x.uid, lambda: pod("x"))
    assert out.node_name
    # The queue invariant holds: every active uid still has its info
    # record; x is not stranded; nothing was quarantined by a recovery
    # bisect.
    assert set(sched.queue._in_active) <= set(sched.queue._info)
    assert x.uid not in sched.queue._in_active
    assert sched.queue.quarantined() == []
    # The whole backlog drains cleanly (pre-fix: KeyError -> engine
    # fault -> bisect -> quarantine).
    sched.schedule_all_pending()
    faults = sched.metrics.registry.counter(
        "scheduler_engine_faults_total"
    )
    assert faults.total() == 0
    bound = {
        uid for uid, pr in sched.cache.pods.items() if pr.bound
    }
    assert {f"default/f{i}" for i in range(16)} | {x.uid} <= bound
