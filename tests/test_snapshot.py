"""Snapshot builder: interning, row round-trips, incremental flush, growth."""

import numpy as np

from kubernetes_tpu.api import types as t
from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.cache import Cache
from kubernetes_tpu.intern import InternTable
from kubernetes_tpu.snapshot import INT_SENTINEL, Schema, SnapshotBuilder


def test_schema_growth_buckets():
    s = Schema()
    g = s.grown(N=100)
    assert g.N == 128
    assert g.R == s.R
    assert s.grown(N=10) is s  # no-grow returns the same schema object


def test_node_row_roundtrip():
    b = SnapshotBuilder()
    node = (
        make_node("n1")
        .capacity({"cpu": "4", "memory": "8Gi", "pods": 16})
        .label("zone", "a")
        .label("size", "64")
        .taint("dedicated", "gpu", t.EFFECT_NO_SCHEDULE)
        .obj()
    )
    b.set_node_row(0, node)
    h = b.host
    assert h["valid"][0]
    assert h["allowed_pods"][0] == 16
    assert h["alloc"][0, 0] == 4000
    assert h["alloc"][0, 1] == 8 * 1024**3
    # Labels interned (hostname + zone + size).
    assert (h["label_key_ids"][0] >= 0).sum() == 3
    # "size"=64 parses as int for Gt/Lt; "a" does not.
    vals = h["label_int_vals"][0]
    assert 64 in vals
    assert (vals == INT_SENTINEL).sum() >= 1
    assert (h["taint_ids"][0] >= 0).sum() == 1


def test_scalar_resource_grows_columns():
    b = SnapshotBuilder()
    node = make_node("n1").capacity({"cpu": "1", "nvidia.com/gpu": 8}).obj()
    b.set_node_row(0, node)
    col = b.res_col["nvidia.com/gpu"]
    assert col == 3
    assert b.host["alloc"][0, col] == 8


def test_incremental_flush_only_dirty_rows():
    b = SnapshotBuilder()
    for i in range(4):
        b.set_node_row(i, make_node(f"n{i}").capacity({"cpu": "1"}).obj())
    st = b.state()  # full build
    assert np.asarray(st.valid)[:4].all()
    # Dirty one row, flush: device must pick it up via row scatter.
    b.set_node_row(2, make_node("n2b").capacity({"cpu": "7"}).obj())
    st2 = b.state()
    assert np.asarray(st2.alloc)[2, 0] == 7000
    assert np.asarray(st2.alloc)[1, 0] == 1000


def test_pod_delta_apply_and_reverse():
    b = SnapshotBuilder()
    b.set_node_row(0, make_node("n").capacity({"cpu": "4", "memory": "8Gi"}).obj())
    pod = make_pod("p").req({"cpu": "1", "memory": "1Gi"}).label("app", "x").obj()
    d = b.pod_delta_vectors(pod)
    b.apply_pod_delta(0, d, +1, device_already=False)
    assert b.host["req"][0, 0] == 1000
    assert b.host["num_pods"][0] == 1
    assert b.host["group_counts"][d["group"], 0] == 1
    b.apply_pod_delta(0, d, -1, device_already=False)
    assert b.host["req"][0, 0] == 0
    assert b.host["num_pods"][0] == 0


def test_cache_assume_forget():
    b = SnapshotBuilder()
    c = Cache(b)
    c.add_node(make_node("n1").capacity({"cpu": "4", "memory": "8Gi"}).obj())
    pod = make_pod("p1").req({"cpu": "2"}).obj()
    c.assume_pod(pod, "n1", device_already=False)
    assert b.host["req"][0, 0] == 2000
    c.forget_pod(pod.uid)
    assert b.host["req"][0, 0] == 0
    assert pod.uid not in c.pods


def test_cache_node_remove_frees_row():
    b = SnapshotBuilder()
    c = Cache(b)
    c.add_node(make_node("n1").capacity({"cpu": "4"}).obj())
    c.add_node(make_node("n2").capacity({"cpu": "4"}).obj())
    c.remove_node("n1")
    assert not b.host["valid"][0]
    c.add_node(make_node("n3").capacity({"cpu": "2"}).obj())
    assert c.row_of("n3") == 0  # reuses the freed row
    assert b.host["alloc"][0, 0] == 2000


def test_node_capacity_growth_preserves_rows():
    b = SnapshotBuilder()
    for i in range(100):  # force N growth past the default 64
        b.set_node_row(i, make_node(f"n{i}").capacity({"cpu": str(i + 1)}).obj())
    assert b.schema.N == 128
    assert b.host["alloc"][99, 0] == 100_000
    assert b.host["alloc"][0, 0] == 1000


def test_interning_stable():
    it = InternTable()
    a = it.label_pairs.id(("zone", "a"))
    b_ = it.label_pairs.id(("zone", "b"))
    assert it.label_pairs.id(("zone", "a")) == a
    assert a != b_
    g1 = it.group_id("default", {"app": "web"})
    g2 = it.group_id("default", {"app": "web"})
    g3 = it.group_id("other", {"app": "web"})
    assert g1 == g2 != g3
