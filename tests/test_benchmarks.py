"""Benchmark harness smoke tests (tiny shapes; CPU)."""

from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.benchmarks.harness import WORKLOADS, Workload, run_workload
from kubernetes_tpu.framework.config import fit_only_profile
from kubernetes_tpu.scheduler import TPUScheduler


def test_workload_registry_covers_baseline_configs():
    names = set(WORKLOADS)
    # The five BASELINE.json A/B configs all have harness entries.
    assert "basic_500n_1kpods_fitonly" in names  # config 1
    assert "spread_nodeaffinity_1kn_5kpods" in names  # config 2
    assert "interpodaffinity_1kn_10kpods" in names  # config 3
    assert "density_5kn_30kpods_default" in names  # config 4
    assert "gang_15kpods_batch" in names  # config 5


def test_run_workload_smoke():
    w = Workload(
        name="tiny",
        baseline_pods_per_sec=10.0,
        build=lambda: TPUScheduler(profile=fit_only_profile(), batch_size=32),
        nodes=lambda s: [
            s.add_node(make_node(f"n{i}").capacity({"cpu": "8", "memory": "16Gi", "pods": 110}).obj())
            for i in range(8)
        ],
        warmup=lambda s: [
            s.add_pod(make_pod(f"w{i}").req({"cpu": "100m"}).obj()) for i in range(4)
        ],
        measured=lambda s: [
            s.add_pod(make_pod(f"m{i}").req({"cpu": "100m"}).obj()) for i in range(16)
        ]
        and 16,
    )
    r = run_workload(w)
    assert r["scheduled"] == 16
    assert r["expected"] == 16
    assert r["pods_per_sec"] > 0
    assert set(r["throughput"]) == {"avg", "p50", "p90", "p99"}
    assert r["vs_baseline"] is not None
