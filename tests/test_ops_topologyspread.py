"""PodTopologySpread vectorized op vs scalar reference semantics."""

import numpy as np

from kubernetes_tpu.api import types as t
from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.framework.config import Profile
from kubernetes_tpu.scheduler import TPUScheduler

from reference_impl import spread_filter, spread_score


def tps_profile(with_score=True):
    return Profile(
        name="tps",
        filters=("NodeResourcesFit", "PodTopologySpread"),
        scorers=(("PodTopologySpread", 2),) if with_score else (),
    )


def cluster(s, n_per_zone=2, zones=("a", "b", "c")):
    for z in zones:
        for i in range(n_per_zone):
            s.add_node(
                make_node(f"n-{z}{i}")
                .capacity({"cpu": "64", "pods": 110})
                .zone(z)
                .obj()
            )


def spread_pod(name, max_skew=1, when=t.DO_NOT_SCHEDULE, topo="topology.kubernetes.io/zone", **kw):
    return (
        make_pod(name)
        .req({"cpu": "100m"})
        .label("app", "web")
        .spread_constraint(max_skew, topo, when, "app", ["web"], **kw)
        .obj()
    )


def test_hard_zone_spread_balances():
    s = TPUScheduler(profile=tps_profile(False), batch_size=16)
    cluster(s)
    for i in range(6):
        s.add_pod(spread_pod(f"p{i}"))
    out = s.schedule_all_pending()
    zones = {}
    for o in out:
        assert o.node_name is not None
        z = o.node_name.split("-")[1][0]
        zones[z] = zones.get(z, 0) + 1
    assert zones == {"a": 2, "b": 2, "c": 2}


def test_hard_spread_blocks_over_skew():
    s = TPUScheduler(profile=tps_profile(False), batch_size=16)
    # One zone only has capacity → after maxSkew pods the rest are blocked.
    s.add_node(make_node("n-a0").capacity({"cpu": "64", "pods": 110}).zone("a").obj())
    s.add_node(make_node("n-b0").capacity({"cpu": "64", "pods": 110}).zone("b").unschedulable().obj())
    prof = Profile(
        name="tps-u",
        filters=("NodeUnschedulable", "NodeResourcesFit", "PodTopologySpread"),
        scorers=(),
    )
    s2 = TPUScheduler(profile=prof, batch_size=16)
    s2.add_node(make_node("n-a0").capacity({"cpu": "64", "pods": 110}).zone("a").obj())
    s2.add_node(make_node("n-b0").capacity({"cpu": "64", "pods": 110}).zone("b").unschedulable().obj())
    for i in range(3):
        s2.add_pod(spread_pod(f"p{i}"))
    out = {o.pod.name: o.node_name for o in s2.schedule_all_pending()}
    # Zone b exists as a domain (node b0 is eligible for counting — it is not
    # excluded by affinity/taint policies) with 0 pods, so zone a can take
    # maxSkew (1) pod before skew would exceed.
    assert out["p0"] == "n-a0"
    assert out["p1"] is None and out["p2"] is None


def test_min_domains_zeroes_global_min():
    s = TPUScheduler(profile=tps_profile(False), batch_size=16)
    cluster(s, n_per_zone=1, zones=("a", "b"))
    # minDomains=3 but only 2 domains → min treated as 0 → skew = count+1.
    p = (
        make_pod("p0")
        .req({"cpu": "100m"})
        .label("app", "web")
        .spread_constraint(1, "topology.kubernetes.io/zone", t.DO_NOT_SCHEDULE, "app", ["web"], min_domains=3)
        .obj()
    )
    s.add_pod(p)
    out = s.schedule_all_pending()
    assert out[0].node_name is not None  # 0 existing pods: skew 1 ≤ 1 OK

    p2 = (
        make_pod("p1")
        .req({"cpu": "100m"})
        .label("app", "web")
        .spread_constraint(1, "topology.kubernetes.io/zone", t.DO_NOT_SCHEDULE, "app", ["web"], min_domains=3)
        .obj()
    )
    s.add_pod(p2)
    out2 = s.schedule_all_pending()
    # One zone now has 1 pod; with min forced to 0, that zone is blocked
    # (skew 2 > 1) but the empty zone still admits (skew 1 ≤ 1).
    assert out2[0].node_name is not None
    placed_zone = out2[0].node_name
    assert placed_zone != out[0].node_name


def test_soft_spread_prefers_emptier_zone():
    s = TPUScheduler(profile=tps_profile(True), batch_size=16)
    cluster(s, n_per_zone=1, zones=("a", "b"))
    # Preload zone a with one matching pod.
    s.add_pod(make_pod("existing").req({"cpu": "100m"}).label("app", "web").node("n-a0").obj())
    s.add_pod(spread_pod("p0", when=t.SCHEDULE_ANYWAY))
    out = s.schedule_all_pending()
    assert out[0].node_name == "n-b0"


def test_hostname_soft_spread():
    s = TPUScheduler(profile=tps_profile(True), batch_size=16)
    for i in range(3):
        s.add_node(make_node(f"n{i}").capacity({"cpu": "64", "pods": 110}).obj())
    s.add_pod(make_pod("e1").req({"cpu": "100m"}).label("app", "web").node("n0").obj())
    s.add_pod(make_pod("e2").req({"cpu": "100m"}).label("app", "web").node("n0").obj())
    s.add_pod(make_pod("e3").req({"cpu": "100m"}).label("app", "web").node("n1").obj())
    s.add_pod(spread_pod("p0", when=t.SCHEDULE_ANYWAY, topo="kubernetes.io/hostname"))
    out = s.schedule_all_pending()
    assert out[0].node_name == "n2"


def test_node_missing_topo_key_is_infeasible_for_hard():
    s = TPUScheduler(profile=tps_profile(False), batch_size=16)
    s.add_node(make_node("zoned").capacity({"cpu": "64", "pods": 110}).zone("a").obj())
    s.add_node(make_node("bare").capacity({"cpu": "64", "pods": 110}).obj())
    s.add_pod(spread_pod("p0"))
    out = s.schedule_all_pending()
    assert out[0].node_name == "zoned"
    assert out[0].feasible_nodes == 1


def test_matches_reference_randomized():
    rng = np.random.default_rng(17)
    zones = ["za", "zb", "zc"]
    nodes = []
    for i in range(18):
        w = make_node(f"n{i}").capacity({"cpu": "640", "pods": 200})
        if rng.integers(0, 5):  # some nodes lack the zone label
            w = w.zone(zones[int(rng.integers(0, 3))])
        nodes.append(w.obj())

    apps = ["web", "db", "cache"]
    pods = []
    for i in range(50):
        app = apps[int(rng.integers(0, 3))]
        w = make_pod(f"p{i}").req({"cpu": "100m"}).label("app", app)
        r = int(rng.integers(0, 4))
        if r == 0:
            w = w.spread_constraint(
                int(rng.integers(1, 3)), "topology.kubernetes.io/zone",
                t.DO_NOT_SCHEDULE, "app", [app],
            )
        elif r == 1:
            w = w.spread_constraint(
                int(rng.integers(1, 3)), "topology.kubernetes.io/zone",
                t.SCHEDULE_ANYWAY, "app", [app],
            )
        elif r == 2:
            w = w.spread_constraint(
                1, "kubernetes.io/hostname", t.SCHEDULE_ANYWAY, "app", [app]
            )
        pods.append(w.obj())

    s = TPUScheduler(profile=tps_profile(True), batch_size=64)
    for n in nodes:
        s.add_node(n)
    for p in pods:
        s.add_pod(p)
    out = {o.pod.name: o for o in s.schedule_all_pending()}

    # Replay sequentially with the oracle, honoring device picks.
    pods_on: dict[str, list] = {n.name: [] for n in nodes}
    for p in pods:
        o = out[p.name]
        feas = spread_filter(p, nodes, pods_on)
        n_feas = sum(feas.values())
        assert o.feasible_nodes == n_feas, (p.name, o.feasible_nodes, n_feas)
        if o.node_name is None:
            assert n_feas == 0, p.name
            continue
        assert feas[o.node_name], (p.name, o.node_name)
        scores = spread_score(p, nodes, pods_on, feas)
        best = max(s_ for name, s_ in scores.items() if feas[name])
        assert scores[o.node_name] == best, (p.name, o.node_name, scores)
        pods_on[o.node_name].append(p)
