"""Measured throughput matrices (ISSUE 16 tentpole a): flight records
fold into per-(workload class, accelerator class) milli-throughput
artifacts — deterministically (2× same-seed runs derive byte-identical
JSON), loadable wherever the synthetic matrix is accepted, and inert
under the A/B oracle (a measured profile binds bit-identically in an
N=2 fleet, exactly like the synthetic one)."""

import json
import os

import pytest

from kubernetes_tpu.framework import measured
from kubernetes_tpu.ops.throughput import (
    load_matrix,
    throughput_aware_profile,
)
from kubernetes_tpu.scheduler import TPUScheduler

from test_heterogeneity import (
    hetero_scenario,
    run_fleet_hetero,
    run_single_hetero,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
COMMITTED = os.path.join(REPO, "measured_matrix.json")


def hetero_flight_snapshot():
    """One hetero golden-scenario run's flight snapshot — the deriver's
    input (per-batch ``hetero`` bind counts ride every batch record)."""
    sched = TPUScheduler(
        profile=throughput_aware_profile(), batch_size=8, chunk_size=1
    )
    nodes, pods = hetero_scenario()
    for n in nodes:
        sched.add_node(n)
    for p in pods:
        sched.add_pod(p)
    sched.schedule_all_pending(wait_backoff=True)
    return sched.flight.snapshot()


def render(doc: dict) -> str:
    return json.dumps(doc, indent=1, sort_keys=True) + "\n"


# -- derivation --------------------------------------------------------------


def test_batches_carry_hetero_bind_counts():
    snap = hetero_flight_snapshot()
    hetero = [
        r["hetero"]
        for r in snap["records"]
        if r.get("kind") == "batch" and r.get("hetero")
    ]
    assert hetero, "hetero scenario batches must stamp hetero bind counts"
    assert all(
        "|" in key and n > 0 for h in hetero for key, n in h.items()
    )


def test_derive_builds_a_row_normalized_matrix():
    doc = measured.derive(hetero_flight_snapshot())
    measured.validate(doc)
    assert doc["version"] == measured.MEASURED_VERSION
    assert doc["kind"] == measured.MEASURED_KIND
    # Integer row-max normalization: the best accel per class is exactly
    # the scale, every cell is a non-negative int.
    for row in doc["matrix"].values():
        assert max(row.values()) == doc["scale"]
        assert all(isinstance(v, int) and v >= 0 for v in row.values())
    assert doc["window"]["binds"] > 0


def test_two_same_seed_derivations_are_byte_identical():
    """The determinism acceptance leg: derive → serialize twice from two
    fresh same-seed runs — byte-identical artifacts."""
    a = render(measured.derive(hetero_flight_snapshot()))
    b = render(measured.derive(hetero_flight_snapshot()))
    assert a == b


def test_save_load_round_trip(tmp_path):
    doc = measured.derive(hetero_flight_snapshot())
    path = tmp_path / "mm.json"
    measured.save(doc, str(path))
    assert measured.load(str(path)) == doc


def test_logical_window_restricts_the_fold():
    snap = hetero_flight_snapshot()
    full = measured.derive(snap)
    clipped = measured.fold([snap], lc_lo=None, lc_hi=-1.0)
    assert clipped[0] == {}  # nothing sits below the window
    assert full["window"]["binds"] > 0


def test_validate_rejects_malformed_artifacts():
    good = measured.derive(hetero_flight_snapshot())
    for mutate in (
        lambda d: d.update(version=99),
        lambda d: d.update(kind="nope"),
        lambda d: d.update(matrix={}),
        lambda d: d["matrix"].update(batch={"gpu-a100": float("nan")}),
        lambda d: d["matrix"].update(batch={"gpu-a100": -5}),
        lambda d: d["matrix"].update(batch={"gpu-a100": 0}),
    ):
        doc = json.loads(json.dumps(good))
        mutate(doc)
        with pytest.raises(ValueError):
            measured.validate(doc)


# -- the committed artifact --------------------------------------------------


def test_committed_artifact_matches_a_fresh_derivation():
    """measured_matrix.json IS a golden: the committed bytes must equal
    what the hetero golden scenario derives today — a silent behavior
    drift in the bind path shows up here as a stale artifact."""
    with open(COMMITTED, "r", encoding="utf-8") as f:
        committed = f.read()
    assert committed == render(measured.derive(hetero_flight_snapshot()))


def test_loader_accepts_the_committed_artifact():
    rows = load_matrix(COMMITTED)
    assert rows
    for wclass, accel_rows in rows:
        assert isinstance(wclass, str) and accel_rows
        assert all(
            isinstance(a, str) and isinstance(m, int)
            for a, m in accel_rows
        )
    # matrix_rows is the same tuple form the synthetic profile takes.
    assert rows == measured.matrix_rows(measured.load(COMMITTED))


# -- the A/B oracle: measured vs synthetic, single vs N=2 fleet --------------


def test_measured_profile_binds_bit_identical_under_fleet_oracle():
    """The acceptance leg: a profile built FROM the measured artifact
    stays bit-identical between the single scheduler and an N=2 fleet —
    the measured matrix rides the same static row-max normalizer, so
    partitioning cannot perturb a score bit.  The synthetic profile's
    own leg (test_heterogeneity) keeps holding alongside."""
    doc = measured.load(COMMITTED)
    profile = throughput_aware_profile(matrix=measured.matrix_rows(doc))
    single = run_single_hetero(profile)
    assert single
    assert run_fleet_hetero(profile, 2) == single


# -- the gauge + scheduler arming -------------------------------------------


def test_note_measured_matrix_publishes_the_gauge_family():
    doc = measured.load(COMMITTED)
    sched = TPUScheduler(batch_size=8)
    sched.note_measured_matrix(doc)
    text = sched.metrics.registry.render_text()
    assert "scheduler_measured_throughput_millis" in text
    for wclass, row in doc["matrix"].items():
        for accel, milli in row.items():
            needle = (
                "scheduler_measured_throughput_millis{"
                f'accel="{accel}",workload_class="{wclass}"}} {milli}'
            )
            assert needle in text, needle
