"""Multi-profile scheduling (profile/profile.go:47) and the extender chain
(pkg/scheduler/extender.go; wire types extender/v1/types.go:73–124)."""

from kubernetes_tpu.api import types as t
from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.extender import ExtenderFilterResult, HostPriority
from kubernetes_tpu.framework.config import Profile, fit_only_profile
from kubernetes_tpu.scheduler import TPUScheduler


def nodes(s, n=4, cpu="8"):
    for i in range(n):
        s.add_node(
            make_node(f"n{i}").capacity({"cpu": cpu, "memory": "16Gi", "pods": 110})
            .label("tier", "gold" if i % 2 else "bronze").obj()
        )


class FakeExtender:
    """In-process fake implementing the Extender surface (the shape of
    testing/fake_extender.go)."""

    name = "fake"
    weight = 1
    ignorable = False
    bind_verb = ""

    def __init__(self, allow=None, scores=None):
        self.allow = allow  # set of node names, or None = all
        self.scores = scores or {}
        self.filter_calls = 0
        self.prioritize_calls = 0

    def is_interested(self, pod):
        return True

    def filter(self, pod, nodes):
        self.filter_calls += 1
        keep = [n for n in nodes if self.allow is None or n in self.allow]
        return ExtenderFilterResult(node_names=keep)

    def prioritize(self, pod, nodes):
        self.prioritize_calls += 1
        return [HostPriority(n, self.scores.get(n, 0)) for n in nodes]

    def bind(self, pod, node):
        return True


def test_extender_filters_and_scores():
    ex = FakeExtender(allow={"n1", "n3"}, scores={"n3": 10})
    s = TPUScheduler(profile=fit_only_profile(), batch_size=4, extenders=[ex])
    nodes(s)
    s.add_pod(make_pod("p").req({"cpu": "1"}).obj())
    out = s.schedule_all_pending()
    # n3 wins: it survives the filter and gets +10×weight extender score.
    assert out[0].node_name == "n3"
    assert ex.filter_calls == 1 and ex.prioritize_calls == 1
    assert s.builder.host_mirror_equal()


def test_extender_rejection_requeues():
    ex = FakeExtender(allow=set())  # rejects everything
    s = TPUScheduler(profile=fit_only_profile(), batch_size=4, extenders=[ex])
    nodes(s)
    s.add_pod(make_pod("p").req({"cpu": "1"}).obj())
    out = s.schedule_all_pending()
    assert out[0].node_name is None
    assert out[0].diagnosis.unschedulable_plugins == {"Extender"}
    # Any event wakes extender-rejected pods (schedule_one.go:528).
    ex.allow = None
    s.add_node(make_node("n9").capacity({"cpu": "8", "pods": 110}).obj())
    out2 = s.schedule_all_pending(wait_backoff=True)
    assert [o.node_name for o in out2 if o.node_name]


def test_two_profiles_compile_distinct_programs():
    """Two schedulerNames → two compiled program variants; pods route by
    .spec.scheduler_name; unknown names are not our pods."""
    strict = Profile(
        name="gold-only",
        filters=("NodeUnschedulable", "NodeName", "NodeAffinity", "NodeResourcesFit"),
        scorers=(("NodeResourcesFit", 1),),
    )
    s = TPUScheduler(
        profile=fit_only_profile(), batch_size=8, profiles=[strict]
    )
    nodes(s)
    s.add_pod(make_pod("default-pod").req({"cpu": "1"}).scheduler("fit-only").obj())
    s.add_pod(
        make_pod("gold-pod").req({"cpu": "1"}).scheduler("gold-only")
        .node_affinity_in("tier", ["gold"]).obj()
    )
    s.add_pod(make_pod("alien").req({"cpu": "1"}).scheduler("other-scheduler").obj())
    out = {o.pod.name: o.node_name for o in s.schedule_all_pending()}
    assert out["default-pod"] is not None
    assert out["gold-pod"] in ("n1", "n3")  # gold tier only
    assert "alien" not in out  # ignored: not responsible for it
    assert s.queue.pending_count() == 0
    assert s.builder.host_mirror_equal()


def test_extender_profile_runs_preemption():
    """PostFilter through the extender path (schedule_one.go:749): an
    unschedulable pod preempts, with preemption-capable extenders vetoing
    or accepting the chosen candidate (ProcessPreemption)."""

    class PreemptingExtender(FakeExtender):
        supports_preemption = True

        def __init__(self, veto=False, **kw):
            super().__init__(**kw)
            self.veto = veto
            self.preempt_calls = 0

        def process_preemption(self, pod, node_to_victims):
            self.preempt_calls += 1
            if self.veto:
                return {}
            return {
                node: [v.uid for v in victims]
                for node, victims in node_to_victims.items()
            }

    def build(ex):
        s = TPUScheduler(batch_size=4, extenders=[ex])
        s.add_node(
            make_node("n0").capacity({"cpu": "4", "memory": "16Gi", "pods": 10}).obj()
        )
        s.add_pod(make_pod("low").req({"cpu": "4"}).priority(1).obj())
        assert [o.node_name for o in s.schedule_all_pending()] == ["n0"]
        s.add_pod(make_pod("high").req({"cpu": "4"}).priority(100).obj())
        return s

    # Accepting extender: the high-priority pod evicts `low` and retries
    # onto its nominated node.
    ex = PreemptingExtender()
    s = build(ex)
    out = s.schedule_all_pending(wait_backoff=True)
    by_name = {o.pod.name: o for o in out}
    assert ex.preempt_calls == 1
    assert any(
        o.pod.name == "high" and o.node_name == "n0" for o in out
    ), by_name
    assert "default/low" not in s.cache.pods
    assert s.builder.host_mirror_equal()

    # Vetoing extender: preemption abandoned, the pod parks unschedulable,
    # the victim survives.
    ex2 = PreemptingExtender(veto=True)
    s2 = build(ex2)
    out2 = s2.schedule_all_pending()
    assert ex2.preempt_calls == 1
    assert all(o.node_name is None for o in out2 if o.pod.name == "high")
    assert "default/low" in s2.cache.pods
    assert "default/high" in s2.queue._unschedulable
