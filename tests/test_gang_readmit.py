"""Regression tests for gang re-admission corner cases (code-review r3).

1. A gang member parked in the gang pool mid-batch (schema-grown deferral
   reactivated while its peer was merely "placed") must be re-admitted when
   the peer enters the WaitOnPermit room — waiter credit growth re-attempts
   admission; nothing else fires in a quiet cluster.
2. Deleting a pod that sits in the PREFETCHED batch must untrack its gang
   membership, or the ghost uid overcounts quorum and Permit waits forever
   on a member that no longer exists.
"""

from kubernetes_tpu.api import types as t
from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.scheduler import TPUScheduler


def big_node(name: str, cpu: str = "16"):
    return make_node(name).capacity({"cpu": cpu, "memory": "64Gi", "pods": 110}).obj()


def gang_pod(name: str, group: str) -> t.Pod:
    return make_pod(name).req({"cpu": "1"}).pod_group(group).obj()


def test_pool_member_readmitted_when_peer_enters_permit_room():
    s = TPUScheduler(batch_size=1)
    s.add_node(big_node("n1"))
    s.add_pod_group(t.PodGroup(name="g1", min_member=2))
    s.add_pod(gang_pod("m0", "g1"))
    s.add_pod(gang_pod("m1", "g1"))
    # Pull both members out of the queue, then hand-craft the bug's state:
    # m1 parked in the gang pool (as a schema-grown deferral would), m0 back
    # on the active queue alone.
    popped = {qp.pod.name: qp for qp in s.queue.pop_batch(2)}
    qp0, qp1 = popped["m0"], popped["m1"]
    s.queue._info[qp1.pod.uid] = qp1
    s.queue._park_gang_member(qp1)          # pool only — no admission attempt
    s.queue._info[qp0.pod.uid] = qp0
    s.queue._push_active(qp0)
    # Batch 1: m0 places, quorum unmet (m1 parked counts as pending) → m0
    # waits on Permit.  The waiter's credit must re-admit m1 from the pool.
    s.schedule_batch()
    assert len(s.permit_waiting.get("g1", ())) == 1
    assert "g1" not in s.queue._gang_pool  # m1 released to activeQ
    out = s.schedule_all_pending()
    assert sorted(o.pod.name for o in out if o.node_name) == ["m0", "m1"]
    assert s.gang_bound == {"g1": 2}
    assert s.builder.host_mirror_equal()


def test_deleting_prefetched_gang_member_untracks_quorum_credit():
    s = TPUScheduler(batch_size=1)
    s.add_node(big_node("n1"))
    s.add_pod_group(t.PodGroup(name="g2", min_member=2))
    s.add_pod(make_pod("x").req({"cpu": "1"}).obj())  # filler: batch 1
    s.add_pod(gang_pod("w0", "g2"))
    s.add_pod(gang_pod("w1", "g2"))
    # Batch 1 schedules the filler and prefetches the next batch (w0).
    s.schedule_batch()
    assert s._prefetched is not None
    pre_names = [qp.pod.name for qp in s._prefetched[0]]
    assert pre_names == ["w0"]
    # Delete the prefetched member: the prefetch dissolves; its gang
    # tracking must dissolve with it.
    s.delete_pod("default/w0")
    assert s.queue.gang_pending("g2") == 1  # w1 only, no ghost
    # w1 alone can never reach quorum: it must roll back (not sit assumed in
    # the WaitOnPermit room behind a ghost's credit).
    out = s.schedule_all_pending()
    assert all(o.node_name is None for o in out if o.pod.name == "w1")
    assert not s.permit_waiting
    assert not any(pr.assumed for pr in s.cache.pods.values())
    assert s.builder.host_mirror_equal()
