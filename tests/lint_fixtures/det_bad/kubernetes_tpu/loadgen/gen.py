"""Seeded loadgen determinism violations: a traffic generator whose
arrivals read wall clocks or ambient entropy cannot replay, so a
same-seed soak could never assert bit-identical bindings."""

import random
import time


def arrivals(rate, duration):
    # POSITIVE det-wallclock: arrival schedule anchored to the wall clock.
    t = time.time()
    out = []
    while t < duration:
        # POSITIVE det-random: bare-`random` inter-arrival gaps — the
        # schedule differs every run (numpy.random.Generator(seed) is
        # the allowed idiom).
        t += random.expovariate(rate)
        out.append(t)
    return out
