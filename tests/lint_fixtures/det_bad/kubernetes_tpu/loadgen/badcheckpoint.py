"""Seeded checkpoint-writer determinism violations: the checkpoint IS
the resumed run's replay oracle — a digest stamped with wall time, a
jittered cadence or an id()-keyed state map can never verify
bit-identity against the uninterrupted twin."""

import random
from datetime import datetime


def stamp_generation(generation):
    # POSITIVE det-wallclock: a wall-clock stamp inside digest-covered
    # state diverges every resume; timestamps belong in the obs half.
    return {"generation": generation, "at": datetime.now()}


def next_checkpoint_due(op_index, every):
    # POSITIVE det-random: a jittered cadence moves the checkpoint
    # boundary between runs — the kill matrix could never pin a cell
    # to "exactly at generation N".
    return op_index + every + int(random.random() * 4)


def state_key(op):
    # POSITIVE det-id-key: CPython addresses vary per process — a
    # resumed run could never find the interrupted run's entry.
    return id(op)
