"""Seeded determinism violations (tests/test_static_analysis.py)."""

import os
import random
import time


def featurize(pods):
    # POSITIVE det-wallclock: a decision input read from the wall clock.
    stamp = time.time()
    # POSITIVE det-random: entropy in a scoring kernel.
    jitter = random.random()
    # POSITIVE det-random: os.urandom.
    salt = os.urandom(4)
    out = []
    # POSITIVE det-set-iteration: hash-ordered iteration reaches the output.
    for name in {p.name for p in pods}:
        out.append(name)
    # POSITIVE det-set-iteration: materialized set order.
    order = list(set(out))
    # POSITIVE det-id-key: process-address identity as a key.
    keys = {id(p): p for p in pods}
    return stamp, jitter, salt, order, keys
