"""Seeded determinism violations in a heterogeneity score path (ISSUE
14): a throughput-matrix/weight loader and scorer that read clocks,
draw entropy, and bucket by salted hash — everything the A/B oracle
forbids (tests/test_static_analysis.py counts these)."""

import random
import time


def load_weights(path):
    with open(path) as f:
        rows = f.read().split()
    # POSITIVE det-random: jitter drawn into the loaded weights.
    return [float(r) + random.gauss(0.0, 0.01) for r in rows]


def score(pods, matrix):
    # POSITIVE det-wallclock: a decision input read from the wall clock.
    freshness = time.time()
    out = {}
    for pod in pods:
        # POSITIVE det-builtin-hash: salted hash() routes the matrix row.
        row = matrix[hash(pod.workload_class) % len(matrix)]
        out[pod.uid] = row[0] * freshness
    # POSITIVE det-set-iteration: hash-ordered accel classes reach the
    # output ranking.
    for accel in {r[1] for r in matrix}:
        out[accel] = 0
    return out
