"""Seeded fleet determinism violations: a router whose shard hashing
draws entropy or reads wall clocks routes the same pod differently every
run — the N-shard vs single-scheduler oracle could never hold."""

import random
import time


def route(pod_uid, n_shards):
    # POSITIVE det-random: entropy in the routing decision — crc32 over
    # the uid (shardmap.stable_shard_hash) is the deterministic idiom.
    return random.randrange(n_shards)


def tie_break(candidates):
    # POSITIVE det-wallclock: a wall-clock-seeded tie-break diverges from
    # the device kernel's counter-hash mirror run to run.
    seed = int(time.time())
    return candidates[seed % len(candidates)]


def lease_home(node_name, n_shards):
    # POSITIVE det-builtin-hash: builtin hash() is PYTHONHASHSEED-salted,
    # so two processes would route the same node's Lease frames to
    # DIFFERENT lifecycle controllers — crc32 (stable_shard_hash) is the
    # cross-process-stable idiom.
    return hash(node_name) % n_shards
