"""Seeded fleet determinism violations: a router whose shard hashing
draws entropy or reads wall clocks routes the same pod differently every
run — the N-shard vs single-scheduler oracle could never hold."""

import random
import time


def route(pod_uid, n_shards):
    # POSITIVE det-random: entropy in the routing decision — crc32 over
    # the uid (shardmap.stable_shard_hash) is the deterministic idiom.
    return random.randrange(n_shards)


def tie_break(candidates):
    # POSITIVE det-wallclock: a wall-clock-seeded tie-break diverges from
    # the device kernel's counter-hash mirror run to run.
    seed = int(time.time())
    return candidates[seed % len(candidates)]
