"""Seeded autoscaler determinism violations: a control loop that reads
wall clocks or iterates bare sets resizes the fleet differently every
run — same-seed soaks could never replay the split/merge history."""

import time


def should_split(last_action_ts, cooldown_s):
    # POSITIVE det-wallclock: cooldowns must run on the LOGICAL clock
    # the caller feeds, never a wall read.
    return time.time() - last_action_ts > cooldown_s


def pick_hot_shard(window_binds):
    # POSITIVE det-set-iteration: bare set iteration order is
    # hash-randomized — two processes would pick different "hottest"
    # shards on equal counts; sorted(...) is the idiom.
    for shard in {s for s in window_binds}:
        return shard
