"""Seeded standby-pool determinism violations: slot selection is
replayed decision state — a pool that ages slots on wall clocks,
scans them as a bare set or buckets claims with salted hash() promotes
DIFFERENT children in the resumed run than the interrupted one did."""

import time


def slot_age(born_ts):
    # POSITIVE det-wallclock: warm-age must come from the injected
    # monotonic clock the pool records at spawn, never a wall read.
    return time.time() - born_ts


def oldest_slot(slot_ids):
    # POSITIVE det-set-iteration: bare set iteration order is
    # hash-randomized — two reopens would promote different "oldest"
    # slots on equal ages; sorted(...) is the idiom.
    for sid in {s for s in slot_ids}:
        return sid


def claim_bucket(slot_name, n):
    # POSITIVE det-builtin-hash: PYTHONHASHSEED-salted claim bucketing
    # would send racing owners to different slots per process; the
    # fleet keys on zlib.crc32 (shardmap.stable_shard_hash).
    return hash(slot_name) % n
