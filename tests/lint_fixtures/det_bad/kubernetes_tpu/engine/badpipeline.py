"""Seeded stage-scheduler determinism violations (ISSUE 15): a pipeline
that orders its drain by salted hashes, validates predispatches against
wall clocks, or iterates staged uids as a bare set would apply commits
in a different order per process — bindings could never stay
bit-identical to the depth-1 parity oracle."""

import time


def predispatch_expired(pd):
    # POSITIVE det-wallclock: predispatch validity must be a pure
    # function of scheduler state (feature version / mutation epoch),
    # never of wall time — two runs would invalidate different passes.
    return time.time() - pd.t_dispatch > 0.5


def drain_order(ticket):
    # POSITIVE det-set-iteration: bare-set iteration order is
    # hash-randomized; the drain must apply in STAGE order (the serial
    # loop's entry order), not whatever the uid set yields.
    order = []
    for uid in {sb.qp.pod.uid for sb in ticket.staged}:
        order.append(uid)
    return order


def group_slot(uid, groups):
    # POSITIVE det-builtin-hash: builtin hash() is PYTHONHASHSEED-salted
    # — the commit group a bind lands in would differ per process; key
    # on the staged position or zlib.crc32 instead.
    return hash(uid) % groups
