"""Seeded chunk-packer determinism violations: a packer that iterates a
bare set or buckets by builtin hash() assigns pods to DIFFERENT chunk
slices in different processes — the packed scan's bindings could never
stay bit-identical to the chunk=1 parity oracle."""


def deal_classes(class_of):
    # POSITIVE det-set-iteration: bare-set iteration order is
    # hash-randomized — the chunk each class lands in would vary run to
    # run; sorted(...) over the ids is the idiom.
    order = []
    for cls in {c for c in class_of}:
        order.append(cls)
    return order


def slice_for(pod_uid, width):
    # POSITIVE det-builtin-hash: builtin hash() is PYTHONHASHSEED-salted;
    # chunk-slice assignment must key on stable ids (zlib.crc32 or the
    # pod's original batch position), never on salted string hashes.
    return hash(pod_uid) % width
