"""Seeded determinism violation in a trace exporter (ISSUE 16): a
"logical" timebase that quietly anchors on the wall clock — the export
can never be byte-identical across same-seed runs
(tests/test_static_analysis.py counts it)."""

import datetime


def emit_logical(records):
    # POSITIVE det-wallclock: the logical timeline's epoch read from the
    # wall clock — every export differs in every ts field.
    epoch = datetime.datetime.now()
    events = []
    for i, rec in enumerate(records):
        events.append({"ts": epoch.timestamp() + i, "name": rec.get("kind")})
    return events
