"""Seeded determinism violations in a weighted-fair admission policy
(ISSUE 17): a wall-clock credit refill, a random tie-break, a bare-set
tenant scan and a salted-hash overflow bucket — the four ways a
replayed admission order silently diverges from the interrupted run's
(tests/test_static_analysis.py counts these)."""

import random
import time


class BadAdmission:
    def __init__(self):
        self.credits = {}
        self.vfinish = {}

    def refill(self, tenant, rate):
        # POSITIVE det-wallclock: credits refilled off wall time — the
        # recovered ledger refills a different amount than the
        # interrupted run did, and the replayed admission order drifts.
        now = time.time()
        self.credits[tenant] = self.credits.get(tenant, 0.0) + rate * now
        return now

    def select(self, tenants):
        best = None
        # POSITIVE det-set-iteration: a hash-ordered tenant scan breaks
        # ties by whatever PYTHONHASHSEED dealt this process — sibling
        # shards disagree on the admission order.
        for tenant in set(tenants):
            key = self.vfinish.get(tenant, 0.0)
            if best is None or key < best[0]:
                best = (key, tenant)
            elif key == best[0] and random.random() < 0.5:
                # POSITIVE det-random: a coin-flip tie-break can never
                # replay — same-seed runs admit different tenants.
                best = (key, tenant)
        return best

    def overflow_bucket(self, tenant, buckets):
        # POSITIVE det-builtin-hash: the salted builtin hash() assigns a
        # different overflow bucket per process — the hashed metric tier
        # and the journaled admission state stop agreeing.
        return hash(tenant) % buckets
