"""Seeded determinism violations in a decision-provenance recorder
(ISSUE 20): a wall-clock capsule stamp, a coin-flip tie-break in the
selectHost reconstruction, a bare-set ring sweep and a salted-hash tie
rand — the four ways an explain record silently disagrees with the
decision it claims to explain (tests/test_static_analysis.py counts
these)."""

import random
import time


class BadProvenanceRing:
    def __init__(self, capacity=4096):
        self.capacity = capacity
        self.capsules = {}

    def record(self, uid, node, score):
        # POSITIVE det-wallclock: the capsule is stamped with wall time —
        # two explains of the same decision carry different stamps, and
        # the record diff flags a divergence that never happened.
        self.capsules[uid] = {
            "node": node,
            "score": score,
            "at": time.time(),
        }

    def sweep(self, keep):
        evicted = []
        # POSITIVE det-set-iteration: a hash-ordered sweep evicts
        # whichever capsules PYTHONHASHSEED dealt first — same-seed
        # runs disagree on which decisions remain explainable.
        for uid in set(self.capsules):
            if uid not in keep:
                evicted.append(uid)
        return evicted

    def reconstruct_pick(self, ties):
        # POSITIVE det-random: a coin-flip kth can never replay the
        # device's tie-break — explain picks a different node than the
        # committed binding on every other run.
        kth = 0
        if len(ties) > 1 and random.random() < 0.5:
            kth = 1
        return ties[kth]

    def tie_rand(self, uid, step):
        # POSITIVE det-builtin-hash: the salted builtin hash() produces
        # a different tie rand per process — the reconstructed argmax
        # trace and the journaled decision stop agreeing.
        return hash((uid, step)) & 0xFFFFFFFF
