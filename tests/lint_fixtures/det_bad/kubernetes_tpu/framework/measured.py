"""Seeded determinism violations in a measured-matrix deriver (ISSUE
16): a fold that windows on the WALL clock and iterates its rows in
hash order — the two ways a "measured" artifact silently stops being
byte-identical across same-seed runs
(tests/test_static_analysis.py counts these)."""

import time


def fold(records):
    # POSITIVE det-wallclock: the fold window anchored on wall time —
    # two same-seed runs derive different windows, different artifacts.
    lc_hi = time.time()
    cells = {}
    for rec in records:
        if rec.get("ts", 0) > lc_hi:
            continue
        for key, n in (rec.get("hetero") or {}).items():
            cells[key] = cells.get(key, 0) + n
    return cells


def matrix_rows(cells):
    rows = []
    # POSITIVE det-set-iteration: hash-ordered row iteration reaches the
    # serialized artifact (the row order IS the byte order).
    for key in {k for k in cells}:
        rows.append((key, cells[key]))
    return rows
