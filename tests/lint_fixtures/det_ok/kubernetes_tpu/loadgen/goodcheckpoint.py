"""Checkpoint-writer determinism negative fixture: logical stamps,
fixed cadence, stable keys (zero findings expected)."""


def stamp_generation(generation, virtual_clock):
    # Digest-covered state carries the LOGICAL clock the driver feeds.
    return {"generation": generation, "at": virtual_clock}


def next_checkpoint_due(op_index, every):
    return op_index + every


def state_key(op):
    return op["uid"]
