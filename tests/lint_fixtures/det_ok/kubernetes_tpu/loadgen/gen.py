"""Loadgen determinism negative fixture: the allowed idioms — a seeded
``numpy.random.Generator`` stream for arrivals, ``perf_counter`` for
pacing/latency measurement (never a decision input), injected clocks."""

import time

import numpy as np


def arrivals(rate, duration, seed, clock=time.perf_counter):
    rng = np.random.Generator(np.random.PCG64(seed))
    t0 = clock()  # pacing reference, not a schedule input
    out, t = [], 0.0
    for gap in rng.exponential(1.0 / rate, size=64):
        t += float(gap)
        if t >= duration:
            break
        out.append(t)
    return out, clock() - t0
