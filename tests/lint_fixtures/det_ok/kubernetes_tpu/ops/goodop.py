"""Determinism negative fixture: the allowed idioms (perf_counter for
latency, sorted() over sets, stable uid keys) produce zero findings."""

import time


def featurize(pods):
    t0 = time.perf_counter()  # latency metric, not a decision input
    names = {p.name for p in pods}
    ordered = sorted(names)  # sets sort before any order-sensitive use
    keys = {p.uid: p for p in pods}  # stable identity, not id()
    seen = set()
    for p in pods:  # iterating the ordered input, membership on the set
        if p.uid in seen:
            continue
        seen.add(p.uid)
    return time.perf_counter() - t0, ordered, keys
