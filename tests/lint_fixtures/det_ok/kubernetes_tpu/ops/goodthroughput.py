"""Determinism negative fixture for the heterogeneity score path: the
allowed idioms — perf_counter for latency only, sorted() over the
accel-class set, weights loaded verbatim from the committed artifact —
produce zero findings."""

import json
import time


def load_weights(path):
    with open(path) as f:
        doc = json.load(f)
    return tuple(tuple(float(x) for x in row) for row in doc["w1"])


def score(pods, matrix):
    t0 = time.perf_counter()  # latency metric, not a decision input
    by_class = {wclass: row for wclass, row in matrix}
    out = {}
    for pod in pods:  # input order, stable uid keys
        out[pod.uid] = by_class.get(pod.workload_class, ((), 0))
    for accel in sorted({r[1] for r in matrix}):  # sets sort before use
        out.setdefault(accel, 0)
    return time.perf_counter() - t0, out
