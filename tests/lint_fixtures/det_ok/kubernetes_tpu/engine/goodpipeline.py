"""Healthy stage-scheduler idioms: perf_counter for stage timing (never
a decision input), stage-order drains, stable-token validity checks."""

import time


def drain_timing(ticket):
    # perf_counter is allowed: it feeds the flight recorder's drain
    # segment, never a scheduling decision.
    t0 = time.perf_counter()
    order = [sb.qp.pod.uid for sb in ticket.staged]  # stage order
    return order, time.perf_counter() - t0


def predispatch_valid(pd, builder):
    # Validity as a pure function of scheduler state tokens.
    return (
        pd.version == builder.feature_version()
        and pd.mutation_epoch == builder.mutation_epoch
    )


def staged_report(ticket):
    # sorted(...) over a set is the deterministic-iteration idiom.
    return sorted({sb.node_name for sb in ticket.staged})
