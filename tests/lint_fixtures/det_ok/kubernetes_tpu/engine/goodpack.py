"""Healthy chunk-packer idioms: deterministic class order (sorted ids,
ties on first appearance) and slice assignment from stable positions."""


def deal_classes(class_of):
    # NEGATIVE: sorted iteration — class order is a pure function of ids.
    return sorted({c for c in class_of})


def slice_for(position, width):
    # NEGATIVE: the pod's original batch position is a stable identity.
    return position % width
