"""The allowed idiom for a trace exporter's logical timebase: ordinal
slots derived from record positions alone — no clock anywhere."""


def emit_logical(records):
    ordered = sorted(
        records, key=lambda r: (r.get("lc", r.get("seq", 0)), r.get("seq", 0))
    )
    return [
        {"ts": i * 1000, "name": rec.get("kind")}
        for i, rec in enumerate(ordered)
    ]
