"""The allowed idioms for a measured-matrix deriver: logical-clock
windowing from the RECORDS (never a wall read) and sorted row
iteration — byte-identical artifacts across same-seed runs."""


def fold(records, lc_lo=None, lc_hi=None):
    cells = {}
    for rec in records:
        pos = rec.get("lc", rec.get("seq", 0))
        if lc_lo is not None and pos < lc_lo:
            continue
        if lc_hi is not None and pos > lc_hi:
            continue
        for key, n in (rec.get("hetero") or {}).items():
            cells[key] = cells.get(key, 0) + n
    return cells


def matrix_rows(cells):
    # NEGATIVE: sorted() over the key set is the fix and is exempt.
    return [(key, cells[key]) for key in sorted(set(cells))]
