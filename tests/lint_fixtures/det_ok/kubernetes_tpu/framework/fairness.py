"""The allowed idioms for a weighted-fair admission policy: the
LOGICAL clock injected by the caller, sorted tenant scans with name
tie-breaks, and crc32 overflow bucketing — a recovered ledger replays
the exact admission order of the interrupted run."""

import zlib


class GoodAdmission:
    def __init__(self, clock):
        self.clock = clock  # injected logical clock, never a wall read
        self.credits = {}
        self.vfinish = {}

    def refill(self, tenant, rate):
        now = self.clock()
        self.credits[tenant] = self.credits.get(tenant, 0.0) + rate * now
        return now

    def select(self, tenants):
        best = None
        # NEGATIVE: sorted() over the candidate set is the fix — ties
        # break on the sorted tenant name, identically in every process.
        for tenant in sorted(set(tenants)):
            key = (self.vfinish.get(tenant, 0.0), tenant)
            if best is None or key < best:
                best = key
        return best

    def overflow_bucket(self, tenant, buckets):
        # NEGATIVE: crc32 is unsalted — every process, every run, the
        # same bucket.
        return zlib.crc32(tenant.encode("utf-8")) % buckets
