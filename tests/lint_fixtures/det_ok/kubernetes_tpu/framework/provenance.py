"""The allowed idioms for a decision-provenance recorder: capsules
stamped with the journal seq (a logical clock), sorted ring sweeps,
the device's own seeded avalanche hash for tie rands — an explain
record reproduces the committed decision bit for bit, every run."""

import zlib


class GoodProvenanceRing:
    def __init__(self, capacity=4096):
        self.capacity = capacity
        self.capsules = {}

    def record(self, uid, node, score, seq):
        # NEGATIVE: the bind record's journal seq is the stamp — a
        # logical clock both the live run and the replay share.
        self.capsules[uid] = {"node": node, "score": score, "seq": seq}

    def sweep(self, keep):
        evicted = []
        # NEGATIVE: sorted() over the ring is the fix — every process
        # evicts the same capsules in the same order.
        for uid in sorted(set(self.capsules)):
            if uid not in keep:
                evicted.append(uid)
        return evicted

    def reconstruct_pick(self, ties, tie_rand):
        # NEGATIVE: kth comes from the device's own journaled tie rand —
        # the reconstruction replays the committed pick exactly.
        return ties[tie_rand % len(ties)]

    def tie_rand(self, uid, step):
        # NEGATIVE: crc32 is unsalted — every process derives the same
        # tie rand from the same (uid, step).
        return zlib.crc32(f"{uid}:{step}".encode("utf-8")) & 0xFFFFFFFF
