"""Healthy autoscaler idioms: logical clocks injected by the caller,
sorted shard iteration, deterministic tie-breaks."""


def should_split(now, last_action_ts, cooldown_s):
    # NEGATIVE: the clock is a parameter (the scenario/logical clock).
    return now - last_action_ts > cooldown_s


def pick_hot_shard(window_binds, n):
    # NEGATIVE: sorted iteration, ties toward the lowest shard id.
    total = sum(window_binds.values()) or 1
    return min(
        sorted(window_binds),
        key=lambda s: (-(window_binds[s] / total) * n, s),
    )
