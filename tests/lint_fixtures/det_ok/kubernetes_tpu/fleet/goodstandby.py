"""Standby-pool determinism negative fixture: monotonic ages, sorted
slot scans and crc32 bucketing (zero findings expected)."""

import time
import zlib


def slot_age(born_mono):
    # perf_counter/monotonic feed observability, never decisions.
    return time.monotonic() - born_mono


def oldest_slot(slot_ids):
    for sid in sorted(slot_ids):
        return sid


def claim_bucket(slot_name, n):
    return zlib.crc32(slot_name.encode()) % n
