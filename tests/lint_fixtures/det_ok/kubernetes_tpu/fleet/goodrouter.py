"""Fleet determinism negative fixture: crc32 routing and counter-hash
tie-breaks are pure functions of their inputs (zero findings)."""

import zlib


def route(pod_uid: str, n_shards: int) -> int:
    return zlib.crc32(pod_uid.encode()) % max(n_shards, 1)


def tie_break(candidates, step: int):
    x = (step * 0x9E3779B1) & 0xFFFFFFFF
    x = ((x ^ (x >> 16)) * 0x7FEB352D) & 0xFFFFFFFF
    return sorted(candidates)[x % len(candidates)]


def lease_home(node_name: str, n_shards: int) -> int:
    # NEGATIVE: crc32 Lease routing is a pure, cross-process-stable
    # function of the node name (zero findings).
    return zlib.crc32(node_name.encode()) % max(n_shards, 1)
