"""Suppression fixture: the same seeded WAL violations as wal_bad, but
annotated with `# tpulint: disable=...` — the engine must report none."""


class SuppressedScheduler:
    def replay_apply(self, qp, node):
        # Recovery replay applies decisions the journal already holds.
        self.cache.finish_binding(qp.pod.uid)  # tpulint: disable=wal-unjournaled-apply

    def replay_quarantine(self, qp):
        # Family-level suppression on the preceding comment line:
        # tpulint: disable=wal
        self.queue.quarantine(qp)
