"""Seeded wal-unsynced-publish violations: atomic-rename publishes whose
bytes were never forced to disk first.  os.replace is only atomic about
NAMES — without the fsync the renamed file can hold garbage after a
crash, and recovery trusts whatever it finds there.
"""

import os


class BadSnapshotter:
    def rotate(self, path, tmp):
        # POSITIVE wal-unsynced-publish: rename with no fsync anywhere
        # on the path.
        with open(tmp, "wb") as f:
            f.write(self._encode())
        os.replace(tmp, path)

    def publish_via_helper(self, path, tmp):
        # POSITIVE, reported HERE (the frontier): the helper does the
        # rename, no caller or callee ever fsyncs.
        with open(tmp, "wb") as f:
            f.write(self._encode())
        self._swap(tmp, path)

    def _swap(self, tmp, path):
        os.replace(tmp, path)

    def fsync_on_one_branch_only(self, path, tmp, fast):
        # POSITIVE: the fast path skips the fsync, so the rename is not
        # DOMINATED by it — must-analysis catches the racy branch.
        f = open(tmp, "wb")
        f.write(self._encode())
        if not fast:
            os.fsync(f.fileno())
        f.close()
        os.rename(tmp, path)
