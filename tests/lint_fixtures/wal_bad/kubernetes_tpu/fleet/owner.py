"""Seeded fleet WAL violations: a shard handoff made live without its
journal record first is a transfer the next takeover cannot redo."""


class BadOwner:
    def import_without_journal(self, record, payload):
        # POSITIVE wal-unjournaled-apply: the handoff applies with no
        # journal append anywhere in scope — a crash here strands the
        # nodes on neither shard's journal.
        self.apply_handoff(payload)

    def import_apply_then_append(self, record, payload):
        # POSITIVE wal-apply-before-journal: apply precedes the append —
        # the exact window pre-map-write crashes into.
        self.apply_handoff(payload)
        self.sched._journal_append("handoff", **record)

    def healthy_import(self, record, payload):
        # NEGATIVE: journal-before-apply, the required shape.
        self.sched._journal_append("handoff", **record)
        self.apply_handoff(payload)
