"""Seeded fleet WAL violations: a shard handoff made live without its
journal record first is a transfer the next takeover cannot redo."""


class BadOwner:
    def import_without_journal(self, record, payload):
        # POSITIVE wal-unjournaled-apply: the handoff applies with no
        # journal append anywhere in scope — a crash here strands the
        # nodes on neither shard's journal.
        self.apply_handoff(payload)

    def import_apply_then_append(self, record, payload):
        # POSITIVE wal-apply-before-journal: apply precedes the append —
        # the exact window pre-map-write crashes into.
        self.apply_handoff(payload)
        self.sched._journal_append("handoff", **record)

    def healthy_import(self, record, payload):
        # NEGATIVE: journal-before-apply, the required shape.
        self.sched._journal_append("handoff", **record)
        self.apply_handoff(payload)


class BadLifecycleOwner:
    """ISSUE 10: the owner-side taint/evict apply sites — a shard's
    lifecycle controller driving them without the journal first would
    replay a dead node as healthy (or lose the evicted pod) at the next
    takeover."""

    def taint_without_journal(self, name, taints):
        # POSITIVE wal-unjournaled-apply: an owner writing a lifecycle
        # taint set live with no ``taint`` record in scope.
        self.sched._apply_node_taints(name, taints)

    def evict_apply_then_append(self, uid, pod):
        # POSITIVE wal-apply-before-journal: the eviction unwinds before
        # its ``evict`` record exists — the crash window loses the
        # requeue the router is owed.
        self.sched._apply_eviction(uid, pod)
        self.sched._journal_append("evict", uid=uid)

    def healthy_evict(self, name, taints, uid, pod):
        # NEGATIVE: journal-before-apply for both owner-side sites.
        self.sched._journal_append("taint", node=name)
        self.sched._apply_node_taints(name, taints)
        self.sched._journal_append("evict", uid=uid)
        self.sched._apply_eviction(uid, pod)
