"""Seeded autoscaler WAL violations (ISSUE 11): a resize action made
live without the acquiring owner's handoff record first is a transfer
the next takeover cannot redo — the autoscaler's action path must stay
on the journaled orchestration."""


class BadAutoscaler:
    def split_without_journal(self, rec, map_path):
        # POSITIVE wal-unjournaled-apply: the live resize applies with
        # no journal append anywhere in scope — a SIGKILL inside leaves
        # the moved nodes on neither owner's journal.
        self.router.apply_handoff(rec, map_path)

    def split_apply_then_append(self, rec, map_path):
        # POSITIVE wal-apply-before-journal: the transfer goes live
        # before its record exists — exactly the window the
        # --autoscale-kill matrix SIGKILLs into.
        self.router.apply_handoff(rec, map_path)
        self.owner.sched._journal_append("handoff", **rec)

    def healthy_split(self, rec, map_path):
        # NEGATIVE: journal-before-apply, the required shape.
        self.owner.sched._journal_append("handoff", **rec)
        self.router.apply_handoff(rec, map_path)
