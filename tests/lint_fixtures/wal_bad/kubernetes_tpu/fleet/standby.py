"""Seeded standby-pool WAL violations: a promotion made live without
its pool WAL record first is a warm child two owners can be handed
after a crash (the claim file alone is not replayable intent)."""


class BadPool:
    def promote_without_journal(self, slot, shard_id):
        # POSITIVE wal-unjournaled-apply: the slot flips to "promoted"
        # with no pool-WAL append anywhere in scope — a reopen after a
        # crash here re-offers the consumed slot.
        self.finish_promotion(slot, shard_id)

    def promote_apply_then_append(self, slot, shard_id, rec):
        # POSITIVE wal-apply-before-journal: apply precedes the append —
        # the exact window the standby kill-matrix cells crash into.
        self.finish_promotion(slot, shard_id)
        self.journal.append(rec)

    def healthy_promote(self, slot, shard_id, rec):
        # NEGATIVE: append-before-apply, the required shape.
        self.journal.append(rec)
        self.finish_promotion(slot, shard_id)
