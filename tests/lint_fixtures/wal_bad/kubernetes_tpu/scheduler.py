"""Seeded WAL-discipline violations (tests/test_static_analysis.py).

Not importable product code — a miniature commit path whose ordering is
deliberately wrong, so each wal-* rule demonstrably fires.
"""


class BadScheduler:
    def commit_apply_then_append(self, qp, node):
        # POSITIVE wal-apply-before-journal: the binding goes live before
        # the write-ahead record exists — a crash between the two forgets
        # a decision the cluster already acted on.
        self.cache.finish_binding(qp.pod.uid)
        self._journal_bind(qp.pod, node)

    def quarantine_without_journal(self, qp):
        # POSITIVE wal-unjournaled-apply: durable quarantine state mutated
        # with no journal append anywhere in the function.
        self.queue.quarantine(qp)

    def healthy_commit(self, qp, node):
        # NEGATIVE: journal-before-apply, the required shape.
        self._journal_bind(qp.pod, node)
        self.cache.finish_binding(qp.pod.uid)
