"""Seeded fairness-ledger WAL violations (ISSUE 17): the durable WFQ
ledger advances only through apply_admission, and the debit batch's
``admission`` record must be inside the group barrier FIRST — applying
debits the journal never heard of lets a crash re-select those pods in
a different order than the run it interrupted."""


class BadCommitDrain:
    def drain_without_journal(self, sched, ticket):
        # POSITIVE wal-unjournaled-apply: the debit batch goes durable
        # with no journal append in scope — a SIGKILL here forgets the
        # admissions while their ledger debits survive the snapshot.
        sched.queue.admission.apply_admission(ticket.admission)

    def drain_apply_then_group(self, sched, ticket):
        # POSITIVE wal-apply-before-journal: debits applied BEFORE the
        # group appends the admission record — the mid-group-fsync crash
        # cell would find a durable ledger with no record to replay.
        sched.queue.admission.apply_admission(ticket.admission)
        with sched.journal.group():
            sched._journal_append("admission", debits=ticket.admission)

    def healthy_drain(self, sched, ticket):
        # NEGATIVE: the admission record rides the group barrier first;
        # debits apply only after the fsync returns.
        with sched.journal.group():
            sched._journal_append("admission", debits=ticket.admission)
        sched.queue.admission.apply_admission(ticket.admission)
