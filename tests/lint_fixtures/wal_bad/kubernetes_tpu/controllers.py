"""Seeded WAL-discipline violations for the failure-response apply sites
(ISSUE 9): the node-lifecycle taint write and the evict-with-requeue path
must journal BEFORE they apply, like every other commit."""


class BadLifecycle:
    def transition_apply_then_journal(self, name, taints):
        # POSITIVE wal-apply-before-journal: the taint set goes live
        # before its ``taint`` record exists — a crash in the window
        # replays a dead node as healthy.
        self.sched._apply_node_taints(name, taints)
        self.sched._journal_append("taint", node=name)

    def evict_without_journal(self, uid, pod):
        # POSITIVE wal-unjournaled-apply: an eviction applied with no
        # journal call in scope — a crash forgets the requeue and the
        # pod is lost.
        self.sched._apply_eviction(uid, pod)

    def healthy_transition(self, name, taints, uid, pod):
        # NEGATIVE: journal-before-apply for both new markers.
        self.sched._journal_append("taint", node=name)
        self.sched._apply_node_taints(name, taints)
        self.sched._journal_append("evict", uid=uid)
        self.sched._apply_eviction(uid, pod)
