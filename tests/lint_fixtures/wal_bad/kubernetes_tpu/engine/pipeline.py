"""Seeded pipeline-drain WAL violations (ISSUE 15): the staged commit
group's applies live in the drain — a drain that applies a bind before
(or without) its group's journal records re-opens exactly the
apply-then-append window the group fsync barrier exists to close."""


class BadDrain:
    def drain_without_journal(self, sched, ticket):
        # POSITIVE wal-unjournaled-apply: the staged binds go live with
        # no journal append in scope — a SIGKILL mid-drain forgets every
        # decision in the group.
        for sb in ticket.staged:
            sb.qp.pod.spec.node_name = sb.node_name
            sched.cache.finish_binding(sb.qp.pod.uid)

    def drain_apply_then_group(self, sched, ticket):
        # POSITIVE wal-apply-before-journal: applies run BEFORE the
        # group's records are even appended — the mid-group-fsync crash
        # cell would find live bindings with no durable records.
        for sb in ticket.staged:
            sched.cache.finish_binding(sb.qp.pod.uid)
        with sched.journal.group():
            for sb in ticket.staged:
                sched._journal_bind(sb.qp.pod, sb.node_name)

    def healthy_drain(self, sched, ticket):
        # NEGATIVE: group-journal first, applies only after the barrier.
        with sched.journal.group():
            for sb in ticket.staged:
                sched._journal_bind(sb.qp.pod, sb.node_name)
        for sb in ticket.staged:
            sched.cache.finish_binding(sb.qp.pod.uid)
