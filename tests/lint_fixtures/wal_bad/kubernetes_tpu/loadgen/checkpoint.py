"""Seeded checkpoint-writer WAL violations: a generation made live
(os.replace) without its journaled digest first leaves a resumed run
nothing to verify bit-identity against."""


class BadCheckpointer:
    def publish_without_journal(self, tmp_path, generation):
        # POSITIVE wal-unjournaled-apply: the generation goes live with
        # no digest record in scope — resume cannot prove the prefix.
        self.finish_checkpoint(tmp_path, generation)

    def publish_apply_then_append(self, tmp_path, generation, rec):
        # POSITIVE wal-apply-before-journal: the os.replace apply runs
        # before the digest append — a crash between them publishes a
        # checkpoint the journal never heard of.
        self.finish_checkpoint(tmp_path, generation)
        self._journal_append("checkpoint", **rec)

    def healthy_publish(self, tmp_path, generation, rec):
        # NEGATIVE: digest journaled first, then the atomic publish.
        self._journal_append("checkpoint", **rec)
        self.finish_checkpoint(tmp_path, generation)
