"""Seeded INTERPROCEDURAL WAL violations (tests/test_static_analysis.py).

The pre-flow engine matched journal/apply pairs per function, so an
apply site buried inside a helper was invisible from the caller — the
blind spot ISSUE 19 closes.  Each positive here hides the apply one or
two calls below the function that owns the ordering decision; the
finding must surface at the FRONTIER (the outermost caller with no
in-scope callers of its own), naming the chain.
"""


class DeepScheduler:
    # -- two-call-deep unjournaled apply --------------------------------

    def commit_via_helpers(self, qp, node):
        # POSITIVE wal-unjournaled-apply, reported HERE: no journal
        # activity anywhere on the chain, and the actual apply is two
        # calls down (commit_via_helpers -> _stage -> _land).
        self._stage(qp, node)

    def _stage(self, qp, node):
        self._land(qp, node)

    def _land(self, qp, node):
        self.cache.finish_binding(qp.pod.uid)

    # -- two-call-deep apply racing the journal -------------------------

    def commit_then_record(self, qp, node):
        # POSITIVE wal-apply-before-journal, reported HERE: the helper
        # chain lands the binding first, the journal record comes after
        # — a crash between the two forgets an applied decision.
        self._stage_fast(qp, node)
        self._journal_bind(qp.pod, node)

    def _stage_fast(self, qp, node):
        self._land_fast(qp, node)

    def _land_fast(self, qp, node):
        self.cache.finish_binding(qp.pod.uid)
