"""jax-partition-unsafe negative fixture: the op that reduces over the
candidate axis IS registered in the fixture router's
PARTITION_INEXACT_OPS, and the gather-only op needs no entry."""

import jax.numpy as jnp

from ..framework import OpDef


def score_fn(state, pf, ctx, feasible):
    raw = pf["affinity_rows"].sum(axis=1)
    peak = jnp.max(jnp.where(feasible, raw, 0))
    return jnp.where(feasible, (raw * 100) // jnp.maximum(peak, 1), 0)


def gather_score_fn(state, pf, ctx, feasible):
    return jnp.where(feasible, pf["local_hint"], 0)


REGISTERED_OP = OpDef(
    name="ShardBlindAffinity",
    featurize=None,
    filter=None,
    score=score_fn,
)

GATHER_OP = OpDef(
    name="LocalHint",
    featurize=None,
    filter=None,
    score=gather_score_fn,
)
