"""Fixture router registry, healthy twin: exactly the ops that reduce
over the candidate axis, nothing stale."""

PARTITION_INEXACT_OPS = frozenset(
    {
        # ops/goodop.py score_fn normalizes by the global feasible peak.
        "ShardBlindAffinity",
    }
)
