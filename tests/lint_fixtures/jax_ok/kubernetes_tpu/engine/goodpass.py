"""jax-family negative fixture, device-discipline half: the same pass
shapes as the bad tree with every hazard spelled the disciplined way.
Zero findings expected — including the host-static idioms (`"k" in pf`,
`x is None`, `.shape` reads) the rules must NOT confuse for syncs."""

import jax
import jax.numpy as jnp
from jax import lax


@jax.jit
def _kernel(state, pf):
    total = jnp.sum(state.req * pf["weight"])
    # Device-side branch: lax.cond keeps the select on device.
    norm = lax.cond(
        jnp.any(state.valid),
        lambda t: t + 1.0,
        lambda t: t,
        jnp.max(total),
    )
    # Host-static idioms that merely mention traced names:
    if "port_keys" in pf:
        total = total + jnp.sum(pf["port_keys"])
    k = state.req.shape[0]
    if k > 1:
        total = total * 2
    return total, norm


@jax.jit
def _outer(state, pf):
    return _scale(state, pf)


def _scale(state, pf, bias=None):
    # Identity-vs-None on a traced argument is host-static.
    if bias is None:
        return state.req * pf["weight"]
    return state.req * pf["weight"] + bias


def _step(state, pf, ks):
    return state.req[ks]


step = jax.jit(_step, static_argnums=(2,))


def drive_static(state, pf):
    # Hashable constants in static positions: one trace, no churn.
    a = step(state, pf, 3)
    b = step(state, pf, 7)
    return a, b


def _apply(state, pf):
    return state


apply_step = jax.jit(_apply, donate_argnums=(0,))


def drive_donation(state, pf):
    # The donation idiom: rebind the result over the donated name —
    # nothing reads the dead buffer.
    state = apply_step(state, pf)
    return state.num_pods
