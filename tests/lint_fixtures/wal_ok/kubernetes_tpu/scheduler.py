"""WAL-discipline negative fixture: every apply site is dominated by a
journal append (tests/test_static_analysis.py expects zero findings)."""


class GoodScheduler:
    def commit(self, qp, node):
        self._journal_bind(qp.pod, node)
        qp.pod.spec.node_name = node
        self.cache.finish_binding(qp.pod.uid)

    def quarantine_poison(self, qp):
        self.journal.append("quarantine", {"uid": qp.pod.uid})
        self.queue.quarantine(qp)

    def no_apply_sites_here(self, qp):
        self.queue.done(qp.pod.uid)
