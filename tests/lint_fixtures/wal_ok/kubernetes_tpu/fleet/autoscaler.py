"""Healthy autoscaler shapes: the journal duty delegated one layer down
(the real module's idiom — owner.import_nodes appends the handoff record
before a node moves), suppressed inline with the reason."""


class GoodAutoscaler:
    def execute(self, rec, map_path):
        # The acquiring owner journals inside import_nodes; the loop
        # only orchestrates.
        # tpulint: disable=wal-unjournaled-apply
        self.router.apply_handoff(rec, map_path)

    def execute_with_own_record(self, rec, map_path):
        # Journal-before-apply directly — also clean.
        self.owner.sched._journal_append("handoff", **rec)
        self.router.apply_handoff(rec, map_path)
