"""Standby-pool WAL negative fixture: the promotion journals before it
applies (tests/test_static_analysis.py expects zero findings)."""


class GoodPool:
    def promote(self, slot, shard_id, rec):
        self.journal.append(rec)
        self.finish_promotion(slot, shard_id)

    def no_apply_sites(self, slots):
        return [s for s in slots if s.warm]
