"""Fleet WAL negative fixture: the handoff journals before it applies
(tests/test_static_analysis.py expects zero findings)."""


class GoodOwner:
    def import_nodes(self, record, payload):
        self.sched._journal_append("handoff", **record)
        self.apply_handoff(payload)

    def no_apply_sites(self, names):
        return [n for n in names if n in self.sched.cache.nodes]

    def lifecycle_evict(self, name, taints, uid, pod):
        # Owner-side taint/evict: journal-before-apply (zero findings).
        self.sched._journal_append("taint", node=name)
        self.sched._apply_node_taints(name, taints)
        self.sched._journal_append("evict", uid=uid)
        self.sched._apply_eviction(uid, pod)
