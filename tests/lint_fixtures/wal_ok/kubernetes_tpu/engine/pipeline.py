"""Healthy pipeline-drain shapes: the group's records are appended (and
fsync'd by the barrier) before any staged bind applies — the real
module's drain_commit ordering."""


class GoodDrain:
    def drain(self, sched, ticket):
        # Journal-before-apply at group scope: append every record
        # inside the barrier, apply only after it returns.
        with sched.journal.group():
            for sb in ticket.staged:
                sched._journal_bind(sb.qp.pod, sb.node_name)
        for sb in ticket.staged:
            sb.qp.pod.spec.node_name = sb.node_name
            sched.cache.finish_binding(sb.qp.pod.uid)
            sched.queue.done(sb.qp.pod.uid)
