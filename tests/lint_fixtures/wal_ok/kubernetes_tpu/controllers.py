"""WAL-discipline negative fixture for the failure-response apply sites:
journal-before-apply for taint writes and evictions, plus a marker's own
definition delegating to another marker (the journal duty lives at its
call sites — zero findings expected)."""


class GoodLifecycle:
    def write_taints(self, name, taints):
        self.sched._journal_append("taint", node=name)
        self.sched._apply_node_taints(name, taints)

    def evict(self, uid, pod):
        self.sched._journal_append("evict", uid=uid)
        self.sched._apply_eviction(uid, pod)

    def _apply_eviction(self, uid, pod):
        # A marker's own definition may delegate to another marker —
        # the caller journals (the write_taints/evict shapes above).
        self._unwind_pod(uid)
        self.queue_add(pod)
