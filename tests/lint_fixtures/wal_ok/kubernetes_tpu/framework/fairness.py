"""Healthy fairness-ledger commit shape: the debit batch's
``admission`` record is appended inside the group barrier before any
bind record, and the durable ledger advances only after the fsync
returns — the real drain_commit ordering."""


class GoodCommitDrain:
    def drain(self, sched, ticket):
        with sched.journal.group():
            sched._journal_append("admission", debits=ticket.admission)
            for sb in ticket.staged:
                sched._journal_bind(sb.qp.pod, sb.node_name)
        sched.queue.admission.apply_admission(ticket.admission)
        for sb in ticket.staged:
            sched.cache.finish_binding(sb.qp.pod.uid)
