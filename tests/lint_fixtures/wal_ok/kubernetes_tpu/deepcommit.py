"""Interprocedural WAL negative fixture: the ordering is right but only
visible ACROSS functions — the shapes the per-function engine either
false-positived on (helper journals, caller applies) or could not credit
at all (journal and apply both buried in helpers, correctly ordered).
Zero findings expected."""


class DeepGoodScheduler:
    def commit(self, qp, node):
        # The journal record is appended by a helper; the per-function
        # matcher saw an apply with no journal here and cried wolf.  The
        # flow engine proves _record journals on every path, so the
        # apply below is dominated.
        self._record(qp, node)
        self.cache.finish_binding(qp.pod.uid)

    def _record(self, qp, node):
        self._journal_bind(qp.pod, node)

    def commit_all_buried(self, qp, node):
        # Journal AND apply both live in helpers, ordered correctly.
        self._record(qp, node)
        self._land(qp, node)

    def _land(self, qp, node):
        self.cache.finish_binding(qp.pod.uid)

    def commit_helper_owns_ordering(self, qp, node):
        # The helper itself journals-then-applies; every caller is clean
        # by construction.
        self._record_and_land(qp, node)

    def _record_and_land(self, qp, node):
        self._journal_bind(qp.pod, node)
        self.cache.finish_binding(qp.pod.uid)
