"""Checkpoint-writer WAL negative fixture: digest journaled first,
then the atomic publish (zero findings expected)."""


class GoodCheckpointer:
    def publish(self, tmp_path, generation, rec):
        self._journal_append("checkpoint", **rec)
        self.finish_checkpoint(tmp_path, generation)
