"""wal-unsynced-publish negative fixture: every rename is dominated by a
data fsync — directly, via a flush helper, or on both arms of a branch.
Zero findings expected."""

import os


class GoodSnapshotter:
    def rotate(self, path, tmp):
        with open(tmp, "wb") as f:
            f.write(self._encode())
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def rotate_via_flush_helper(self, path, tmp):
        # The fsync lives in a helper; the flow engine proves _flush
        # syncs on every path, so this rename is dominated.
        f = open(tmp, "wb")
        f.write(self._encode())
        self._flush(f)
        f.close()
        os.replace(tmp, path)

    def _flush(self, f):
        f.flush()
        os.fsync(f.fileno())

    def rotate_both_branches(self, path, tmp, compress):
        f = open(tmp, "wb")
        if compress:
            f.write(self._encode_compressed())
            os.fsync(f.fileno())
        else:
            f.write(self._encode())
            os.fsync(f.fileno())
        f.close()
        os.rename(tmp, path)
