"""Wire negative fixture: complete handler + client coverage."""


def _dispatch(sched, env, out):
    kind = env.WhichOneof("msg")
    if kind == "add":
        sched.add(env.add.kind)
        out.response.SetInParent()
    elif kind == "remove":
        sched.remove(env.remove.uid)
        out.response.SetInParent()


class FixtureClient:
    def add(self, kind):
        env = self._envelope()
        env.add.kind = kind
        return self._call(env)

    def remove(self, uid):
        env = self._envelope()
        env.remove.uid = uid
        return self._call(env)

    def _call(self, env):
        resp = self._roundtrip(env)
        if resp.response.error:
            raise RuntimeError(resp.response.error)
        return resp

    def read_push(self):
        env = self._read()
        return env.push
