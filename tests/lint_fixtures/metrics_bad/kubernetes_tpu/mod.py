"""Seeded metrics-hygiene violations (tests/test_static_analysis.py)."""


def install(reg):
    # POSITIVE metrics-prefix: no scheduler_/sidecar_ namespace.
    bad = reg.counter("attempts_total", "Unprefixed family.")
    bad.inc()
    # POSITIVE metrics-duplicate: same family registered at two sites.
    first = reg.counter("scheduler_dup_total", "Registered here...")
    first.inc()
    # POSITIVE metrics-labels: one name written with two label schemas.
    split = reg.counter("scheduler_split_total", "Forked series.")
    split.inc(result="ok")
    split.inc(kind="batch")


def install_again(reg):
    # ...and POSITIVE metrics-duplicate again here.
    second = reg.counter("scheduler_dup_total", "Divergent help string.")
    second.inc()


def tenant_leak(reg, pod):
    # POSITIVE metrics-tenant-label: a raw per-pod string reaches the
    # tenant label (unbounded cardinality) — must route through
    # TenantLabeler.label_for.
    c = reg.counter("scheduler_tenant_probe_total", "Tenant probe.")
    c.inc(tenant=pod.metadata.labels["scheduler.tpu/tenant"])
    raw = pod.metadata.name
    # POSITIVE metrics-tenant-label again: a symbol NOT fed by label_for.
    c.inc(tenant=raw)
