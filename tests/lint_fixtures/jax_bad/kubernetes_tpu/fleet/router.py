"""Fixture router registry for the jax-partition-unsafe rule: lists an
op nobody defines (stale) and omits the one that actually reduces over
the candidate axis (ShardBlindAffinity, ops/badop.py)."""

PARTITION_INEXACT_OPS = frozenset(
    {
        # POSITIVE (stale entry): no registered score op of this name
        # reduces over the candidate axis.
        "GhostOp",
    }
)
