"""Seeded jax-family violations, device-discipline half
(tests/test_static_analysis.py).

Miniature compiled-pass shapes where each hazard the rules exist for is
committed on purpose: host syncs inside jit, retrace-per-call static
args, and donated buffers read after dispatch.
"""

import jax
import jax.numpy as jnp
from functools import partial


@jax.jit
def _kernel(state, pf):
    total = jnp.sum(state.req * pf["weight"])
    # POSITIVE jax-host-sync: .item() forces a device->host transfer on
    # every pass invocation.
    budget = total.item()
    # POSITIVE jax-host-sync: float() over a traced value is the same
    # sync spelled differently (and a TypeError under trace).
    norm = float(jnp.max(total))
    # POSITIVE jax-host-sync: branching on a device value blocks on the
    # transfer (lax.cond is the on-device form).
    if jnp.any(state.valid):
        norm = norm + 1.0
    return total, budget, norm


@jax.jit
def _outer(state, pf):
    # The sync hides one call down — the closure walk still finds it.
    return _scale(state, pf)


def _scale(state, pf):
    # POSITIVE jax-host-sync (reported against _scale, a device context
    # by closure): asserting on a traced value syncs.
    assert state.valid.any()
    return state.req * pf["weight"]


def _step(state, pf, ks):
    return state.req[ks]


step = jax.jit(_step, static_argnums=(2,))


@partial(jax.jit, static_argnames=("mode",))
def _ranked(state, pf, mode):
    return state.req * (2 if mode == "wide" else 1)


def drive_retrace(state, pf, names):
    # POSITIVE jax-retrace-hazard: a list in a static position is
    # unhashable — TypeError at dispatch.
    a = step(state, pf, [1, 2, 3])
    # POSITIVE jax-retrace-hazard: a fresh expression per call in a
    # static position recompiles the kernel every time.
    b = step(state, pf, len(names) + 1)
    # POSITIVE jax-retrace-hazard: same hazard through static_argnames.
    c = _ranked(state, pf, mode="wide-%d" % len(names))
    return a, b, c


def _apply(state, pf):
    return state


apply_step = jax.jit(_apply, donate_argnums=(0,))


def drive_donation(state, pf):
    out = apply_step(state, pf)
    # POSITIVE jax-donation-reuse: ``state`` was donated at dispatch —
    # this read touches a buffer the runtime already reused.
    stale = state.num_pods
    return out, stale
