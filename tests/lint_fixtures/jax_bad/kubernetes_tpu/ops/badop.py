"""Seeded jax-partition-unsafe violation: a score op that normalizes
over the GLOBAL candidate axis without being registered in the router's
PARTITION_INEXACT_OPS — per-shard evaluation would silently diverge from
a single scheduler."""

import jax.numpy as jnp

from ..framework import OpDef


def score_fn(state, pf, ctx, feasible):
    raw = pf["affinity_rows"].sum(axis=1)
    # The hazard: max over ALL feasible candidates — each fleet shard
    # sees only its own slice, so the normalizer differs per shard.
    peak = jnp.max(jnp.where(feasible, raw, 0))
    return jnp.where(feasible, (raw * 100) // jnp.maximum(peak, 1), 0)


def gather_score_fn(state, pf, ctx, feasible):
    # NEGATIVE shape in the bad tree: pure per-candidate gather math,
    # no cross-candidate reduction — stays unregistered AND unflagged.
    return jnp.where(feasible, pf["local_hint"], 0)


BAD_OP = OpDef(
    name="ShardBlindAffinity",
    featurize=None,
    filter=None,
    score=score_fn,
)

GATHER_OP = OpDef(
    name="LocalHint",
    featurize=None,
    filter=None,
    score=gather_score_fn,
)
