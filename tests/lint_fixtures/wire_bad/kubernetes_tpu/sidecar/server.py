"""Seeded wire fixture server: handles add/remove/dump plus a `bogus`
kind the proto never declared; `schedule` and `cancel` are unhandled."""


def _dispatch(sched, env, out):
    kind = env.WhichOneof("msg")
    if kind == "add":
        sched.add(env.add.kind)
        out.response.SetInParent()
    elif kind == "remove":
        sched.remove(env.remove.uid)
        out.response.SetInParent()
    elif kind == "dump":
        out.response.SetInParent()
    elif kind == "bogus":
        out.response.SetInParent()


class FixtureClient:
    def add(self, kind):
        env = self._envelope()
        env.add.kind = kind
        return self._call(env)

    def remove(self, uid):
        env = self._envelope()
        env.remove.uid = uid
        return self._call(env)

    def schedule(self, drain=True):
        env = self._envelope()
        env.schedule.drain = drain
        return self._call(env)

    def dump(self):
        env = self._envelope()
        env.dump.SetInParent()
        return self._call(env)

    def _call(self, env):
        resp = self._roundtrip(env)
        if resp.response.error:
            raise RuntimeError(resp.response.error)
        return resp

    def read_push(self):
        env = self._read()
        return env.push
