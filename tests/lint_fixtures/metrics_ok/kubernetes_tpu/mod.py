"""Metrics-hygiene negative fixture: prefixed, single-site, one label
schema per family — zero findings."""


def install(reg):
    c = reg.counter("scheduler_good_total", "Prefixed, one site.")
    c.inc(result="ok")
    c.inc(result="error")
    g = reg.gauge("sidecar_depth", "Sidecar-prefixed gauge.")
    g.set(3.0, queue="active")
    g.set(0.0, queue="backoff")


def tenant_bounded(reg, labeler, pod, TENANT_FALLBACK="-"):
    """Every accepted tenant-label shape: a direct label_for call, a
    symbol assigned from one (conditional expressions included), the
    fallback constant, and string literals."""
    t = reg.counter("scheduler_tenant_good_total", "Bounded tenants.")
    t.inc(tenant=labeler.label_for("team-a"))
    label = (
        labeler.label_for(pod.metadata.labels.get("x"))
        if labeler is not None
        else TENANT_FALLBACK
    )
    t.inc(tenant=label)
    t.inc(tenant=TENANT_FALLBACK)
    t.inc(tenant="-")
