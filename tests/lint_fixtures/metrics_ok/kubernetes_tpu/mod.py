"""Metrics-hygiene negative fixture: prefixed, single-site, one label
schema per family — zero findings."""


def install(reg):
    c = reg.counter("scheduler_good_total", "Prefixed, one site.")
    c.inc(result="ok")
    c.inc(result="error")
    g = reg.gauge("sidecar_depth", "Sidecar-prefixed gauge.")
    g.set(3.0, queue="active")
    g.set(0.0, queue="backoff")
