"""Crash-safe scheduler state (PR 3): the write-ahead binding journal —
record framing/CRC, torn-tail repair, snapshot barriers, lease-epoch
fencing (append-side and replay-side), full scheduler snapshot+replay
recovery, quarantine persistence, the LIST reconcile rules, the durable
host replay store, and a fast subset of the SIGKILL crash matrix
(scripts/run_fault_matrix.py --kill sweeps the full grid)."""

import json
import os
import struct
import subprocess
import sys
import zlib

import pytest

from kubernetes_tpu.api import serialize
from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.faults import FaultPlan
from kubernetes_tpu.framework.config import fit_only_profile
from kubernetes_tpu.framework.leaderelection import FileLease, read_epoch
from kubernetes_tpu.informers import (
    FakeSource,
    Reflector,
    reconcile_after_recovery,
)
from kubernetes_tpu.journal import (
    Journal,
    StaleEpochError,
    recover,
    scheduler_state,
)
from kubernetes_tpu.queue import SchedulingQueue
from kubernetes_tpu.scheduler import TPUScheduler

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def small_sched(**kw):
    return TPUScheduler(profile=fit_only_profile(), batch_size=8, chunk_size=1, **kw)


def bindings_of(sched):
    return {
        uid: pr.node_name
        for uid, pr in sched.cache.pods.items()
        if pr.bound
    }


def node(name, cpu="4"):
    return make_node(name).capacity({"cpu": cpu, "memory": "16Gi", "pods": 16}).obj()


def pod(name, cpu="1", **kw):
    b = make_pod(name).req({"cpu": cpu})
    if kw.get("node"):
        b = b.node(kw["node"])
    if kw.get("priority"):
        b = b.priority(kw["priority"])
    return b.obj()


# -- record format ----------------------------------------------------------


def test_append_replay_roundtrip(tmp_path):
    j = Journal(str(tmp_path), epoch=1)
    j.append("bind", {"uid": "a", "node": "n1"})
    j.append("delete", {"uid": "b"})
    snap, recs, stats = j.replay()
    assert snap is None
    assert [(r["t"], r["q"]) for r in recs] == [("bind", 1), ("delete", 2)]
    assert stats["fenced"] == 0
    # A reopened journal continues the sequence.
    j.close()
    j2 = Journal(str(tmp_path), epoch=1)
    assert j2.seq == 2
    j2.append("bind", {"uid": "c", "node": "n2"})
    _, recs, _ = j2.replay()
    assert [r["q"] for r in recs] == [1, 2, 3]


def test_torn_tail_truncated_at_open(tmp_path):
    j = Journal(str(tmp_path), epoch=1)
    j.append("bind", {"uid": "a", "node": "n1"})
    j.close()
    wal = os.path.join(str(tmp_path), Journal.WAL)
    good = os.path.getsize(wal)
    with open(wal, "ab") as f:
        f.write(b"\x00\x00\x01\x00" + b"half-a-record")  # length 256, 13 bytes
    j2 = Journal(str(tmp_path), epoch=1)
    assert j2.torn_bytes == 4 + 13
    assert os.path.getsize(wal) == good  # repaired in place
    _, recs, _ = j2.replay()
    assert [r["d"]["uid"] for r in recs] == ["a"]


def test_corrupt_record_stops_replay(tmp_path):
    j = Journal(str(tmp_path), epoch=1)
    j.append("bind", {"uid": "a", "node": "n1"})
    j.append("bind", {"uid": "b", "node": "n2"})
    j.close()
    wal = os.path.join(str(tmp_path), Journal.WAL)
    blob = bytearray(open(wal, "rb").read())
    # Flip a byte inside the FIRST record's payload: framing can't be
    # trusted past a CRC failure, so replay must stop before it.
    blob[12] ^= 0xFF
    with open(wal, "wb") as f:
        f.write(blob)
    j2 = Journal(str(tmp_path), epoch=1)
    _, recs, _ = j2.replay()
    assert recs == []


def test_snapshot_barrier_skips_covered_records(tmp_path):
    j = Journal(str(tmp_path), epoch=1)
    j.append("bind", {"uid": "a", "node": "n1"})
    j.snapshot({"marker": 1})
    j.append("bind", {"uid": "b", "node": "n2"})
    snap, recs, _ = j.replay()
    assert snap["state"] == {"marker": 1}
    assert [r["d"]["uid"] for r in recs] == ["b"]
    # The truncation actually happened (log holds only post-barrier data).
    j.close()
    j2 = Journal(str(tmp_path), epoch=1)
    snap, recs, _ = j2.replay()
    assert snap["seq"] == 1 and [r["q"] for r in recs] == [2]


def test_snapshot_seq_filter_survives_missing_truncate(tmp_path):
    """The mid-truncate crash window: snapshot replaced, log NOT yet
    truncated — every surviving record is <= the barrier and must be
    skipped, not replayed on top of the snapshot."""
    j = Journal(str(tmp_path), epoch=1)
    j.append("bind", {"uid": "a", "node": "n1"})
    j.append("bind", {"uid": "b", "node": "n2"})
    # Write the snapshot document by hand (what snapshot() makes durable
    # before the truncate), leaving the wal untouched.
    with open(os.path.join(str(tmp_path), Journal.SNAP), "wb") as f:
        f.write(json.dumps({"epoch": 1, "seq": 2, "state": {"x": 1}}).encode())
    j.close()
    j2 = Journal(str(tmp_path), epoch=1)
    snap, recs, _ = j2.replay()
    assert snap["state"] == {"x": 1}
    assert recs == []


def test_torn_snapshot_tmp_discarded(tmp_path):
    j = Journal(str(tmp_path), epoch=1)
    j.append("bind", {"uid": "a", "node": "n1"})
    j.snapshot({"good": True})
    # A crash mid-snapshot leaves a torn temp; the replace never ran, so
    # the previous snapshot must still win.
    with open(os.path.join(str(tmp_path), Journal.SNAP + ".tmp"), "wb") as f:
        f.write(b'{"epoch": 9, "seq": 99, "state"')
    j.close()
    j2 = Journal(str(tmp_path), epoch=1)
    snap, _, _ = j2.replay()
    assert snap["state"] == {"good": True}
    assert not os.path.exists(os.path.join(str(tmp_path), Journal.SNAP + ".tmp"))


# -- epoch fencing ----------------------------------------------------------


def test_stale_epoch_append_rejected(tmp_path):
    j1 = Journal(str(tmp_path), epoch=1)
    j1.append("bind", {"uid": "a", "node": "n1"})
    j2 = Journal(str(tmp_path), epoch=2)
    j2.append("bind", {"uid": "b", "node": "n2"})
    # The deposed writer's next append trips the self-fencing tripwire
    # (the log grew under it) even without a fence callable.
    with pytest.raises(StaleEpochError):
        j1.append("bind", {"uid": "c", "node": "nX"})
    assert j1.fenced == 1
    _, recs, _ = Journal(str(tmp_path), epoch=3).replay()
    assert [r["d"]["uid"] for r in recs] == ["a", "b"]


def test_stale_epoch_record_ignored_at_replay(tmp_path):
    """Belt and braces: even a stale record that RACED onto disk is
    dropped by the replay-side running-maximum fence."""
    j = Journal(str(tmp_path), epoch=2)
    j.append("bind", {"uid": "new", "node": "n1"})
    j.close()
    # Forge a stale-epoch record after the epoch-2 one.
    payload = json.dumps(
        {"e": 1, "q": 99, "t": "bind", "d": {"uid": "stale", "node": "nX"}}
    ).encode()
    with open(os.path.join(str(tmp_path), Journal.WAL), "ab") as f:
        f.write(struct.pack(">II", len(payload), zlib.crc32(payload)) + payload)
    j2 = Journal(str(tmp_path), epoch=3)
    _, recs, stats = j2.replay()
    assert [r["d"]["uid"] for r in recs] == ["new"]
    assert stats["fenced"] == 1


def test_leader_failover_mid_append_no_double_bind(tmp_path):
    """Satellite: the standby acquires the flock while the old leader is
    mid-commit.  The old leader's in-flight append is fenced (dropped,
    not written), the new leader's decision stands alone — recovery sees
    exactly one binding for the pod."""
    lease_path = str(tmp_path / "lease")
    jdir = str(tmp_path / "journal")
    old = FileLease(lease_path, identity="old")
    assert old.acquire(block=False)
    j_old = Journal(
        jdir, epoch=old.epoch, fence=lambda: read_epoch(lease_path)
    )
    p = pod("contended")
    j_old.append(
        "bind", {"uid": p.uid, "node": "n0", "pod": serialize.to_dict(p)}
    )
    # The old leader's HOST dies mid-flight (flock freed by the kernel,
    # no clean release); the standby takes over and re-decides the pod.
    os.close(old._fd)
    old._fd = None
    new = FileLease(lease_path, identity="new")
    assert new.acquire(block=False)
    assert new.epoch == old.epoch + 1
    j_new = Journal(
        jdir, epoch=new.epoch, fence=lambda: read_epoch(lease_path)
    )
    j_new.append(
        "bind", {"uid": p.uid, "node": "n1", "pod": serialize.to_dict(p)}
    )
    # The lingering old leader finishes its in-flight commit: fenced.
    with pytest.raises(StaleEpochError):
        j_old.append(
            "bind", {"uid": p.uid, "node": "n0", "pod": serialize.to_dict(p)}
        )
    # Recovery: one binding, the new leader's.
    sched = small_sched()
    sched.add_node(node("n0"))
    sched.add_node(node("n1"))
    recover(sched, Journal(jdir, epoch=new.epoch + 1))
    assert bindings_of(sched) == {p.uid: "n1"}
    new.release()


def test_epoch_monotonicity_feeds_journal(tmp_path):
    """test_leader_election's epoch-monotonicity case, journal-side: each
    tenure's records carry its epoch and order correctly at replay."""
    lease_path = str(tmp_path / "lease")
    jdir = str(tmp_path / "journal")
    for i, who in enumerate(("a", "b", "c"), start=1):
        lease = FileLease(lease_path, identity=who)
        assert lease.acquire(block=False)
        assert lease.epoch == i
        j = Journal(jdir, epoch=lease.epoch)
        j.append("bind", {"uid": f"p{i}", "node": f"n{i}"})
        j.close()
        lease.release()
    _, recs, stats = Journal(jdir, epoch=99).replay()
    assert [r["e"] for r in recs] == [1, 2, 3]
    assert stats["fenced"] == 0


# -- scheduler snapshot + recovery ------------------------------------------


def scenario_sched(journal=None):
    s = small_sched()
    if journal is not None:
        s.attach_journal(journal, snapshot_every_batches=1)
    for i in range(3):
        s.add_node(node(f"n{i}"))
    s.add_pod(pod("resident", cpu="3", node="n0"))
    return s


def test_recovery_from_journal_only(tmp_path):
    """A crash before the first snapshot: bindings rebuild from the raw
    journal (the post-append/pre-apply window end to end)."""
    j = Journal(str(tmp_path), epoch=1)
    s1 = scenario_sched()
    s1.journal = j  # journal appends without snapshot cadence
    s1.queue.journal = j
    s1.add_pod(pod("w1"))
    s1.add_pod(pod("w2"))
    s1.schedule_all_pending()
    want = bindings_of(s1)
    assert {"default/w1", "default/w2"} <= set(want)
    s2 = scenario_sched()
    j2 = Journal(str(tmp_path), epoch=2)
    stats = recover(s2, j2)
    assert stats["records"] >= 2 and not stats["snapshot"]
    assert bindings_of(s2) == want


def test_recovery_from_snapshot_and_journal(tmp_path):
    j = Journal(str(tmp_path), epoch=1)
    s1 = scenario_sched(journal=j)
    s1.add_pod(pod("w1"))
    s1.schedule_all_pending()  # snapshot_every_batches=1 → checkpointed
    assert j.snapshots >= 1
    s1.add_pod(pod("w2"))
    s1.journal = None  # crash window: w2's bind never journals...
    s1.queue.journal = None
    want_pre = bindings_of(s1)
    s2 = small_sched()
    stats = recover(s2, Journal(str(tmp_path), epoch=2))
    assert stats["snapshot"]
    # w1's binding survives via the snapshot; w2 was never scheduled in
    # the journaled world and is simply absent (it would re-arrive via
    # the LIST reconcile as pending).
    got = bindings_of(s2)
    assert got == want_pre
    # Queue state (depths) survives too.
    assert s2.queue.pending_count() == s1.queue.pending_count() - 1  # w2


def test_queue_backoff_and_attempts_survive_restart():
    clock = [100.0]
    q1 = SchedulingQueue(clock=lambda: clock[0])
    p1 = pod("backing-off")
    q1.add(p1)
    qp = q1.pop_batch(1)[0]
    qp.attempts = 3
    q1.add_backoff(qp)
    q1._info[p1.uid] = qp
    state = q1.durable_state()
    [e] = state["entries"]
    assert e["pool"] == "backoff" and e["attempts"] == 3
    assert 0 < e["backoff_remaining_s"] <= q1.backoff_duration(3)
    # Restore into a fresh queue on a DIFFERENT clock base: the remaining
    # backoff carries over relative, not absolute.
    clock2 = [5000.0]
    q2 = SchedulingQueue(clock=lambda: clock2[0])
    assert q2.restore_state(state) == 1
    assert q2.pop_batch(1) == []  # still backing off
    clock2[0] += e["backoff_remaining_s"] + 0.01
    out = q2.pop_batch(1)
    assert [x.pod.uid for x in out] == [p1.uid]
    assert out[0].attempts == 4  # 3 restored + this pop


def test_quarantine_survives_restart(tmp_path):
    """Satellite: quarantined pods (PR 2) survive a host restart with
    their backoff state intact and still release via release_quarantine."""
    j = Journal(str(tmp_path), epoch=1)
    s1 = scenario_sched()
    s1.journal = j
    s1.queue.journal = j
    plan = FaultPlan().add_rule("engine", pod="default/poison")
    plan.install_engine(s1)
    s1.add_pod(pod("poison"))
    s1.add_pod(pod("healthy"))
    s1.schedule_all_pending()
    assert s1.queue.quarantined() == ["default/poison"]
    attempts = s1.queue._quarantine["default/poison"].attempts
    assert "default/healthy" in bindings_of(s1)
    # Restart: fresh scheduler, no fault plan (the poison was transient).
    s2 = scenario_sched()
    recover(s2, Journal(str(tmp_path), epoch=2))
    assert s2.queue.quarantined() == ["default/poison"]
    assert s2.queue._quarantine["default/poison"].attempts == attempts
    assert bindings_of(s2)["default/healthy"] == bindings_of(s1)["default/healthy"]
    # Release flows through backoff and schedules.
    assert s2.queue.release_quarantine("default/poison") == 1
    s2.schedule_all_pending(wait_backoff=True)
    assert "default/poison" in bindings_of(s2)
    assert s2.queue.quarantined() == []


def test_quarantine_release_is_journaled(tmp_path):
    j = Journal(str(tmp_path), epoch=1)
    s1 = scenario_sched()
    s1.journal = j
    s1.queue.journal = j
    plan = FaultPlan().add_rule("engine", pod="default/poison")
    plan.install_engine(s1)
    s1.add_pod(pod("poison"))
    s1.schedule_all_pending()
    s1.fault_injector = None
    assert s1.queue.release_quarantine() == 1
    s1.schedule_all_pending(wait_backoff=True)
    # Restart must NOT resurrect the pod into quarantine: the release —
    # and the subsequent bind — are both in the log.
    s2 = scenario_sched()
    recover(s2, Journal(str(tmp_path), epoch=2))
    assert s2.queue.quarantined() == []
    assert "default/poison" in bindings_of(s2)


# -- LIST reconcile ---------------------------------------------------------


def test_reconcile_rules(tmp_path):
    """The three recovery-ordering rules: journal bindings absent from
    the relist are re-applied; relist bindings win as host truth; objects
    absent from the relist are deleted."""
    j = Journal(str(tmp_path), epoch=1)
    px, py, pz = pod("x"), pod("y"), pod("z")
    for p, n in ((px, "n0"), (py, "n1"), (pz, "n2")):
        j.append(
            "bind", {"uid": p.uid, "node": n, "pod": serialize.to_dict(p)}
        )
    s = small_sched()
    for i in range(3):
        s.add_node(node(f"n{i}"))
    recover(s, j)
    assert bindings_of(s) == {px.uid: "n0", py.uid: "n1", pz.uid: "n2"}
    # Host truth: x unbound (the bind never reached the relist), y bound
    # ELSEWHERE (n2), z gone entirely.
    src_n, src_p = FakeSource(), FakeSource()
    for i in range(3):
        src_n.add(f"n{i}", node(f"n{i}"))
    src_p.add(px.uid, pod("x"))
    src_p.add(py.uid, pod("y", node="n2"))
    reconcile_after_recovery(
        s,
        Reflector(s, "Node", src_n.lister, src_n.watcher),
        Reflector(s, "Pod", src_p.lister, src_p.watcher),
    )
    got = bindings_of(s)
    assert got[px.uid] == "n0"  # journal binding re-applied
    assert got[py.uid] == "n2"  # relist won as host truth
    assert pz.uid not in got  # LIST-as-replace delete


def test_reconcile_applies_late_binding_when_node_relists(tmp_path):
    """A journal bind whose node the snapshot never held parks on
    _recovered_bindings and lands once the LIST delivers the node."""
    j = Journal(str(tmp_path), epoch=1)
    p = pod("late")
    j.append(
        "bind",
        {"uid": p.uid, "node": "n-new", "pod": serialize.to_dict(p)},
    )
    s = small_sched()  # no nodes at all pre-recovery
    stats = recover(s, j)
    assert stats["pending_bindings"] == 1
    assert bindings_of(s) == {}
    src_n, src_p = FakeSource(), FakeSource()
    src_n.add("n-new", node("n-new"))
    src_p.add(p.uid, pod("late"))
    rstats = reconcile_after_recovery(
        s,
        Reflector(s, "Node", src_n.lister, src_n.watcher),
        Reflector(s, "Pod", src_p.lister, src_p.watcher),
    )
    assert rstats["late_bindings_applied"] == 1
    assert bindings_of(s) == {p.uid: "n-new"}


# -- durable host replay store (sidecar/host.py) ----------------------------


def test_resyncing_client_store_rebuilt_from_journal(tmp_path):
    """The host's replay store survives a host kill: a fresh
    ResyncingClient(journal=...) rebuilds the mirror from durable state
    and re-ships it — including learned bindings — to the sidecar."""
    import tempfile

    from kubernetes_tpu.sidecar.host import ResyncingClient
    from kubernetes_tpu.sidecar.server import SidecarServer

    jdir = str(tmp_path / "hostj")
    with tempfile.TemporaryDirectory() as td:
        sock = os.path.join(td, "s.sock")
        srv = SidecarServer(sock, scheduler=small_sched())
        srv.serve_background()
        c1 = ResyncingClient(sock, journal=Journal(jdir, epoch=1))
        c1.add("Node", node("n0"))
        c1.add("Node", node("n1"))
        c1.add("Node", node("gone"))
        c1.add("Pod", pod("bound", cpu="1", node="n0"))
        c1.add("Pod", pod("doomed", cpu="1", node="gone"))
        results = c1.schedule(pods=[pod("w")], drain=True)
        learned = {r.pod_uid: r.node_name for r in results if r.node_name}
        assert learned
        c1.remove("Node", "gone")  # its pods vanish from the store too
        c1.close()  # host "dies" (journal already durable)
        srv.close()
        # A fresh sidecar + a fresh host process: the durable store must
        # replay the bound world (not just live-mirror memory).
        srv2 = SidecarServer(sock, scheduler=small_sched())
        srv2.serve_background()
        c2 = ResyncingClient(sock, journal=Journal(jdir, epoch=2))
        try:
            dump = c2.dump()
            assert set(dump["nodes"]) == {"n0", "n1"}  # the remove held
            assert "default/doomed" not in dump["pods"]  # died with its node
            for uid, node_name in learned.items():
                assert dump["pods"][uid]["node"] == node_name
            assert dump["pods"]["default/bound"]["node"] == "n0"
        finally:
            c2.close()
            srv2.close()


def test_host_checkpoint_covers_latest_mutation(tmp_path):
    """Checkpoint-ordering regression: a checkpoint whose seq covers the
    just-appended record must also CONTAIN its mutation — snapshotting
    before the store applied it would truncate the record into nothing
    durable.  Cadence 1 makes every mutation a checkpoint boundary."""
    import tempfile

    from kubernetes_tpu.sidecar.host import ResyncingClient
    from kubernetes_tpu.sidecar.server import SidecarServer

    jdir = str(tmp_path / "hostj")
    with tempfile.TemporaryDirectory() as td:
        sock = os.path.join(td, "s.sock")
        srv = SidecarServer(sock, scheduler=small_sched())
        srv.serve_background()
        c1 = ResyncingClient(
            sock, journal=Journal(jdir, epoch=1), journal_snapshot_every=1
        )
        c1.add("Node", node("n0"))
        results = c1.schedule(pods=[pod("w")], drain=True)
        learned = {r.pod_uid: r.node_name for r in results if r.node_name}
        assert learned == {"default/w": "n0"}
        c1.close()
        srv.close()
        # Every record was immediately checkpointed+truncated; the
        # snapshot alone must reproduce the bound store.
        j2 = Journal(jdir, epoch=2)
        snap, recs, _ = j2.replay()
        assert recs == []  # all barriers held
        pods = {p["metadata"]["name"]: p for p in snap["state"]["store"]["Pod"]}
        assert pods["w"]["spec"]["node_name"] == "n0"


# -- online compaction (bounded WAL over unbounded streams) -----------------


def _append_stream(sched, cycles: int = 12, per_cycle: int = 3):
    """An unbounded-stream stand-in: each cycle binds fresh pods and
    retires the ones bound two cycles ago (the soak driver's live-pod
    cap), so the journal sees a perpetual bind+delete append stream."""
    wal = os.path.join(sched.journal.dir, Journal.WAL)
    sizes = []
    bound_cycles: list[list[str]] = []
    for c in range(cycles):
        batch = []
        for j in range(per_cycle):
            p = pod(f"st-{c}-{j}")
            batch.append(p.uid)
            sched.add_pod(p)
        sched.schedule_all_pending()
        bound_cycles.append(batch)
        if len(bound_cycles) > 2:
            for uid in bound_cycles.pop(0):
                sched.delete_pod(uid)
        sizes.append(os.path.getsize(wal))
    return sizes


def test_wal_bounded_under_unbounded_append_stream(tmp_path):
    """Compaction guard: over a long bind+delete stream, the snapshot
    cadence keeps journal.wal bounded (truncations observed repeatedly,
    high-water mark well under the cadence-free growth) and recovery
    from the compacted state is still bit-identical."""
    # Cadence-free reference: the WAL grows monotonically.
    j_free = Journal(str(tmp_path / "free"), epoch=1)
    s_free = scenario_sched()
    s_free.attach_journal(j_free)  # no snapshot cadence
    free_sizes = _append_stream(s_free)
    assert free_sizes == sorted(free_sizes)

    # Compacted run: same stream, snapshot every 2 batches.
    j = Journal(str(tmp_path / "compact"), epoch=1)
    s1 = scenario_sched()
    s1.attach_journal(j, snapshot_every_batches=2)
    sizes = _append_stream(s1)
    assert j.truncations >= 2, "compaction must cycle during the stream"
    assert max(sizes) < 0.6 * free_sizes[-1], (
        f"WAL high-water {max(sizes)} not bounded vs cadence-free "
        f"{free_sizes[-1]}"
    )
    # The compacted journal still recovers the exact final world.
    want = bindings_of(s1)
    s2 = scenario_sched()
    recover(s2, Journal(str(tmp_path / "compact"), epoch=2))
    assert bindings_of(s2) == want


@pytest.mark.faults
@pytest.mark.parametrize("point", ["pre-snapshot", "post-truncate"])
def test_mid_compaction_sigkill_recovers_bit_identical(point):
    """The compaction cycle's own crash windows (the KILL_POINTS this PR
    added around snapshot+truncate): SIGKILL just before the checkpoint
    begins and just after the truncate lands, and assert recovery is
    bit-identical to an uninterrupted run."""
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    import tempfile

    from run_fault_matrix import _read_bindings, _spawn

    with tempfile.TemporaryDirectory() as td:
        base = os.path.join(td, "base")
        os.makedirs(base)
        assert _spawn("--kill-child", base) == 0
        baseline = _read_bindings(base)
        assert baseline
        case = os.path.join(td, "case")
        os.makedirs(case)
        rc = _spawn("--kill-child", case, kill=f"{point}:1")
        assert rc == -9, f"child survived the {point} SIGKILL (rc={rc})"
        assert _spawn("--recover-child", case) == 0
        assert _read_bindings(case) == baseline


def test_quarantine_release_history_is_trimmed():
    """The release history is a bounded ring: an unbounded release
    stream keeps only the trailing RELEASE_HISTORY_MAX entries, the
    window survives a durable_state round trip, and an over-long stored
    list trims on restore."""
    from kubernetes_tpu.queue import RELEASE_HISTORY_MAX, QueuedPodInfo

    clock = [100.0]
    q = SchedulingQueue(clock=lambda: clock[0])
    n = RELEASE_HISTORY_MAX + 44
    for i in range(n):
        p = pod(f"q-{i}")
        qp = QueuedPodInfo(
            pod=p, timestamp=clock[0], initial_attempt_timestamp=clock[0],
            attempts=i % 5,
        )
        q.quarantine(qp)
        assert q.release_quarantine(p.uid) == 1
        q.delete(p.uid)  # released pods leave; only the history remains
        clock[0] += 1.0
    assert len(q.release_history) == RELEASE_HISTORY_MAX
    uids = [e["uid"] for e in q.release_history]
    assert uids[0] == "default/q-44"  # the oldest 44 were trimmed
    assert uids[-1] == f"default/q-{n - 1}"
    # The window rides durable_state (stamps stored as ages — raw
    # monotonic clocks are meaningless in the next process) and
    # restores trimmed, rebased onto the restoring clock.
    state = q.durable_state()
    assert len(state["release_history"]) == RELEASE_HISTORY_MAX
    assert all(
        "age_s" in e and "ts" not in e for e in state["release_history"]
    )
    clock[0] += 50.0
    q2 = SchedulingQueue(clock=lambda: clock[0])
    q2.restore_state(state)
    assert [e["uid"] for e in q2.release_history] == uids
    assert all(
        abs((b["ts"] - a["ts"]) - 50.0) < 1e-6
        for a, b in zip(q.release_history, q2.release_history)
    )
    # An over-long stored list (a snapshot from a future, larger bound)
    # trims to this process's window instead of growing unboundedly.
    state["release_history"] = [
        {"uid": f"x-{i}", "attempts": 0, "ts": 0.0}
        for i in range(RELEASE_HISTORY_MAX + 100)
    ]
    q3 = SchedulingQueue(clock=lambda: clock[0])
    q3.restore_state(state)
    assert len(q3.release_history) == RELEASE_HISTORY_MAX
    assert q3.release_history[-1]["uid"] == f"x-{RELEASE_HISTORY_MAX + 99}"


# -- group commit (ISSUE 15) ------------------------------------------------


def test_group_commit_one_fsync_per_group(tmp_path):
    """Appends inside ``journal.group()`` defer their fsync to ONE
    barrier at group exit; nested groups ride the outermost barrier."""
    j = Journal(str(tmp_path), epoch=1)
    f0 = j.fsyncs
    with j.group():
        j.append("bind", {"uid": "a", "node": "n1"})
        with j.group():  # nested: no inner barrier
            j.append("bind", {"uid": "b", "node": "n2"})
        j.append("bind", {"uid": "c", "node": "n1"})
        assert j.fsyncs == f0  # nothing durable yet
    assert j.fsyncs == f0 + 1
    assert j.group_commits == 1
    assert j.group_appends == 3
    assert j.last_group_size == 3 and j.max_group_size == 3
    # An empty group costs nothing.
    with j.group():
        pass
    assert j.fsyncs == f0 + 1 and j.group_commits == 1
    # Outside a group, appends fsync immediately as before.
    j.append("delete", {"uid": "a"})
    assert j.fsyncs == f0 + 2
    # Every record is on the log (the group deferred durability only).
    _snap, records, _stats = j.replay()
    assert [r["t"] for r in records] == ["bind", "bind", "bind", "delete"]


def test_group_commit_no_apply_before_group_fsync(tmp_path):
    """The commit drain's ordering contract: every staged bind's record
    is appended, then the group's SINGLE fsync barrier returns, and only
    then does any bind apply (finish_binding) — instrumented end to end
    through a real schedule_batch."""
    events = []
    sched = small_sched(enable_preemption=False)
    journal = Journal(str(tmp_path), epoch=1)

    orig_append = journal.append

    def rec_append(rtype, data):
        events.append(("append", rtype))
        return orig_append(rtype, data)

    journal.append = rec_append
    orig_commit = journal._group_commit

    def rec_commit():
        was_outermost = journal._group_depth == 1
        had_pending = journal._group_pending > 0
        orig_commit()
        if was_outermost and had_pending:
            events.append(("group-fsync",))

    journal._group_commit = rec_commit
    sched.attach_journal(journal)
    orig_fb = sched.cache.finish_binding

    def rec_fb(uid):
        events.append(("apply", uid))
        orig_fb(uid)

    sched.cache.finish_binding = rec_fb
    for i in range(4):
        sched.add_node(node(f"gc-n{i}"))
    for i in range(6):
        sched.add_pod(pod(f"gc-p{i}"))
    out = sched.schedule_batch()
    assert sum(1 for o in out if o.node_name) == 6
    kinds = [e[0] for e in events]
    assert kinds == ["append"] * 6 + ["group-fsync"] + ["apply"] * 6, kinds
    # And the applies ran in stage order = the batch's outcome order.
    applied = [e[1] for e in events if e[0] == "apply"]
    assert applied == [o.pod.uid for o in out if o.node_name]


@pytest.mark.faults
def test_mid_pipeline_sigkill_recovers_bit_identical():
    """One pipeline crash cell end to end through the real harness: a
    SIGKILL between the group's buffered appends and the fsync barrier
    (mid-group-fsync — records written, NONE applied) must recover to
    bindings bit-identical to an uninterrupted pipelined run.
    scripts/run_fault_matrix.py --pipeline-kill sweeps all six cells."""
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    import tempfile

    from run_fault_matrix import _read_bindings, _spawn

    with tempfile.TemporaryDirectory() as td:
        base = os.path.join(td, "base")
        os.makedirs(base)
        assert _spawn("--pipeline-kill-child", base) == 0
        baseline = _read_bindings(base)
        assert baseline
        case = os.path.join(td, "case")
        os.makedirs(case)
        rc = _spawn("--pipeline-kill-child", case, kill="mid-group-fsync:1")
        assert rc == -9, f"child survived the SIGKILL point (rc={rc})"
        assert _spawn("--pipeline-recover-child", case) == 0
        assert _read_bindings(case) == baseline


# -- the crash matrix (fast subset; --kill sweeps the grid) -----------------


@pytest.mark.faults
def test_kill_matrix_fast_subset():
    """One SIGKILL case end to end through the real harness: torn-append
    (the nastiest window — half a record durable on disk) must recover
    to bit-identical bindings.  scripts/run_fault_matrix.py --kill runs
    all ten cells."""
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    import tempfile

    from run_fault_matrix import _read_bindings, _spawn

    with tempfile.TemporaryDirectory() as td:
        base = os.path.join(td, "base")
        os.makedirs(base)
        assert _spawn("--kill-child", base) == 0
        baseline = _read_bindings(base)
        assert baseline
        case = os.path.join(td, "case")
        os.makedirs(case)
        rc = _spawn("--kill-child", case, kill="torn-append:1")
        assert rc == -9, f"child survived the SIGKILL point (rc={rc})"
        assert _spawn("--recover-child", case) == 0
        assert _read_bindings(case) == baseline


def test_recover_cli_reports_bindings(tmp_path):
    """The `recover` subcommand: offline triage of a journal directory."""
    jdir = str(tmp_path / "j")
    j = Journal(jdir, epoch=1)
    s1 = scenario_sched(journal=j)  # snapshot cadence: nodes checkpointed
    s1.add_pod(pod("w1"))
    s1.schedule_all_pending()
    assert j.snapshots >= 1
    want = bindings_of(s1)
    j.close()
    proc = subprocess.run(
        [
            sys.executable, "-m", "kubernetes_tpu", "recover",
            "--journal-dir", jdir, "--batch-size", "8",
        ],
        capture_output=True,
        text=True,
        cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        timeout=300,
    )
    assert proc.returncode == 0, proc.stderr
    report = json.loads(proc.stdout[proc.stdout.index("{"):])
    # The offline recovery can't re-seat pods whose nodes only the LIST
    # would deliver; here the journal carries everything.
    assert report["bindings"] == want
    assert report["recovery"]["snapshot"] is True
    assert report["journal"]["epoch"] >= 1  # the journal lease's tenure


# -- speculative decision-cache epoch (the PR 3 roadmap gap) ----------------


def test_spec_epoch_journaled_and_recovered(tmp_path):
    """The speculative frontend's epoch is journaled on every invalidation
    and restored by recovery: a restarted frontend resumes the monotonic
    sequence (subscribers hold epoch-stamped decisions — a cold start at 0
    would violate the Push stream's monotonic-epoch contract)."""
    from kubernetes_tpu.sidecar.speculate import SpeculativeFrontend

    j = Journal(str(tmp_path), epoch=1)
    s1 = small_sched()
    s1.add_node(node("n1"))
    s1.attach_journal(j)
    f1 = SpeculativeFrontend(s1)
    assert f1.epoch == 0
    # Miss with a hinted co-pod: the hint is speculated and cached.
    f1.add_hint(pod("extra"))
    out = f1._serve_one("default/p1", lambda: pod("p1"))
    assert out.node_name == "n1"
    assert f1.cached, "the hinted pod should hold a cached decision"
    f1.invalidate()  # full rollback → epoch 1, write-ahead spec_epoch record
    f1.invalidate({"default/never-cached"})  # scoped no-op: no bump
    assert f1.epoch == 1
    j.close()

    # An in-process frontend swap (no crash) must also resume, not reset:
    # subscribers hold epoch-stamped decisions from the old frontend.
    f1b = SpeculativeFrontend(s1)
    assert f1b.epoch == 1, "re-created frontend must not re-emit epoch 0"

    # Records-only recovery (no snapshot covered the epoch record).
    j2 = Journal(str(tmp_path), epoch=2)
    s2 = small_sched()
    recover(s2, j2)
    f2 = SpeculativeFrontend(s2)
    assert f2.epoch == 1, "recovered frontend must resume the epoch"

    # Snapshot path: checkpoint with the live frontend attached, truncate
    # the log, recover again — the epoch rides the snapshot document.
    s2.add_node(node("n1"))
    s2.attach_journal(j2)
    f2.add_hint(pod("extra2"))
    f2._serve_one("default/p2", lambda: pod("p2"))
    f2.invalidate()  # epoch 2, journaled
    j2.snapshot(scheduler_state(s2))
    j2.close()
    j3 = Journal(str(tmp_path), epoch=3)
    s3 = small_sched()
    recover(s3, j3)
    assert s3._recovered_spec_epoch == 2
    f3 = SpeculativeFrontend(s3)
    assert f3.epoch == 2
    j3.close()
