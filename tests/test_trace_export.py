"""Perfetto/Chrome trace-event export (ISSUE 16 tentpole b): any flight
dump or merged fleet document renders as trace_event JSON — valid,
byte-identical on the logical timebase across same-seed runs (wall
fields stripped), golden-pinned against a committed incident dump, and
served identically over HTTP ``GET /debug/trace`` and the CLI path."""

import copy
import json
import os
import subprocess
import sys
import tempfile
import urllib.request

from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.framework import trace_export
from kubernetes_tpu.scheduler import TPUScheduler
from kubernetes_tpu.sidecar.metrics_http import ObservabilityHTTPServer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DUMP = os.path.join(
    REPO, "soak_dumps", "flight-scheduler-38208-001-node-unreachable.json"
)
SOAK_DUMP = os.path.join(REPO, "soak_dumps", "soak-flight.json")
MERGED = os.path.join(REPO, "soak_dumps", "fleet-flight-merged.json")
GOLDEN = os.path.join(REPO, "tests", "golden", "flight_trace.json")


def load(path: str) -> dict:
    with open(path, "r", encoding="utf-8") as f:
        return json.load(f)


def run_scheduler() -> TPUScheduler:
    s = TPUScheduler(batch_size=8)
    for i in range(3):
        s.add_node(
            make_node(f"n{i}").capacity({"cpu": "8", "pods": 110}).obj()
        )
    for i in range(12):
        s.add_pod(make_pod(f"p{i}").req({"cpu": "500m"}).obj())
    s.schedule_all_pending()
    return s


# -- validity ----------------------------------------------------------------


def assert_valid_trace(doc: dict) -> None:
    assert set(doc) == {"displayTimeUnit", "otherData", "traceEvents"}
    events = doc["traceEvents"]
    assert isinstance(events, list) and events
    for e in events:
        assert e["ph"] in ("M", "X", "i"), e
        assert isinstance(e["pid"], int)
        if e["ph"] == "X":
            # Wall-anchored slices may carry fractional µs; the logical
            # timebase emits pure ints (pinned below).
            assert isinstance(e["ts"], (int, float)) and e["ts"] >= 0
            assert isinstance(e["dur"], (int, float)) and e["dur"] >= 0
        if e["ph"] == "i":
            assert e["s"] == "t"
    # Every pid/tid pair used by a slice is named by metadata.
    named = {
        (e["pid"], e.get("tid"))
        for e in events
        if e["ph"] == "M" and e["name"] == "thread_name"
    }
    for e in events:
        if e["ph"] in ("X", "i"):
            assert (e["pid"], e["tid"]) in named, e


def test_live_ring_renders_valid_trace_event_json():
    doc = json.loads(trace_export.render(run_scheduler().flight.snapshot()))
    assert_valid_trace(doc)
    # The logical timebase slots on integer microseconds only.
    for e in doc["traceEvents"]:
        if e["ph"] == "X":
            assert isinstance(e["ts"], int) and isinstance(e["dur"], int)


def test_committed_dumps_render_on_both_timebases():
    for path in (DUMP, SOAK_DUMP, MERGED):
        for timebase in ("logical", "wall"):
            doc = json.loads(load_and_render(path, timebase))
            assert_valid_trace(doc)


def load_and_render(path: str, timebase: str) -> str:
    return trace_export.render(load(path), timebase=timebase)


# -- determinism -------------------------------------------------------------


def test_logical_timebase_strips_wall_fields():
    """Same records, different wall weather → byte-identical logical
    export.  The wall timebase may differ; the logical one may not."""
    doc = load(SOAK_DUMP)
    warped = copy.deepcopy(doc)
    for rec in warped["records"]:
        if "ts" in rec:
            rec["ts"] += 1234.5
        if "wall_s" in rec:
            rec["wall_s"] *= 3.0
        for phase in list(rec.get("phases") or {}):
            rec["phases"][phase] *= 2.0
    a = trace_export.render(doc, timebase="logical")
    b = trace_export.render(warped, timebase="logical")
    assert a == b
    text = json.dumps(json.loads(a))
    assert '"wall_s"' not in text and '"trace_id"' not in text


def test_two_same_seed_runs_export_byte_identical():
    a = trace_export.render(run_scheduler().flight.snapshot())
    b = trace_export.render(run_scheduler().flight.snapshot())
    assert a == b


def test_pipeline_phases_render_as_overlapping_track():
    """The PR 15 story must be visible: predispatch/drain slices land on
    their own track (tid 2) and overlap the stage tiles' span on tid 1
    within the same batch slot."""
    snap = run_scheduler().flight.snapshot()
    events = json.loads(trace_export.render(snap))["traceEvents"]
    stage = [e for e in events if e["ph"] == "X" and e.get("tid") == 1]
    pipe = [e for e in events if e["ph"] == "X" and e.get("tid") == 2]
    assert stage and pipe, "both tracks must carry slices"
    # At least one pipeline slice overlaps a stage slice in time.
    assert any(
        p["ts"] < s["ts"] + s["dur"] and s["ts"] < p["ts"] + p["dur"]
        for p in pipe
        for s in stage
    )


# -- the golden --------------------------------------------------------------


def test_golden_trace_for_committed_incident_dump():
    """tests/golden/flight_trace.json pins the exporter's bytes for one
    committed incident dump — renderer drift is a conscious regold."""
    with open(GOLDEN, "r", encoding="utf-8") as f:
        golden = f.read()
    assert golden == load_and_render(DUMP, "logical")


# -- the serving surfaces ----------------------------------------------------


def test_http_debug_trace_agrees_with_direct_render():
    sched = run_scheduler()
    srv = ObservabilityHTTPServer(scheduler=sched, port=0)
    srv.serve_background()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        body = urllib.request.urlopen(
            f"{base}/debug/trace", timeout=5
        ).read().decode()
        assert body == trace_export.render(
            sched.flight.snapshot(), timebase="logical"
        )
        limited = urllib.request.urlopen(
            f"{base}/debug/trace?limit=2", timeout=5
        ).read().decode()
        assert json.loads(limited)["traceEvents"]
    finally:
        srv.close()


def test_cli_exporter_agrees_with_http_shape():
    """scripts/export_trace.py (the file-side twin) renders the same
    bytes trace_export.render does for the same document."""
    with tempfile.TemporaryDirectory() as td:
        out = os.path.join(td, "t.json")
        proc = subprocess.run(
            [
                sys.executable,
                os.path.join(REPO, "scripts", "export_trace.py"),
                DUMP,
                "--out",
                out,
            ],
            capture_output=True,
            text=True,
            timeout=60,
        )
        assert proc.returncode == 0, proc.stderr
        with open(out, "r", encoding="utf-8") as f:
            assert f.read() == load_and_render(DUMP, "logical")
