"""Full default-profile scalar oracle: a sequential scheduler composing the
per-plugin scalar references (reference_impl.py) into end-to-end decisions —
filters → truncation → fused normalized-weighted scoring → seeded tie-break
→ greedy-reprieve preemption → nominated retry — mirroring, decision for
decision, the device engine in parity mode (chunk_size=1).

Used by tests/test_parity.py (in-process) and scripts/parity_ab.py (over
the sidecar wire) for the bit-identical-bindings A/B the north star
requires (schedule_one.go:411–920, preemption.go:148–470).

Scope: the default profile's compute plugins (unschedulable/name/taints/
node-affinity/ports/fit/spread/inter-pod-affinity + all five scorers).
Volume/DRA/gates are exercised by their own suites; fixtures here carry no
such objects, so those plugins are inactive on both sides."""

from __future__ import annotations

import heapq
import itertools
from dataclasses import dataclass, field

from kubernetes_tpu.api import types as t

from reference_impl import (
    MAX_NODE_SCORE,
    RefNodeState,
    balanced_allocation_score,
    fit_score,
    fits_request,
    ipa_filter,
    ipa_score,
    node_affinity_filter,
    node_affinity_score_raw,
    node_ports_filter,
    spread_filter,
    spread_score,
    taint_toleration_filter,
    taint_toleration_score_raw,
)
from test_parity import hash_u32, interleave_zones, num_feasible_nodes_to_find


def default_normalize(raws: dict[str, int], feasible: list[str], reverse: bool) -> dict[str, int]:
    """Scalar DefaultNormalizeScore (plugins/helper/normalize_score.go)."""
    mx = max((raws.get(n, 0) for n in feasible), default=0)
    out = {}
    for n in feasible:
        if mx == 0:
            out[n] = MAX_NODE_SCORE if reverse else 0
            continue
        s = raws.get(n, 0) * MAX_NODE_SCORE // mx
        out[n] = MAX_NODE_SCORE - s if reverse else s
    return out


@dataclass
class Decision:
    pod: t.Pod
    node: str | None
    nominated: str | None = None
    victims: tuple[str, ...] = ()


@dataclass
class _Queued:
    pod: t.Pod
    nominated: str | None = None


class FullOracleScheduler:
    """Sequential scalar scheduler over the default plugin set with the
    engine's queue/batch/preemption discipline (parity mode)."""

    def __init__(
        self,
        nodes: list[t.Node],
        pct: int | None = None,
        seed: int = 0,
        hard_pod_affinity_weight: int = 1,
        batch_size: int = 128,
        ns_labels: dict[str, dict[str, str]] | None = None,
        pdbs: list[t.PodDisruptionBudget] | None = None,
    ):
        self.nodes = list(nodes)  # row order = insertion order
        self.states = {n.name: RefNodeState(node=n) for n in nodes}
        by_zone: dict[str, list[str]] = {}
        for n in nodes:
            z = n.metadata.labels.get("topology.kubernetes.io/zone", "")
            by_zone.setdefault(z, []).append(n.name)
        self.order = interleave_zones(by_zone)
        self.pct = pct
        self.seed = seed
        self.hard_w = hard_pod_affinity_weight
        self.batch_size = batch_size
        self.ns_labels = ns_labels or {}
        self.pdbs = list(pdbs or [])
        self.start = 0
        self.step = 0
        self._seq = itertools.count()
        self._heap: list = []
        self._info: dict[str, _Queued] = {}
        # Nominator overlay: uid → (node, pod) — freed capacity a preemptor
        # claimed; other pods' fit checks count it (framework.go:973).
        self.nominator: dict[str, tuple[str, t.Pod]] = {}

    # -- cluster mutation (bound pods) --------------------------------------

    def add_bound(self, pod: t.Pod) -> None:
        self.states[pod.spec.node_name].pods.append(pod)

    # -- queue --------------------------------------------------------------

    def add(self, pod: t.Pod, nominated: str | None = None) -> None:
        q = self._info.get(pod.uid)
        if q is None:
            q = _Queued(pod=pod)
            self._info[pod.uid] = q
        q.nominated = nominated
        heapq.heappush(
            self._heap, (-pod.spec.priority, next(self._seq), pod.uid)
        )

    def _pop_batch(self) -> list[_Queued]:
        out = []
        while self._heap and len(out) < self.batch_size:
            _, _, uid = heapq.heappop(self._heap)
            q = self._info.pop(uid, None)
            if q is not None:
                out.append(q)
        return out

    # -- one scheduling cycle ----------------------------------------------

    def _pods_on(self) -> dict[str, list[t.Pod]]:
        return {name: st.pods for name, st in self.states.items()}

    def _filter(self, pod: t.Pod, exclude_uid: str | None = None) -> dict[str, bool]:
        """All filter plugins in profile order, incl. the nominator overlay
        (a nominated pod's claim counts against OTHER pods' fit)."""
        pods_on = self._pods_on()
        spread_ok = spread_filter(pod, self.nodes, pods_on)
        ipa_ok = ipa_filter(pod, self.nodes, pods_on, self.ns_labels)
        out = {}
        unsched_taint = t.Taint(
            key="node.kubernetes.io/unschedulable", effect=t.EFFECT_NO_SCHEDULE
        )
        for n in self.nodes:
            st = self.states[n.name]
            ok = not n.spec.unschedulable or any(
                tol.tolerates(unsched_taint) for tol in pod.spec.tolerations
            )
            if ok and pod.spec.node_name:
                ok = pod.spec.node_name == n.name
            ok = ok and taint_toleration_filter(pod, n)
            ok = ok and node_affinity_filter(pod, n)
            ok = ok and node_ports_filter(pod, st.pods)
            if ok:
                ok = not fits_request(pod, st)
            if ok:
                # Nominator overlay (RunFilterPluginsWithNominatedPods /
                # ops/noderesources.py): when the pod's priority ≤ the
                # node's max nominated priority, it must ALSO fit with
                # every nominated pod's claim counted (self excluded).
                overlay = [
                    p
                    for uid2, (nn, p) in self.nominator.items()
                    if nn == n.name and uid2 != (exclude_uid or "")
                ]
                if overlay and pod.spec.priority <= max(
                    p.spec.priority for p in overlay
                ):
                    st2 = RefNodeState(node=n, pods=st.pods + overlay)
                    ok = not fits_request(pod, st2)
            ok = ok and spread_ok[n.name] and ipa_ok[n.name]
            out[n.name] = ok
        return out

    def _score(self, pod: t.Pod, feasible: list[str]) -> dict[str, int]:
        pods_on = self._pods_on()
        feas_map = {n: n in feasible for n in self.states}
        taint = default_normalize(
            {n.name: taint_toleration_score_raw(pod, n) for n in self.nodes},
            feasible, reverse=True,
        )
        naff = default_normalize(
            {n.name: node_affinity_score_raw(pod, n) for n in self.nodes},
            feasible, reverse=False,
        )
        spread = spread_score(pod, self.nodes, pods_on, feas_map)
        ipa = ipa_score(
            pod, self.nodes, pods_on, feas_map, self.hard_w, self.ns_labels
        )
        total = {}
        for name in feasible:
            st = self.states[name]
            total[name] = (
                3 * taint[name]
                + 2 * naff[name]
                + 1 * fit_score(pod, st)
                + 2 * spread[name]
                + 2 * ipa[name]
                + 1 * balanced_allocation_score(pod, st)
                # ImageLocality: fixtures carry no images → inactive on the
                # engine side; a uniform 0 here never changes the argmax.
            )
        return total

    def _schedule_one(self, q: _Queued) -> Decision:
        pod = q.pod
        n_all = len(self.order)
        limit = num_feasible_nodes_to_find(self.pct, n_all)
        full = self._filter(pod, exclude_uid=pod.uid)
        feasible: list[str] = []  # rotated scan order
        processed = n_all
        for j in range(n_all):
            name = self.order[(self.start + j) % n_all]
            if not full[name]:
                continue
            if len(feasible) == limit:
                processed = j
                break
            feasible.append(name)
        tie_rand = hash_u32((self.seed * 2654435761 + self.step) & 0xFFFFFFFF)
        self.step += 1
        self.start = (self.start + processed) % n_all
        if not feasible:
            return Decision(pod=pod, node=None)
        # Nominated fast path (schedule_one.go:491–502 / engine eval_pod):
        # take the nominated node whenever it is feasible.
        if q.nominated and q.nominated in feasible:
            pick = q.nominated
        else:
            scores = self._score(pod, feasible)
            best = max(scores.values())
            ties = [n for n in feasible if scores[n] == best]
            pick = ties[tie_rand % len(ties)]
        self.states[pick].pods.append(pod)
        self.nominator.pop(pod.uid, None)
        return Decision(pod=pod, node=pick)

    # -- preemption (greedy reprieve, scalar) --------------------------------

    def _preempt(self, pod: t.Pod) -> Decision:
        if pod.spec.preemption_policy == t.PREEMPT_NEVER:
            return Decision(pod=pod, node=None)
        prio = pod.spec.priority
        pods_on = self._pods_on()

        def matched(p: t.Pod) -> list[int]:
            return [
                i
                for i, pdb in enumerate(self.pdbs)
                if pdb.namespace == p.namespace
                and t.label_selector_matches(pdb.selector, p.metadata.labels)
            ]

        candidates: list[tuple[str, list[t.Pod]]] = []
        for n in self.nodes:
            st = self.states[n.name]
            lower = [p for p in st.pods if p.spec.priority < prio]
            if not lower:
                continue
            # Release-independent filters must already pass.
            if not (
                (not n.spec.unschedulable)
                and taint_toleration_filter(pod, n)
                and node_affinity_filter(pod, n)
            ):
                continue
            keep = [p for p in st.pods if p.spec.priority >= prio]

            def ok_with(removed: list[t.Pod]) -> bool:
                trial = {
                    name: (
                        [p for p in ps if p not in removed]
                        if name == n.name
                        else ps
                    )
                    for name, ps in pods_on.items()
                }
                st2 = RefNodeState(node=n, pods=trial[n.name])
                if fits_request(pod, st2):
                    return False
                if not node_ports_filter(pod, st2.pods):
                    return False
                if not spread_filter(pod, self.nodes, trial)[n.name]:
                    return False
                if not ipa_filter(pod, self.nodes, trial, self.ns_labels)[n.name]:
                    return False
                return True

            if not ok_with(lower):
                continue
            # Violating classification with simulated budget consumption,
            # most-important-first (filterPodsWithPDBViolation).
            remaining = [max(p.disruptions_allowed, 0) for p in self.pdbs]
            viol: dict[str, bool] = {}
            for p in sorted(
                st.pods, key=lambda p: (-p.spec.priority, p.status.start_time)
            ):
                v = False
                for i in matched(p):
                    if remaining[i] > 0:
                        remaining[i] -= 1
                    else:
                        v = True
                viol[p.uid] = v
            # Greedy reprieve: violating most-important-first, then
            # non-violating most-important-first.
            victims = list(lower)
            order = sorted(
                lower,
                key=lambda p: (
                    not viol.get(p.uid, False),
                    -p.spec.priority,
                    p.status.start_time,
                ),
            )
            for p in order:
                trial_victims = [v for v in victims if v is not p]
                if ok_with(trial_victims):
                    victims = trial_victims
            if victims:
                candidates.append((n.name, victims))

        if not candidates:
            return Decision(pod=pod, node=None)

        def criteria(entry):
            name, victims = entry
            viols = 0
            rem = [max(p.disruptions_allowed, 0) for p in self.pdbs]
            cnt = [0] * len(self.pdbs)
            for p in victims:
                for i in matched(p):
                    cnt[i] += 1
            viols = sum(max(c - r, 0) for c, r in zip(cnt, rem))
            mx = max(p.spec.priority for p in victims)
            ssum = sum(p.spec.priority for p in victims)
            earliest = min(
                (p.status.start_time for p in victims if p.spec.priority == mx),
            )
            start_key = -int(earliest * 1e6)
            return (viols, mx, ssum, len(victims), start_key)

        # Lexicographic minimum; ties → lowest row index (engine argmax).
        row = {n.name: i for i, n in enumerate(self.nodes)}
        best = min(candidates, key=lambda e: (criteria(e), row[e[0]]))
        name, victims = best
        for v in victims:
            self.states[name].pods.remove(v)
            for i in matched(v):
                self.pdbs[i].disruptions_allowed -= 1
        self.nominator[pod.uid] = (name, pod)
        return Decision(
            pod=pod, node=None, nominated=name,
            victims=tuple(v.uid for v in victims),
        )

    # -- driver (mirrors schedule_batch + prefetch ordering) -----------------

    def run(self, pods: list[t.Pod], max_rounds: int = 1000) -> list[Decision]:
        for p in pods:
            self.add(p)
        decisions: list[Decision] = []
        prefetched: list[_Queued] | None = None
        for _ in range(max_rounds):
            batch = prefetched if prefetched is not None else self._pop_batch()
            prefetched = None
            if not batch:
                break
            results = [self._schedule_one(q) for q in batch]
            # The engine prefetches the NEXT batch before completing this
            # one, so this batch's preemption requeues land in batch k+2.
            nxt = self._pop_batch()
            prefetched = nxt if nxt else None
            for q, d in zip(batch, results):
                if d.node is None:
                    d = self._preempt(q.pod)
                    if d.nominated:
                        self.add(q.pod, nominated=d.nominated)
                decisions.append(d)
        return decisions


# ---------------------------------------------------------------------------
# Shared A/B fixture (tests/test_parity_default.py + scripts/parity_ab.py)
# ---------------------------------------------------------------------------

ZONE = "topology.kubernetes.io/zone"


def build_fixture(n_nodes: int = 304, n_pending: int = 120, n_tiny: int = 10):
    """Deterministic default-profile A/B fixture: heterogeneous tainted/
    labeled nodes, seeded bound pods, a pending mix exercising every
    compute plugin, and a preemption theater (tiny saturated pool + vips).
    Every non-vip pod is schedulable on first attempt, so oracle and
    engine agree on the event-free flow."""
    from kubernetes_tpu.api.wrappers import make_node, make_pod

    nodes = []
    for i in range(n_nodes):
        w = (
            make_node(f"node-{i:04d}")
            .capacity({"cpu": "8" if i % 3 else "16", "memory": "32Gi", "pods": 64})
            .zone(f"zone-{i % 4}")
            .region("r1")
        )
        if i % 7 == 0:
            w = w.taint("dedicated", "gpu", t.EFFECT_NO_SCHEDULE)
        if i % 11 == 0:
            w = w.label("disk", "ssd")
        nodes.append(w.obj())
    for i in range(n_tiny):
        nodes.append(
            make_node(f"tiny-{i}")
            .capacity({"cpu": "1", "memory": "4Gi", "pods": 8})
            .zone(f"zone-{i % 4}")
            .region("r1")
            .label("pool", "tiny")
            .obj()
        )

    bound = []
    for i in range(max(n_nodes // 8, 8)):
        bound.append(
            make_pod(f"seed-{i}")
            .req({"cpu": "500m", "memory": "1Gi"})
            .label("color", f"c{i % 8}")
            .start_time(float(i))
            .node(f"node-{(i * 13) % n_nodes:04d}")
            .obj()
        )
    for i in range(n_tiny):
        bound.append(
            make_pod(f"filler-{i}")
            .req({"cpu": "800m", "memory": "1Gi"})
            .label("app", "low")
            .priority(1)
            .start_time(100.0 + i)
            .node(f"tiny-{i}")
            .obj()
        )

    pending = []
    for i in range(n_pending):
        kind = i % 6
        w = make_pod(f"p-{i:04d}").req({"cpu": "700m", "memory": "1Gi"})
        if kind == 0:
            w = w.label("app", f"a{i % 5}")
        elif kind == 1:
            w = w.preferred_node_affinity_in(ZONE, [f"zone-{i % 4}"], weight=30)
        elif kind == 2:
            w = (
                w.toleration("dedicated", value="gpu", effect=t.EFFECT_NO_SCHEDULE)
                .preferred_node_affinity_in("disk", ["ssd"], weight=10)
            )
        elif kind == 3:
            w = w.label("color", f"c{i % 8}").preferred_pod_affinity_in(
                "color", [f"c{i % 8}"], ZONE, weight=25
            )
        elif kind == 4:
            w = w.label("anti", f"x{i}").pod_anti_affinity_in(
                "anti", [f"x{i}"], ZONE
            )
        else:
            w = w.label("app", f"s{i % 3}").spread_constraint(
                2, ZONE, t.SCHEDULE_ANYWAY, "app", [f"s{i % 3}"]
            )
        pending.append(w.obj())
    for i in range(max(n_tiny - 4, 2)):
        pending.append(
            make_pod(f"vip-{i}")
            .req({"cpu": "900m"})
            .priority(50)
            .node_affinity_in("pool", ["tiny"])
            .obj()
        )
    pdbs = [
        t.PodDisruptionBudget(
            name="low-guard",
            namespace="default",
            selector=t.LabelSelector(match_labels=(("app", "low"),)),
            disruptions_allowed=max(n_tiny - 2, 1),
        )
    ]
    return nodes, bound, pending, pdbs
