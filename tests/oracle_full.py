"""Full default-profile scalar oracle: a sequential scheduler composing the
per-plugin scalar references (reference_impl.py) into end-to-end decisions —
filters → truncation → fused normalized-weighted scoring → seeded tie-break
→ greedy-reprieve preemption → nominated retry — mirroring, decision for
decision, the device engine in parity mode (chunk_size=1).

Used by tests/test_parity.py (in-process) and scripts/parity_ab.py (over
the sidecar wire) for the bit-identical-bindings A/B the north star
requires (schedule_one.go:411–920, preemption.go:148–470).

Scope (r4): the FULL default profile — the compute plugins (unschedulable/
name/taints/node-affinity/ports/fit/spread/inter-pod-affinity + all five
scorers) AND the host-state plugins: VolumeBinding (bound PV affinity,
WFFC candidate/provisioner topology, PreBind binding with smallest-fitting
PV), VolumeZone, VolumeRestrictions (device conflicts + RWOP),
NodeVolumeLimits (CSI attach limits), DynamicResources (counted devices,
delayed allocation), and SchedulingGates (gated pods never enter the
queue).  build_fixture carries the objects that make them ACTIVE."""

from __future__ import annotations

import heapq
import itertools
from dataclasses import dataclass, field

from kubernetes_tpu.api import types as t

from reference_impl import (
    MAX_NODE_SCORE,
    RefClaims,
    RefNodeState,
    RefVolumes,
    balanced_allocation_score,
    dra_commit,
    dra_filter,
    fit_score,
    fits_request,
    ipa_filter,
    ipa_score,
    node_affinity_filter,
    node_affinity_score_raw,
    node_ports_filter,
    node_volume_limits_filter,
    spread_filter,
    spread_score,
    taint_toleration_filter,
    taint_toleration_score_raw,
    volume_binding_filter,
    volume_commit,
    volume_restrictions_filter,
    volume_zone_filter,
)
from test_parity import hash_u32, interleave_zones, num_feasible_nodes_to_find


def default_normalize(raws: dict[str, int], feasible: list[str], reverse: bool) -> dict[str, int]:
    """Scalar DefaultNormalizeScore (plugins/helper/normalize_score.go)."""
    mx = max((raws.get(n, 0) for n in feasible), default=0)
    out = {}
    for n in feasible:
        if mx == 0:
            out[n] = MAX_NODE_SCORE if reverse else 0
            continue
        s = raws.get(n, 0) * MAX_NODE_SCORE // mx
        out[n] = MAX_NODE_SCORE - s if reverse else s
    return out


@dataclass
class Decision:
    pod: t.Pod
    node: str | None
    nominated: str | None = None
    victims: tuple[str, ...] = ()


@dataclass
class _Queued:
    pod: t.Pod
    nominated: str | None = None


class FullOracleScheduler:
    """Sequential scalar scheduler over the default plugin set with the
    engine's queue/batch/preemption discipline (parity mode)."""

    def __init__(
        self,
        nodes: list[t.Node],
        pct: int | None = None,
        seed: int = 0,
        hard_pod_affinity_weight: int = 1,
        batch_size: int = 128,
        ns_labels: dict[str, dict[str, str]] | None = None,
        pdbs: list[t.PodDisruptionBudget] | None = None,
        vols: RefVolumes | None = None,
        claims: RefClaims | None = None,
    ):
        self.nodes = list(nodes)  # row order = insertion order
        self.states = {n.name: RefNodeState(node=n) for n in nodes}
        by_zone: dict[str, list[str]] = {}
        for n in nodes:
            z = n.metadata.labels.get("topology.kubernetes.io/zone", "")
            by_zone.setdefault(z, []).append(n.name)
        self.order = interleave_zones(by_zone)
        self.pct = pct
        self.seed = seed
        self.hard_w = hard_pod_affinity_weight
        self.batch_size = batch_size
        self.ns_labels = ns_labels or {}
        self.pdbs = list(pdbs or [])
        self.start = 0
        self.step = 0
        self._seq = itertools.count()
        self._heap: list = []
        self._info: dict[str, _Queued] = {}
        # Nominator overlay: uid → (node, pod) — freed capacity a preemptor
        # claimed; other pods' fit checks count it (framework.go:973).
        self.nominator: dict[str, tuple[str, t.Pod]] = {}
        self.vols = vols or RefVolumes()
        self.claims = claims or RefClaims()
        self.pvc_users: dict[str, int] = {}
        self.gated: list[t.Pod] = []

    # -- cluster mutation (bound pods) --------------------------------------

    def add_bound(self, pod: t.Pod) -> None:
        self.states[pod.spec.node_name].pods.append(pod)
        for pvc in self.vols.pod_pvcs(pod):
            if pvc is not None:
                self.pvc_users[pvc.uid] = self.pvc_users.get(pvc.uid, 0) + 1

    # -- queue --------------------------------------------------------------

    def add(self, pod: t.Pod, nominated: str | None = None) -> None:
        if pod.spec.scheduling_gates:
            # PreEnqueue: SchedulingGates parks gated pods out of every
            # queue (schedulinggates/scheduling_gates.go).
            self.gated.append(pod)
            return
        q = self._info.get(pod.uid)
        if q is None:
            q = _Queued(pod=pod)
            self._info[pod.uid] = q
        q.nominated = nominated
        heapq.heappush(
            self._heap, (-pod.spec.priority, next(self._seq), pod.uid)
        )

    def _pop_batch(self) -> list[_Queued]:
        out = []
        while self._heap and len(out) < self.batch_size:
            _, _, uid = heapq.heappop(self._heap)
            q = self._info.pop(uid, None)
            if q is not None:
                out.append(q)
        return out

    # -- one scheduling cycle ----------------------------------------------

    def _pods_on(self) -> dict[str, list[t.Pod]]:
        return {name: st.pods for name, st in self.states.items()}

    def _filter(self, pod: t.Pod, exclude_uid: str | None = None) -> dict[str, bool]:
        """All filter plugins in profile order, incl. the nominator overlay
        (a nominated pod's claim counts against OTHER pods' fit)."""
        pods_on = self._pods_on()
        spread_ok = spread_filter(pod, self.nodes, pods_on)
        ipa_ok = ipa_filter(pod, self.nodes, pods_on, self.ns_labels)
        out = {}
        unsched_taint = t.Taint(
            key="node.kubernetes.io/unschedulable", effect=t.EFFECT_NO_SCHEDULE
        )
        for n in self.nodes:
            st = self.states[n.name]
            ok = not n.spec.unschedulable or any(
                tol.tolerates(unsched_taint) for tol in pod.spec.tolerations
            )
            if ok and pod.spec.node_name:
                ok = pod.spec.node_name == n.name
            ok = ok and taint_toleration_filter(pod, n)
            ok = ok and node_affinity_filter(pod, n)
            ok = ok and node_ports_filter(pod, st.pods)
            if ok:
                ok = not fits_request(pod, st)
            if ok:
                # Nominator overlay (RunFilterPluginsWithNominatedPods /
                # ops/noderesources.py): when the pod's priority ≤ the
                # node's max nominated priority, it must ALSO fit with
                # every nominated pod's claim counted (self excluded).
                overlay = [
                    p
                    for uid2, (nn, p) in self.nominator.items()
                    if nn == n.name and uid2 != (exclude_uid or "")
                ]
                if overlay and pod.spec.priority <= max(
                    p.spec.priority for p in overlay
                ):
                    st2 = RefNodeState(node=n, pods=st.pods + overlay)
                    ok = not fits_request(pod, st2)
            ok = ok and spread_ok[n.name] and ipa_ok[n.name]
            # Host-state plugins (volume quartet + DRA).
            ok = ok and volume_restrictions_filter(
                pod, st.pods, self.vols, self.pvc_users
            )
            ok = ok and node_volume_limits_filter(pod, n, st.pods, self.vols)
            ok = ok and volume_binding_filter(pod, n, self.vols)
            ok = ok and volume_zone_filter(pod, n, self.vols)
            ok = ok and dra_filter(pod, n, self.claims)
            out[n.name] = ok
        return out

    def _score(self, pod: t.Pod, feasible: list[str]) -> dict[str, int]:
        pods_on = self._pods_on()
        feas_map = {n: n in feasible for n in self.states}
        taint = default_normalize(
            {n.name: taint_toleration_score_raw(pod, n) for n in self.nodes},
            feasible, reverse=True,
        )
        naff = default_normalize(
            {n.name: node_affinity_score_raw(pod, n) for n in self.nodes},
            feasible, reverse=False,
        )
        spread = spread_score(pod, self.nodes, pods_on, feas_map)
        ipa = ipa_score(
            pod, self.nodes, pods_on, feas_map, self.hard_w, self.ns_labels
        )
        total = {}
        for name in feasible:
            st = self.states[name]
            total[name] = (
                3 * taint[name]
                + 2 * naff[name]
                + 1 * fit_score(pod, st)
                + 2 * spread[name]
                + 2 * ipa[name]
                + 1 * balanced_allocation_score(pod, st)
                # ImageLocality: fixtures carry no images → inactive on the
                # engine side; a uniform 0 here never changes the argmax.
            )
        return total

    def _schedule_one(self, q: _Queued) -> Decision:
        pod = q.pod
        n_all = len(self.order)
        limit = num_feasible_nodes_to_find(self.pct, n_all)
        full = self._filter(pod, exclude_uid=pod.uid)
        feasible: list[str] = []  # rotated scan order
        processed = n_all
        for j in range(n_all):
            name = self.order[(self.start + j) % n_all]
            if not full[name]:
                continue
            if len(feasible) == limit:
                processed = j
                break
            feasible.append(name)
        tie_rand = hash_u32((self.seed * 2654435761 + self.step) & 0xFFFFFFFF)
        self.step += 1
        self.start = (self.start + processed) % n_all
        if not feasible:
            return Decision(pod=pod, node=None)
        # Nominated fast path (schedule_one.go:491–502 / engine eval_pod):
        # take the nominated node whenever it is feasible.
        if q.nominated and q.nominated in feasible:
            pick = q.nominated
        else:
            scores = self._score(pod, feasible)
            best = max(scores.values())
            ties = [n for n in feasible if scores[n] == best]
            pick = ties[tie_rand % len(ties)]
        self.states[pick].pods.append(pod)
        self.nominator.pop(pod.uid, None)
        # Reserve/PreBind: bind delayed volumes + allocate claims on the
        # chosen node (volume_binding.go:521; dynamicresources PreBind).
        volume_commit(pod, self.states[pick].node, self.vols, self.pvc_users)
        dra_commit(pod, pick, self.claims)
        return Decision(pod=pod, node=pick)

    # -- preemption (greedy reprieve, scalar) --------------------------------

    def _preempt(self, pod: t.Pod) -> Decision:
        if pod.spec.preemption_policy == t.PREEMPT_NEVER:
            return Decision(pod=pod, node=None)
        prio = pod.spec.priority
        pods_on = self._pods_on()

        def matched(p: t.Pod) -> list[int]:
            return [
                i
                for i, pdb in enumerate(self.pdbs)
                if pdb.namespace == p.namespace
                and t.label_selector_matches(pdb.selector, p.metadata.labels)
            ]

        candidates: list[tuple[str, list[t.Pod]]] = []
        for n in self.nodes:
            st = self.states[n.name]
            lower = [p for p in st.pods if p.spec.priority < prio]
            if not lower:
                continue
            # Release-independent filters must already pass (VolumeBinding
            # and VolumeZone are invariant under pod removal — evicting
            # moves no volume; build_preempt_pass treats them the same).
            if not (
                (not n.spec.unschedulable)
                and taint_toleration_filter(pod, n)
                and node_affinity_filter(pod, n)
                and volume_binding_filter(pod, n, self.vols)
                and volume_zone_filter(pod, n, self.vols)
            ):
                continue
            # DRA hard candidacy: a missing claim or a claim pinned to
            # another node is unresolvable by eviction; a device SHORTAGE
            # is resolvable but skips the reprieve (every lower-priority
            # pod goes; the retry validates against post-eviction truth —
            # preemption.py _RELEASE_DEPENDENT/resolvable_ops).
            dra_hard_ok = True
            for claim in self.claims.pod_claims(pod):
                if claim is None or (
                    claim.allocated_node and claim.allocated_node != n.name
                ):
                    dra_hard_ok = False
                    break
            if not dra_hard_ok:
                continue
            # RWOP exclusivity is the engine's remaining evict-all route
            # (preemption.py divergences): a blocked preemptor skips the
            # reprieve; everything else — device conflicts, CSI attach
            # counts, DRA device shortage — releases in the what-if (r5).
            res_fail = any(
                pvc is not None
                and t.RWOP in pvc.access_modes
                and self.pvc_users.get(pvc.uid, 0) > 0
                for pvc in self.vols.pod_pvcs(pod)
            )
            keep = [p for p in st.pods if p.spec.priority >= prio]

            def dra_filter_trial(removed: list[t.Pod]) -> bool:
                """dra_filter with the victims' claim charges released:
                a claim frees its devices on n exactly when evicting the
                removed set would empty its reservations — the same
                reserved_for rule the eviction code below applies, so the
                what-if and post-eviction truth agree (review finding:
                a claim co-reserved by an external consumer never
                releases)."""
                removed_uids = {p.uid for p in removed}
                released: dict[str, int] = {}
                seen: set[str] = set()
                for p in removed:
                    for claim in self.claims.pod_claims(p):
                        if (
                            claim is None
                            or claim.uid in seen
                            or claim.allocated_node != n.name
                            or not set(claim.reserved_for) <= removed_uids
                        ):
                            continue
                        seen.add(claim.uid)
                        released[claim.device_class] = (
                            released.get(claim.device_class, 0) + claim.count
                        )
                need: dict[str, int] = {}
                for claim in self.claims.pod_claims(pod):
                    if claim is None:
                        return False
                    if claim.allocated_node:
                        if claim.allocated_node != n.name:
                            return False
                        continue
                    need[claim.device_class] = (
                        need.get(claim.device_class, 0) + claim.count
                    )
                for cls, cnt in need.items():
                    if self.claims.free(n.name, cls) + released.get(cls, 0) < cnt:
                        return False
                return True

            def ok_with(removed: list[t.Pod]) -> bool:
                trial = {
                    name: (
                        [p for p in ps if p not in removed]
                        if name == n.name
                        else ps
                    )
                    for name, ps in pods_on.items()
                }
                st2 = RefNodeState(node=n, pods=trial[n.name])
                if fits_request(pod, st2):
                    return False
                if not node_ports_filter(pod, st2.pods):
                    return False
                if not spread_filter(pod, self.nodes, trial)[n.name]:
                    return False
                if not ipa_filter(pod, self.nodes, trial, self.ns_labels)[n.name]:
                    return False
                # Volume/DRA releases (r5): the trial pod set drives the
                # device-conflict and attach-count checks directly; DRA
                # uses the claim-crossing release above.  The RWOP check
                # is excluded here (empty user map) exactly like the
                # engine's what-if forces vr_rwop_ok — the res_fail
                # evict-all route owns RWOP semantics.
                if not volume_restrictions_filter(
                    pod, st2.pods, self.vols, {}
                ):
                    return False
                if not node_volume_limits_filter(pod, n, st2.pods, self.vols):
                    return False
                if not dra_filter_trial(removed):
                    return False
                return True

            if not ok_with(lower):
                continue
            # Violating classification with simulated budget consumption,
            # most-important-first (filterPodsWithPDBViolation).
            remaining = [max(p.disruptions_allowed, 0) for p in self.pdbs]
            viol: dict[str, bool] = {}
            for p in sorted(
                st.pods, key=lambda p: (-p.spec.priority, p.status.start_time)
            ):
                v = False
                for i in matched(p):
                    if remaining[i] > 0:
                        remaining[i] -= 1
                    else:
                        v = True
                viol[p.uid] = v
            # Greedy reprieve: violating most-important-first, then
            # non-violating most-important-first.  Nodes whose failure
            # includes an unsimulated-resolvable op (DRA shortage) skip
            # reprieve: every lower-priority pod goes.
            victims = list(lower)
            if not res_fail:
                order = sorted(
                    lower,
                    key=lambda p: (
                        not viol.get(p.uid, False),
                        -p.spec.priority,
                        p.status.start_time,
                    ),
                )
                for p in order:
                    trial_victims = [v for v in victims if v is not p]
                    if ok_with(trial_victims):
                        victims = trial_victims
            if victims:
                candidates.append((n.name, victims))

        if not candidates:
            return Decision(pod=pod, node=None)

        def criteria(entry):
            name, victims = entry
            viols = 0
            rem = [max(p.disruptions_allowed, 0) for p in self.pdbs]
            cnt = [0] * len(self.pdbs)
            for p in victims:
                for i in matched(p):
                    cnt[i] += 1
            viols = sum(max(c - r, 0) for c, r in zip(cnt, rem))
            mx = max(p.spec.priority for p in victims)
            ssum = sum(p.spec.priority for p in victims)
            earliest = min(
                (p.status.start_time for p in victims if p.spec.priority == mx),
            )
            start_key = -int(earliest * 1e6)
            return (viols, mx, ssum, len(victims), start_key)

        # Lexicographic minimum; ties → lowest row index (engine argmax).
        row = {n.name: i for i, n in enumerate(self.nodes)}
        best = min(candidates, key=lambda e: (criteria(e), row[e[0]]))
        name, victims = best
        for v in victims:
            self.states[name].pods.remove(v)
            for i in matched(v):
                self.pdbs[i].disruptions_allowed -= 1
            # The engine's delete_pod releases the victim's claim
            # reservations (the DRA claim-release control loop: a claim
            # deallocates when its last reserver goes) and its RWOP usage
            # counts — the retry validates against post-eviction truth on
            # both sides.
            for claim in self.claims.pod_claims(v):
                if claim is None:
                    continue
                claim.reserved_for = tuple(
                    u for u in claim.reserved_for if u != v.uid
                )
                if claim.allocated_node and not claim.reserved_for:
                    key = (claim.allocated_node, claim.device_class)
                    self.claims.allocated[key] = (
                        self.claims.allocated.get(key, 0) - claim.count
                    )
                    claim.allocated_node = ""
            for pvc in self.vols.pod_pvcs(v):
                if pvc is not None and self.pvc_users.get(pvc.uid):
                    self.pvc_users[pvc.uid] -= 1
        self.nominator[pod.uid] = (name, pod)
        return Decision(
            pod=pod, node=None, nominated=name,
            victims=tuple(v.uid for v in victims),
        )

    # -- driver (mirrors schedule_batch + prefetch ordering) -----------------

    def run(
        self, pods: list[t.Pod], max_rounds: int = 1000,
        prefetch: bool = True,
    ) -> list[Decision]:
        """``prefetch`` mirrors the engine's featurize-overlap: when on,
        this batch's preemption requeues land in batch k+2.  The engine
        gates prefetch OFF for batches whose active ops read mutable host
        catalogs (VolumeBinding/DynamicResources — scheduler.py
        _batch_traced), so full-surface fixtures run both sides with
        prefetch=False (and the engine pinned off) for a deterministic
        alignment."""
        for p in pods:
            self.add(p)
        decisions: list[Decision] = []
        prefetched: list[_Queued] | None = None
        for _ in range(max_rounds):
            batch = prefetched if prefetched is not None else self._pop_batch()
            prefetched = None
            if not batch:
                break
            results = [self._schedule_one(q) for q in batch]
            nxt = self._pop_batch() if prefetch else []
            prefetched = nxt if nxt else None
            for q, d in zip(batch, results):
                if d.node is None:
                    d = self._preempt(q.pod)
                    if d.nominated:
                        self.add(q.pod, nominated=d.nominated)
                decisions.append(d)
        return decisions


# ---------------------------------------------------------------------------
# Shared A/B fixture (tests/test_parity_default.py + scripts/parity_ab.py)
# ---------------------------------------------------------------------------

ZONE = "topology.kubernetes.io/zone"


def build_fixture(n_nodes: int = 304, n_pending: int = 120, n_tiny: int = 10,
                  volumes: bool = False):
    """Deterministic default-profile A/B fixture: heterogeneous tainted/
    labeled nodes, seeded bound pods, a pending mix exercising every
    compute plugin, and a preemption theater (tiny saturated pool + vips).
    Every non-vip pod is schedulable on first attempt, so oracle and
    engine agree on the event-free flow.

    ``volumes=True`` (r4) adds the host-state surface: bound-PV pods
    (VolumeBinding affinity + VolumeZone), WFFC static PVs with forced
    smallest-fitting choice, dynamically provisioned claims under
    allowedTopologies, CSI attach limits, an RWOP contention pair,
    counted-device DRA claims (incl. one missing claim), and gated pods.
    Returns (nodes, bound, pending, pdbs, objects) where ``objects`` is
    the extra-object dict (empty when volumes=False)."""
    from kubernetes_tpu.api.wrappers import make_node, make_pod, make_pv, make_pvc

    nodes = []
    for i in range(n_nodes):
        w = (
            make_node(f"node-{i:04d}")
            .capacity({"cpu": "8" if i % 3 else "16", "memory": "32Gi", "pods": 64})
            .zone(f"zone-{i % 4}")
            .region("r1")
        )
        if i % 7 == 0:
            w = w.taint("dedicated", "gpu", t.EFFECT_NO_SCHEDULE)
        if i % 11 == 0:
            w = w.label("disk", "ssd")
        nodes.append(w.obj())
    for i in range(n_tiny):
        nodes.append(
            make_node(f"tiny-{i}")
            .capacity({"cpu": "1", "memory": "4Gi", "pods": 8})
            .zone(f"zone-{i % 4}")
            .region("r1")
            .label("pool", "tiny")
            .obj()
        )

    bound = []
    for i in range(max(n_nodes // 8, 8)):
        bound.append(
            make_pod(f"seed-{i}")
            .req({"cpu": "500m", "memory": "1Gi"})
            .label("color", f"c{i % 8}")
            .start_time(float(i))
            .node(f"node-{(i * 13) % n_nodes:04d}")
            .obj()
        )
    for i in range(n_tiny):
        bound.append(
            make_pod(f"filler-{i}")
            .req({"cpu": "800m", "memory": "1Gi"})
            .label("app", "low")
            .priority(1)
            .start_time(100.0 + i)
            .node(f"tiny-{i}")
            .obj()
        )

    pending = []
    for i in range(n_pending):
        kind = i % 6
        w = make_pod(f"p-{i:04d}").req({"cpu": "700m", "memory": "1Gi"})
        if kind == 0:
            w = w.label("app", f"a{i % 5}")
        elif kind == 1:
            w = w.preferred_node_affinity_in(ZONE, [f"zone-{i % 4}"], weight=30)
        elif kind == 2:
            w = (
                w.toleration("dedicated", value="gpu", effect=t.EFFECT_NO_SCHEDULE)
                .preferred_node_affinity_in("disk", ["ssd"], weight=10)
            )
        elif kind == 3:
            w = w.label("color", f"c{i % 8}").preferred_pod_affinity_in(
                "color", [f"c{i % 8}"], ZONE, weight=25
            )
        elif kind == 4:
            w = w.label("anti", f"x{i}").pod_anti_affinity_in(
                "anti", [f"x{i}"], ZONE
            )
        else:
            w = w.label("app", f"s{i % 3}").spread_constraint(
                2, ZONE, t.SCHEDULE_ANYWAY, "app", [f"s{i % 3}"]
            )
        pending.append(w.obj())
    for i in range(max(n_tiny - 4, 2)):
        pending.append(
            make_pod(f"vip-{i}")
            .req({"cpu": "900m"})
            .priority(50)
            .node_affinity_in("pool", ["tiny"])
            .obj()
        )
    pdbs = [
        t.PodDisruptionBudget(
            name="low-guard",
            namespace="default",
            selector=t.LabelSelector(match_labels=(("app", "low"),)),
            disruptions_allowed=max(n_tiny - 2, 1),
        )
    ]
    objects: dict = {}
    if volumes:
        classes = [
            # One static class per WFFC claim: candidate sets don't overlap,
            # so no same-batch PV race (the engine resolves races by
            # reserve-failure + retry — covered in test_volumes — which a
            # sequential oracle cannot mirror step-for-step).
            *[
                t.StorageClass(
                    name=f"sc-static-{i}",
                    provisioner="kubernetes.io/no-provisioner",
                    binding_mode=t.BINDING_WAIT_FOR_FIRST_CONSUMER,
                )
                for i in range(4)
            ],
            t.StorageClass(
                name="sc-dyn", provisioner="csi.example.com",
                binding_mode=t.BINDING_WAIT_FOR_FIRST_CONSUMER,
                allowed_topologies=t.NodeSelector(terms=(
                    t.NodeSelectorTerm(match_expressions=(
                        t.NodeSelectorRequirement(
                            ZONE, t.OP_IN, ("zone-0", "zone-1")
                        ),
                    )),
                )),
            ),
        ]
        pvs, pvcs = [], []
        # Bound-PV pods: PV pinned to one zone via node affinity AND zone
        # labels (VolumeBinding + VolumeZone both constrain).
        for i in range(6):
            z = f"zone-{i % 4}"
            pvs.append(make_pv(f"pv-bound-{i}", capacity="8Gi",
                               zone=z, node_affinity_zone=[z]))
            pvcs.append(make_pvc(f"bpvc-{i}", volume_name=f"pv-bound-{i}"))
            pvs[-1].claim_ref = f"default/bpvc-{i}"
        # WFFC static pool: distinct capacities force the smallest-fitting
        # choice (FindMatchingVolume) deterministically on both sides.
        for i in range(4):
            pvs.append(make_pv(f"pv-wffc-{i}", capacity=f"{2 + i}Gi",
                               storage_class=f"sc-static-{i}",
                               node_affinity_zone=[f"zone-{i % 4}"]))
            pvcs.append(make_pvc(f"wpvc-{i}", storage_class=f"sc-static-{i}",
                                 request=f"{2 + i}Gi"))
        # Dynamic provisioning under allowedTopologies (zone-0/1 only).
        for i in range(4):
            pvcs.append(make_pvc(f"dpvc-{i}", storage_class="sc-dyn",
                                 request="1Gi"))
        # RWOP contention: two pods want the same single-writer claim.
        pvs.append(make_pv("pv-rwop", capacity="4Gi",
                           access_modes=(t.RWOP,)))
        pvcs.append(make_pvc("rwop-claim", volume_name="pv-rwop",
                             access_modes=(t.RWOP,)))
        pvs[-1].claim_ref = "default/rwop-claim"
        # CSI attach limits on the ssd nodes (driver = sc-dyn provisioner).
        csinodes = [
            t.CSINode(name=f"node-{i:04d}", driver_limits={"csi.example.com": 2})
            for i in range(0, n_nodes, 11)
        ]
        # DRA: gpu devices on the first 8 nodes, 2 each; 6 one-device
        # claims (fits), plus a pod referencing a claim that doesn't exist.
        slices = [
            t.ResourceSlice(node_name=f"node-{i:04d}", device_class="gpu", count=2)
            for i in range(8)
        ]
        dclaims = [
            t.ResourceClaim(name=f"gclaim-{i}", device_class="gpu", count=1)
            for i in range(6)
        ]
        vol_pending = []
        for i in range(6):
            vol_pending.append(
                make_pod(f"vb-{i}").req({"cpu": "200m"}).pvc_volume(f"bpvc-{i}").obj()
            )
        for i in range(4):
            vol_pending.append(
                make_pod(f"vw-{i}").req({"cpu": "200m"}).pvc_volume(f"wpvc-{i}").obj()
            )
        for i in range(4):
            # ssd affinity makes the CSI attach limit BITE (only the ssd
            # nodes carry CSINode records).
            vol_pending.append(
                make_pod(f"vd-{i}").req({"cpu": "200m"})
                .node_affinity_in("disk", ["ssd"])
                .pvc_volume(f"dpvc-{i}").obj()
            )
        # rw-a gets priority so it pops (and commits) in an EARLIER batch
        # than rw-b: featurization is batch-wide, so the loser must be
        # featurized after the winner's PreBind bumped the RWOP use count.
        vol_pending.append(
            make_pod("rw-a").req({"cpu": "100m"}).priority(5)
            .pvc_volume("rwop-claim").obj()
        )
        vol_pending.append(
            make_pod("rw-b").req({"cpu": "100m"}).pvc_volume("rwop-claim").obj()
        )
        for i in range(6):
            vol_pending.append(
                make_pod(f"dra-{i}").req({"cpu": "100m"})
                .resource_claim(f"gclaim-{i}").obj()
            )
        vol_pending.append(
            make_pod("dra-missing").req({"cpu": "100m"})
            .resource_claim("no-such-claim").obj()
        )
        gated = [
            make_pod(f"gated-{i}").req({"cpu": "100m"})
            .scheduling_gate("example.com/hold").obj()
            for i in range(2)
        ]
        # Volume/DRA preemption theater (r5): nodes feasible ONLY via a
        # volume/DRA victim, with a same-priority bystander that must
        # REPRIEVE — pins the what-if's released volume/DRA tensors (the
        # old evict-all route would take the bystander too).
        nodes.append(
            make_node("volpre-0")
            .capacity({"cpu": "64", "memory": "64Gi", "pods": 64})
            .zone("zone-0").region("r1").label("pool", "volpre").obj()
        )
        bound.append(
            make_pod("vpre-holder").req({"cpu": "500m"}).priority(1)
            .label("kind", "holder").start_time(300.0)
            .device_volume("shared-disk-0").node("volpre-0").obj()
        )
        bound.append(
            make_pod("vpre-bystander").req({"cpu": "500m"}).priority(1)
            .label("kind", "bystander").start_time(301.0)
            .node("volpre-0").obj()
        )
        vol_pending.append(
            make_pod("vip-vol").req({"cpu": "500m"}).priority(50)
            .node_affinity_in("pool", ["volpre"])
            .device_volume("shared-disk-0").obj()
        )
        nodes.append(
            make_node("drapre-0")
            .capacity({"cpu": "64", "memory": "64Gi", "pods": 64})
            .zone("zone-1").region("r1").label("pool", "drapre").obj()
        )
        slices.append(
            t.ResourceSlice(node_name="drapre-0", device_class="pgpu", count=1)
        )
        held = t.ResourceClaim(
            name="dheld", device_class="pgpu", count=1,
            allocated_node="drapre-0",
            reserved_for=("default/dpre-holder",),
        )
        dclaims.append(held)
        dclaims.append(t.ResourceClaim(name="dwant", device_class="pgpu", count=1))
        bound.append(
            make_pod("dpre-holder").req({"cpu": "500m"}).priority(1)
            .label("kind", "holder").start_time(302.0)
            .resource_claim("dheld").node("drapre-0").obj()
        )
        bound.append(
            make_pod("dpre-bystander").req({"cpu": "500m"}).priority(1)
            .label("kind", "bystander").start_time(303.0)
            .node("drapre-0").obj()
        )
        vol_pending.append(
            make_pod("vip-dra").req({"cpu": "500m"}).priority(50)
            .node_affinity_in("pool", ["drapre"])
            .resource_claim("dwant").obj()
        )
        pending = pending + vol_pending + gated
        objects = dict(
            classes=classes, pvs=pvs, pvcs=pvcs, csinodes=csinodes,
            slices=slices, dclaims=dclaims,
            gated_uids={p.uid for p in gated},
        )
    return nodes, bound, pending, pdbs, objects
