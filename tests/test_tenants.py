"""Per-tenant SLO attribution + federated fleet observability
(ISSUE 12): the bounded tenant labeler, tenant counters end-to-end
(queue admission → bind → preemption/deferral), frame-vs-HTTP agreement
for the tenant-labeled families, the joined router→owner→sidecar trace
tree, and the federated flight merge (deterministic timeline,
overlap/critical-path attribution)."""

import json
import re
import tempfile
import urllib.request
import zlib

from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.framework.fairness import (
    FairAdmission,
    weights_from_matrix,
)
from kubernetes_tpu.framework.flight import FlightRecorder, merge_fleet
from kubernetes_tpu.framework.measured import matrix_rows
from kubernetes_tpu.framework.metrics import (
    TENANT_FALLBACK,
    TENANT_LABEL_KEY,
    MetricsRegistry,
    TenantLabeler,
    TenantMetrics,
    pod_tenant,
)
from kubernetes_tpu.framework.tracing import stitch_spans
from kubernetes_tpu.framework.config import Profile
from kubernetes_tpu.loadgen.workloads import WorkloadMix
from kubernetes_tpu.fleet import FleetRouter, ShardMap, ShardOwner
from kubernetes_tpu.ops.throughput import DEFAULT_THROUGHPUT_MATRIX
from kubernetes_tpu.queue import SchedulingQueue
from kubernetes_tpu.scheduler import TPUScheduler
from kubernetes_tpu.sidecar import SidecarClient, SidecarServer


def tenant_pod(name: str, tenant: str, cpu: str = "1"):
    return (
        make_pod(name).req({"cpu": cpu}).label(TENANT_LABEL_KEY, tenant).obj()
    )


# -- the bounded labeler -----------------------------------------------------


def test_tenant_labeler_bounds_cardinality():
    lab = TenantLabeler(limit=2)
    assert lab.label_for("a") == "a"
    assert lab.label_for("b") == "b"
    # Over the cap: collapses into the fallback cell, counted.
    assert lab.label_for("c") == TENANT_FALLBACK
    assert lab.label_for(None) == TENANT_FALLBACK
    assert lab.label_for("") == TENANT_FALLBACK
    # Known tenants keep answering by name.
    assert lab.label_for("a") == "a"
    assert lab.overflowed == 1
    assert lab.known() == ["a", "b"]


def test_tenant_metrics_snapshot_shape():
    reg = MetricsRegistry()
    tm = TenantMetrics(reg, limit=4)
    tm.note("admitted", "team-a")
    tm.note("admitted", "team-a")
    tm.note("bound", "team-a")
    tm.note("deferred", None)
    snap = tm.snapshot()
    assert snap["team-a"] == {"admitted": 2.0, "bound": 1.0}
    assert snap[TENANT_FALLBACK] == {"deferred": 1.0}
    # The families render under the scheduler_ namespace.
    text = reg.render_text()
    assert 'scheduler_tenant_admitted_total{tenant="team-a"} 2' in text


# -- the workload generator --------------------------------------------------


def test_workload_mix_tenant_draw_is_deterministic():
    a = WorkloadMix("basic", seed=7, tenants=(("t1", 0.5), ("t2", 0.5)))
    b = WorkloadMix("basic", seed=7, tenants=(("t1", 0.5), ("t2", 0.5)))
    ta = [pod_tenant(a.pod(i)) for i in range(40)]
    tb = [pod_tenant(b.pod(i)) for i in range(40)]
    assert ta == tb
    assert set(ta) == {"t1", "t2"}
    # The explicit override (per-tenant arrival streams) wins.
    assert pod_tenant(a.pod(100, tenant="forced")) == "forced"
    # Tenants ride their own seeded stream: the template draw sequence
    # is identical with tenants off.
    c = WorkloadMix("mixed", seed=9)
    d = WorkloadMix("mixed", seed=9, tenants=(("x", 1.0),))
    for i in range(30):
        c.pod(i)
        d.pod(i)
    assert c.counts == d.counts


# -- scheduler-side counters -------------------------------------------------


def test_scheduler_tenant_counters_end_to_end():
    sched = TPUScheduler(batch_size=16)
    sched.add_node(
        make_node("n1").capacity(
            {"cpu": "4", "memory": "16Gi", "pods": 10}
        ).obj()
    )
    sched.add_pod(tenant_pod("p1", "team-a"))
    sched.add_pod(tenant_pod("p2", "team-b"))
    # Infeasible: defers to the unschedulable pool.
    sched.add_pod(tenant_pod("p3", "team-b", cpu="64"))
    sched.schedule_all_pending()
    snap = sched.tenant_metrics.snapshot()
    assert snap["team-a"]["admitted"] == 1
    assert snap["team-a"]["bound"] == 1
    assert snap["team-b"]["admitted"] == 2
    assert snap["team-b"]["bound"] == 1
    assert snap["team-b"]["deferred"] >= 1
    # Attribution off: no tenant machinery at all, decisions unchanged.
    off = TPUScheduler(batch_size=16, tenant_attribution=False)
    assert off.tenant_metrics is None


def test_tenant_families_frame_and_http_agree():
    path = tempfile.mktemp(suffix=".sock")
    srv = SidecarServer(
        path, scheduler=TPUScheduler(batch_size=16), http_port=0
    )
    srv.serve_background()
    try:
        client = SidecarClient(path)
        client.add(
            "Node",
            make_node("n1")
            .capacity({"cpu": "8", "memory": "16Gi", "pods": 110})
            .obj(),
        )
        res = client.schedule(
            [tenant_pod("p1", "team-a"), tenant_pod("p2", "team-b")]
        )
        assert all(r.node_name for r in res)
        pat = re.compile(r"^scheduler_tenant_.*$", re.M)
        frame_lines = sorted(pat.findall(client.metrics()))
        http_text = urllib.request.urlopen(
            f"http://127.0.0.1:{srv.http.port}/metrics", timeout=5
        ).read().decode()
        http_lines = sorted(pat.findall(http_text))
        assert frame_lines == http_lines
        assert (
            'scheduler_tenant_bound_total{tenant="team-a"} 1' in frame_lines
        )
        assert (
            'scheduler_tenant_admitted_total{tenant="team-b"} 1'
            in frame_lines
        )
        client.close()
    finally:
        srv.close()


# -- the fleet: aggregation + joined traces ----------------------------------


def mk_sched() -> TPUScheduler:
    return TPUScheduler(
        profile=Profile(
            name="tenant-test",
            filters=(
                "NodeUnschedulable", "NodeName", "NodeAffinity",
                "NodeResourcesFit",
            ),
            scorers=(("NodeResourcesFit", 1),),
        ),
        batch_size=8,
        chunk_size=1,
    )


def build_fleet(n_shards: int = 2):
    smap = ShardMap(n_shards=n_shards, n_buckets=16)
    owners = {k: ShardOwner(k, mk_sched(), smap) for k in range(n_shards)}
    router = FleetRouter(owners, smap, batch_size=8)
    router.profile_filters = tuple(owners[0].sched.profile.filters)
    for i in range(6):
        router.add_object(
            "Node",
            make_node(f"an{i}")
            .capacity({"cpu": str(4 + i), "memory": "16Gi", "pods": 64})
            .obj(),
        )
    return router, owners, smap


def test_router_aggregates_and_owners_split_tenants():
    router, owners, _smap = build_fleet(2)
    for i in range(4):
        router.add_pod(tenant_pod(f"a{i}", "team-a", cpu="200m"))
    for i in range(2):
        router.add_pod(tenant_pod(f"b{i}", "team-b", cpu="200m"))
    out = router.schedule_all_pending(wait_backoff=True)
    assert sum(1 for o in out if o.node_name) == 6
    # Fleet-aggregated at the router.
    agg = router.tenant_metrics.snapshot()
    assert agg["team-a"]["admitted"] == 4 and agg["team-a"]["bound"] == 4
    assert agg["team-b"]["bound"] == 2
    assert router.stats()["tenants"]["team-a"]["bound"] == 4
    # Per-shard split on the owners (commit-site counting + the stats
    # mirror's top-K block).
    per_shard = {
        k: dict(o.stats()["tenants"]["top"]) for k, o in owners.items()
    }
    assert sum(d.get("team-a", 0) for d in per_shard.values()) == 4
    assert sum(d.get("team-b", 0) for d in per_shard.values()) == 2


def _find(span: dict, name: str) -> dict | None:
    if span.get("name") == name:
        return span
    for child in span.get("children") or ():
        hit = _find(child, name)
        if hit is not None:
            return hit
    return None


def test_fleet_trace_tree_joins_router_owner_sidecar():
    router, _owners, _smap = build_fleet(2)
    router.trace_threshold_s = 0.0  # every batch is "slow": dump it
    router.add_pod(tenant_pod("p1", "team-a", cpu="200m"))
    out = router.schedule_all_pending(wait_backoff=True)
    assert any(o.node_name for o in out)
    assert router.slow_spans
    root = router.slow_spans[0]
    assert root["name"] == "FleetScheduleBatch"
    pod_span = _find(root, "SchedulePod")
    assert pod_span is not None
    rpc = _find(pod_span, "ProposeRPC")
    assert rpc is not None
    # The owner's op span rode back on the RPC response and joined as a
    # remote child — same trace id, parented on the RPC span.
    op = _find(rpc, "FleetOp:propose")
    assert op is not None
    assert op["trace_id"] == root["trace_id"]
    assert op["parent_span_id"] == rpc["span_id"]
    # ...and carries the sidecar-leg device spans.
    assert _find(op, "Featurize") is not None
    assert _find(op, "DevicePass") is not None
    commit = _find(pod_span, "CommitRPC")
    assert commit is not None
    assert _find(commit, "FleetOp:commit") is not None


def test_stitch_spans_joins_cross_process_dumps():
    # Two "processes": a root span dumped by one, a child dumped by the
    # other, joined post-hoc on (trace_id, parent_span_id).
    root = {
        "name": "root", "trace_id": "t1", "span_id": "r",
        "parent_span_id": None, "children": [],
    }
    remote = {
        "name": "remote-op", "trace_id": "t1", "span_id": "x",
        "parent_span_id": "r", "children": [],
    }
    orphan = {
        "name": "other", "trace_id": "t2", "span_id": "y",
        "parent_span_id": "gone", "children": [],
    }
    roots = stitch_spans([root, remote, orphan])
    assert [r["name"] for r in roots] == ["root", "other"]
    assert roots[0]["children"][0]["name"] == "remote-op"
    # Inputs are not mutated.
    assert root["children"] == []


# -- the federated flight merge ----------------------------------------------


def _snap(component: str, records: list[dict]) -> dict:
    rec = FlightRecorder(component=component, clock=lambda: 0.0)
    return {"component": component, "records": records}


def test_merge_fleet_timeline_orders_on_logical_clock():
    a = _snap("owner-0", [
        {"kind": "batch", "seq": 1, "lc": 2.0, "ts": 10.0, "wall_s": 0.5,
         "pods": 1, "scheduled": 1, "phases": {"commit": 0.5}},
        {"kind": "marker", "seq": 2, "lc": 3.0, "event": "handoff_in"},
    ])
    b = _snap("router", [
        {"kind": "batch", "seq": 1, "lc": 1.0, "ts": 10.2, "wall_s": 0.9,
         "pods": 1, "scheduled": 1, "phases": {"scatter": 0.9}},
    ])
    merged = merge_fleet([a, b])
    kinds = [(e["component"], e.get("lc")) for e in merged["timeline"]]
    assert kinds == [("router", 1.0), ("owner-0", 2.0), ("owner-0", 3.0)]
    # Wall-derived fields never reach the hashed timeline.
    assert all(
        "ts" not in e and "wall_s" not in e and "phases" not in e
        for e in merged["timeline"]
    )
    # Same snapshots with DIFFERENT wall numbers: identical timeline sha.
    b2 = _snap("router", [dict(b["records"][0], ts=99.0, wall_s=0.1,
                               phases={"scatter": 0.1})])
    merged2 = merge_fleet([a, b2])
    assert merged2["timeline_sha256"] == merged["timeline_sha256"]


def test_merge_fleet_overlap_and_innermost_critical_path():
    # Router busy [0, 1.0] (scatter), owner busy [0.2, 0.8] (device):
    # overlap 0.6s; the owner's slice is the INNERMOST active work and
    # takes the critical path while it runs; the router takes the rest.
    router = _snap("router", [
        {"kind": "batch", "seq": 1, "ts": 1.0, "wall_s": 1.0,
         "pods": 1, "scheduled": 1, "phases": {"scatter": 1.0}},
    ])
    owner = _snap("owner-0", [
        {"kind": "batch", "seq": 1, "ts": 0.8, "wall_s": 0.6,
         "pods": 1, "scheduled": 0, "phases": {"device": 0.6}},
    ])
    merged = merge_fleet([router, owner])
    wall = merged["wall"]
    assert abs(wall["busy_s_total"] - 1.6) < 1e-6
    assert abs(wall["union_busy_s"] - 1.0) < 1e-6
    assert abs(wall["overlap_s"] - 0.6) < 1e-6
    crit = {
        (c["component"], c["phase"]): c["seconds"]
        for c in merged["critical_path"]
    }
    assert abs(crit[("owner-0", "device")] - 0.6) < 1e-6
    assert abs(crit[("router", "scatter")] - 0.4) < 1e-6


def test_merge_fleet_duplicate_names_disambiguate():
    a = _snap("scheduler", [{"kind": "marker", "seq": 1, "event": "x"}])
    b = _snap("scheduler", [{"kind": "marker", "seq": 1, "event": "y"}])
    merged = merge_fleet([a, b])
    assert sorted(merged["components"]) == ["scheduler", "scheduler#2"]
    named = merge_fleet([a, b], names=["owner-0", "owner-1"])
    assert sorted(named["components"]) == ["owner-0", "owner-1"]


def test_fleet_soak_merged_timeline_is_deterministic():
    """2× same-seed in-process fleet soak → byte-identical merged
    timeline (the federated flight merge is part of the determinism
    contract), and observability off leaves bindings bit-identical."""
    import dataclasses

    from kubernetes_tpu.loadgen.soak import SoakConfig, run_fleet_soak

    cfg = SoakConfig(
        seed=21, nodes=12, churn_nodes=2, duration_s=1.5,
        rate_pods_per_s=10.0, live_pod_cap=40, warm_pods=16,
        batch_size=32, two_process=False, pace="virtual",
        journal_fsync="never", node_flap_period_s=0.0,
        cold_consumer_period_s=0.0,
        tenant_streams=(
            {"name": "steady", "rate_pods_per_s": 5.0},
            {"name": "bursty", "rate_pods_per_s": 3.0,
             "burst_factor": 4.0, "burst_start_s": 0.5,
             "burst_end_s": 1.0},
        ),
    )
    a = run_fleet_soak(cfg, 2)
    b = run_fleet_soak(cfg, 2)
    assert a["determinism"]["bindings_sha256"] == (
        b["determinism"]["bindings_sha256"]
    )
    assert a["determinism"]["timeline_sha256"] is not None
    assert a["determinism"]["timeline_sha256"] == (
        b["determinism"]["timeline_sha256"]
    )
    # The per-tenant split is present and sums to the decisions.
    per_tenant = a["tenants"]["per_tenant"]
    assert set(per_tenant) <= {"steady", "bursty", "-"}
    assert sum(t["decisions"] for t in per_tenant.values()) == (
        a["decisions"]
    )
    off = run_fleet_soak(
        dataclasses.replace(cfg, observability=False), 2
    )
    assert off["determinism"]["bindings_sha256"] == (
        a["determinism"]["bindings_sha256"]
    )
    assert off["fleet_timeline"] is None
    assert off["tenants"]["counters"] == {}


def test_profile_report_renders_fleet_merge(tmp_path, capsys):
    import os
    import sys

    sys.path.insert(
        0, os.path.join(os.path.dirname(__file__), "..", "scripts")
    )
    import profile_report

    router = _snap("router", [
        {"kind": "batch", "seq": 1, "lc": 1.0, "ts": 1.0, "wall_s": 1.0,
         "pods": 2, "scheduled": 2, "phases": {"scatter": 1.0}},
    ])
    owner = _snap("owner-0", [
        {"kind": "batch", "seq": 1, "lc": 1.0, "ts": 0.8, "wall_s": 0.6,
         "pods": 1, "scheduled": 1, "phases": {"device": 0.6}},
    ])
    merged = merge_fleet([router, owner])
    p = tmp_path / "merged.json"
    p.write_text(json.dumps(merged))
    assert profile_report.main(["--fleet", str(p)]) == 0
    out = capsys.readouterr().out
    assert "fleet flight merge" in out
    assert "critical path" in out
    assert "owner-0" in out and "router" in out
    # Raw dumps merge on the spot too (flight.py loaded by file path).
    pa, pb = tmp_path / "a.json", tmp_path / "b.json"
    pa.write_text(json.dumps(router))
    pb.write_text(json.dumps(owner))
    assert profile_report.main(["--fleet", str(pa), str(pb)]) == 0
    assert "parallelism" in capsys.readouterr().out


# -- weighted-fair admission (ISSUE 17) --------------------------------------


def test_wfq_admission_order_is_deterministic_and_weighted():
    def run():
        pol = FairAdmission(weights={"a": 2.0, "b": 1.0, "c": 0.5})
        q = SchedulingQueue(clock=lambda: 0.0, admission_policy=pol)
        for t in ("a", "b", "c"):
            for i in range(8):
                q.add(tenant_pod(f"{t}-{i}", t))
        order = []
        while True:
            batch = q.pop_batch(4)
            if not batch:
                break
            order.extend(qp.pod.uid for qp in batch)
        return order

    o1, o2 = run(), run()
    assert o1 == o2 and len(o1) == 24
    # Accelerator-time WFQ, not round-robin: over the first 7 slots the
    # 2 : 1 : 0.5 weights admit 4 a's, 2 b's and 1 c (virtual-finish
    # tags advance by cost/weight; ties break on the sorted name).
    head = o1[:7]
    counts = {t: sum(1 for u in head if f"/{t}-" in u) for t in "abc"}
    assert counts == {"a": 4, "b": 2, "c": 1}
    # Within one tenant, QueueSort (arrival) order is untouched.
    a_pops = [u for u in o1 if "/a-" in u]
    assert a_pops == sorted(a_pops, key=lambda u: int(u.rsplit("-", 1)[1]))


def test_weights_from_matrix_synthetic_and_measured():
    classes = {"steady": "serve", "bursty": "train-large"}
    w = weights_from_matrix(DEFAULT_THROUGHPUT_MATRIX, classes)
    # train-large throughput is lower on the mean pool, so its
    # accelerator-TIME share (the weight) is higher; shares normalize
    # to mean 1.0 over the mapped tenants.
    assert w["bursty"] > w["steady"]
    assert abs((w["bursty"] + w["steady"]) / 2 - 1.0) < 1e-9
    # Unmapped classes and an empty matrix fall back to uniform 1.0.
    w2 = weights_from_matrix(
        DEFAULT_THROUGHPUT_MATRIX, {**classes, "misc": "no-such-class"}
    )
    assert w2["misc"] == 1.0
    assert weights_from_matrix((), classes) == {"bursty": 1.0, "steady": 1.0}
    # The MEASURED artifact's row form is interchangeable with the
    # synthetic committed matrix (framework/measured.matrix_rows).
    doc = {
        "version": 1,
        "kind": "measured_throughput_matrix",
        "matrix": {
            "serve": {"tpu-v4": 540, "tpu-v5e": 1000},
            "train-large": {"tpu-v4": 1000, "tpu-v5e": 520},
        },
    }
    wm = weights_from_matrix(matrix_rows(doc), classes)
    assert wm["bursty"] > wm["steady"]
    # Hetero pools re-weight the mix: an all-v4 pool makes serve the
    # expensive class (540 vs train-large's 1000 on v4).
    wp = weights_from_matrix(matrix_rows(doc), classes, pools={"tpu-v4": 4})
    assert wp["steady"] > wp["bursty"]


def test_rate_cap_credit_exhaustion_and_refill_on_logical_clock():
    pol = FairAdmission(
        weights={},
        rate_pods_per_s=1.0,
        burst=2.0,
        aging_max_wait_s=100.0,
        slo_wait_budget_s=100.0,
    )
    q = SchedulingQueue(clock=lambda: 0.0, admission_policy=pol)
    for i in range(5):
        q.add(tenant_pod(f"p-{i}", "team-a"))
    # Burst credits admit 2, then the tenant is credit-blocked — the
    # queue reports THROTTLED (not drained) so pollers stop spinning.
    assert [qp.pod.name for qp in q.pop_batch(10)] == ["p-0", "p-1"]
    assert q.last_pop_throttled
    assert pol.status()["throttle_hits"] >= 1
    # One LOGICAL second refills one credit; no wall clock anywhere.
    pol.note_time(1.0)
    assert [qp.pod.name for qp in q.pop_batch(10)] == ["p-2"]
    # Refill is min-clamped at the burst ceiling: a long idle gap buys
    # at most `burst` credits, not rate x gap.
    pol.note_time(100.0)
    assert [qp.pod.name for qp in q.pop_batch(10)] == ["p-3", "p-4"]
    assert pol.status()["tenants"]["team-a"]["credits"] == 0.0
    assert not q.last_pop_throttled  # drained, not blocked


def test_aging_escape_admits_a_starved_head_and_counts_the_violation():
    pol = FairAdmission(
        weights={},
        rate_pods_per_s=0.01,
        burst=1.0,
        aging_max_wait_s=5.0,
        slo_wait_budget_s=4.0,
    )
    q = SchedulingQueue(clock=lambda: 0.0, admission_policy=pol)
    q.add(tenant_pod("p-0", "team-a"))
    q.add(tenant_pod("p-1", "team-a"))
    assert [qp.pod.name for qp in q.pop_batch(10)] == ["p-0"]
    assert q.last_pop_throttled
    # Past the aging bound the escape admits the head DESPITE an empty
    # bucket; the wait also blew the (tighter) starvation budget, so the
    # violation counters the soak/kill gates read both tick.
    pol.note_time(6.0)
    assert [qp.pod.name for qp in q.pop_batch(10)] == ["p-1"]
    st = pol.status()
    assert st["aging_escapes"] == 1
    assert st["starvation_violations"] == 1
    assert st["tenants"]["team-a"]["starved"] == 1


def test_hashed_tail_tier_bounds_labels_and_is_shared_per_registry():
    lab = TenantLabeler(limit=4, hash_buckets=8)
    labels = {lab.label_for(f"team-{i:03d}") for i in range(100)}
    hashed = {l for l in labels if l.startswith("~")}
    assert len(labels - hashed) == 4
    assert 0 < len(hashed) <= 8
    assert len(labels) <= 4 + 8 + 1
    # crc32 bucketing — stable across processes and runs, unlike the
    # salted builtin hash().
    assert lab.label_for("team-099") == "~{:02d}".format(
        zlib.crc32(b"team-099") % 8
    )
    # ONE labeler per registry: a second TenantMetrics on the same
    # registry shares the exact-tier table instead of forking its own
    # top-K — the fleet registry carries the driver's, the router's and
    # the admission policy's tenant= writers at once, and the bound
    # holds over their union.
    reg = MetricsRegistry()
    tm1 = TenantMetrics(reg, limit=2, hash_buckets=4)
    tm2 = TenantMetrics(reg)
    assert tm2.labeler is tm1.labeler
    tm1.note("admitted", "a")
    tm1.note("admitted", "b")
    tm2.note("admitted", "c")
    assert tm2.labeler.label_for("c").startswith("~")


def test_fleet_admission_is_bit_identical_across_runs():
    def run():
        router, _owners, _smap = build_fleet(2)
        pol = FairAdmission(weights={"team-a": 2.0, "team-b": 1.0})
        router.arm_admission(pol)
        tenant_of_uid = {}
        for i in range(5):
            for t in ("team-a", "team-b"):
                p = tenant_pod(f"{t[-1]}{i}", t, cpu="200m")
                tenant_of_uid[p.uid] = t
                router.add_pod(p)
        out = router.schedule_all_pending(wait_backoff=True)
        binds = sorted((o.pod.uid, o.node_name) for o in out)
        return binds, list(pol.admitted_log), tenant_of_uid

    (b1, log1, tmap), (b2, log2, _) = run(), run()
    assert b1 == b2
    assert log1 == log2 and len(log1) == 10
    # The armed order interleaves by WEIGHT, not arrival: 2:1 admits
    # 4 team-a in the first 6 slots.
    head = [tmap[u] for u in log1[:6]]
    assert head.count("team-a") == 4 and head.count("team-b") == 2
