"""Scoped speculative invalidation + the decision push stream.

VERDICT r4 next-4: per-decision dependency sets (node touched, domain
reads, volume/DRA use, gang membership) so a cluster event invalidates
only INTERSECTING decisions — the O(changed) principle of the reference's
generation-diff snapshot (backend/cache/cache.go:186) applied to the
speculation cache.  Plus the subscribe/push surface (VERDICT r4 next-1):
decisions stream to subscribers as epoch-ordered frames so the host
plugin answers PreFilter from a local map with no wire round trip."""

import tempfile

from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.scheduler import TPUScheduler
from kubernetes_tpu.sidecar.server import SidecarClient, SidecarServer


def node(name: str, cpu: str = "8", labels: dict | None = None):
    b = make_node(name).capacity({"cpu": cpu, "memory": "32Gi", "pods": 110})
    for k, v in (labels or {}).items():
        b = b.label(k, v)
    return b.obj()


def pod(name: str, cpu: str = "1", priority: int = 0):
    p = make_pod(name).req({"cpu": cpu})
    if priority:
        p = p.priority(priority)
    return p.obj()


def _spec_server(batch_size=8, lookahead=None):
    path = tempfile.mktemp(suffix=".sock")
    srv = SidecarServer(
        path,
        scheduler=TPUScheduler(batch_size=batch_size),
        speculate=True,
        lookahead=lookahead,
    )
    srv.serve_background()
    return srv, SidecarClient(path), path


def test_foreign_bind_invalidates_only_its_node():
    """A bind we didn't decide consumes ONE node's resources: decisions
    on other nodes (no domain terms) survive it."""
    srv, client, _ = _spec_server()
    try:
        client.add("Node", node("n0", cpu="4"))
        client.add("Node", node("n1", cpu="4"))
        pods = [pod(f"p{i}") for i in range(6)]
        for p in pods:
            client.add("PendingPod", p)
        (r0,) = client.schedule([pods[0]], drain=False)
        assert r0.node_name
        # Foreign pod bound to n0 by another profile.
        foreign = pod("foreign", cpu="1")
        foreign.spec.node_name = "n0"
        client.add("Pod", foreign)
        stats = client.dump()["speculation"]
        # Scoped: only decisions ON n0 rolled back, not the whole cache.
        assert stats["full_invalidations"] == 0
        cached_before = stats["speculated"] - stats["rolled_back"]
        assert cached_before > 0  # some survivors still cached
        # Survivors still serve as hits; evictees recompute on miss.
        hits0 = stats["hits"]
        for p in pods[1:]:
            (r,) = client.schedule([p], drain=False)
            assert r.node_name
        stats = client.dump()["speculation"]
        assert stats["hits"] > hits0
        dump = client.dump()
        assert dump["mirror_equal"]
        # Capacity respected post-recompute: n0 holds the foreign pod too.
        cpu_used = {}
        for rec in dump["pods"].values():
            cpu_used[rec["node"]] = cpu_used.get(rec["node"], 0) + 1
        assert all(c <= 4 for c in cpu_used.values())
    finally:
        client.close()
        srv.close()


def test_node_add_wakes_unschedulable_verdicts():
    """A cached 'no feasible node' verdict is invalidated by new capacity
    (the node-add queueing hint, scheduling_queue.go:1029) — without
    disturbing committed placements."""
    srv, client, _ = _spec_server()
    try:
        client.add("Node", node("n0", cpu="2"))
        pods = [pod(f"p{i}", cpu="2") for i in range(3)]
        for p in pods:
            client.add("PendingPod", p)
        (r0,) = client.schedule([pods[0]], drain=False)
        assert r0.node_name == "n0"
        # p1/p2 got unschedulable verdicts in the same batch (no room).
        (r1,) = client.schedule([pods[1]], drain=False)
        assert not r1.node_name
        client.add("Node", node("n-new", cpu="4"))
        stats = client.dump()["speculation"]
        assert stats["full_invalidations"] == 0
        # p2's cached unschedulable verdict was scoped out; the re-ask
        # recomputes against the new node and fits.
        (r2,) = client.schedule([pods[2]], drain=False)
        assert r2.node_name == "n-new"
        # p1 re-asks after its backoff: also recomputed, fits now.
        (r1b,) = client.schedule([pods[1]], drain=False)
        assert r1b.node_name == "n-new"
    finally:
        client.close()
        srv.close()


def test_volume_event_spares_volumeless_decisions():
    """A StorageClass upsert touches only volume-dependent decisions;
    plain pods' cached decisions survive."""
    from kubernetes_tpu.api import types as t

    srv, client, _ = _spec_server()
    try:
        client.add("Node", node("n0"))
        pods = [pod(f"p{i}") for i in range(4)]
        for p in pods:
            client.add("PendingPod", p)
        (r0,) = client.schedule([pods[0]], drain=False)
        assert r0.node_name
        client.add(
            "StorageClass",
            t.StorageClass(name="fast", provisioner="csi.example.com"),
        )
        stats = client.dump()["speculation"]
        assert stats["full_invalidations"] == 0
        assert stats["rolled_back"] == 0  # no cached decision uses volumes
        for p in pods[1:]:
            (r,) = client.schedule([p], drain=False)
            assert r.node_name
        stats = client.dump()["speculation"]
        assert stats["hits"] == 3  # all served from the surviving cache
    finally:
        client.close()
        srv.close()


def test_push_stream_serves_decisions_without_wire_calls():
    """Subscribe → decisions arrive as Push frames after the miss batch;
    the emulated plugin-local map then answers without Schedule calls,
    and the bind echo retires entries without invalidation."""
    srv, client, path = _spec_server()
    sub = None
    try:
        client.add("Node", node("n0"))
        client.add("Node", node("n1"))
        sub = SidecarClient(path)
        sub.subscribe()
        pods = [pod(f"p{i}") for i in range(8)]
        for p in pods:
            client.add("PendingPod", p)
        # One wire miss computes the batch and pushes the co-scheduled 7.
        (r0,) = client.schedule([pods[0]], drain=False)
        assert r0.node_name
        push = sub.read_push()
        assert push is not None and not push.invalidate_all
        local = {d.pod_uid: d for d in push.decisions}
        assert len(local) == 7  # requested pod rides the response, not the push
        assert r0.pod_uid not in local
        # The plugin-local map answers the remaining pods with NO wire call.
        for p in pods[1:]:
            d = local.pop(p.uid)
            assert d.node_name
            # Host binds it; the informer echo is a confirmation.
            p.spec.node_name = d.node_name
            client.add("Pod", p)
        stats = client.dump()["speculation"]
        assert stats["pushed"] == 7
        assert stats["invalidations"] == 0  # echoes confirmed, not mutated
        assert stats["hits"] == 0  # nothing needed the wire hit path
        dump = client.dump()
        assert dump["mirror_equal"]
        assert len(dump["pods"]) == 8
    finally:
        if sub is not None:
            sub.close()
        client.close()
        srv.close()


def test_push_invalidation_precedes_recomputed_decisions():
    """Stream-order contract: the invalidation frame (epoch bump) arrives
    BEFORE any decision recomputed after it, so an in-order subscriber
    can never hold a rolled-back decision."""
    srv, client, path = _spec_server()
    sub = None
    try:
        client.add("Node", node("n0", cpu="4", labels={"zone": "a"}))
        sub = SidecarClient(path)
        sub.subscribe()
        pods = [pod(f"p{i}") for i in range(4)]
        for p in pods:
            client.add("PendingPod", p)
        (r0,) = client.schedule([pods[0]], drain=False)
        first = sub.read_push()
        assert len(first.decisions) == 3
        epoch0 = first.epoch
        # Global mutation: label change → full rollback.
        client.add("Node", node("n0", cpu="4", labels={"zone": "b"}))
        inv = sub.read_push()
        assert inv.invalidate_all
        assert inv.epoch == epoch0 + 1
        # Recompute lands at the NEW epoch, after the invalidation frame.
        (r1,) = client.schedule([pods[1]], drain=False)
        assert r1.node_name
        nxt = sub.read_push()
        assert not nxt.invalidate_all
        assert nxt.epoch == epoch0 + 1
        assert all(d.pod_uid != r1.pod_uid for d in nxt.decisions)
    finally:
        if sub is not None:
            sub.close()
        client.close()
        srv.close()


def test_scoped_push_invalidation_names_uids():
    """A scoped rollback pushes the specific uids, not invalidate_all."""
    srv, client, path = _spec_server()
    sub = None
    try:
        client.add("Node", node("n0", cpu="4"))
        client.add("Node", node("n1", cpu="4"))
        sub = SidecarClient(path)
        sub.subscribe()
        pods = [pod(f"p{i}") for i in range(6)]
        for p in pods:
            client.add("PendingPod", p)
        (r0,) = client.schedule([pods[0]], drain=False)
        push = sub.read_push()
        by_node: dict[str, list] = {}
        for d in push.decisions:
            by_node.setdefault(d.node_name, []).append(d.pod_uid)
        # Foreign bind on n0: only n0's cached decisions roll back.
        foreign = pod("foreign")
        foreign.spec.node_name = "n0"
        client.add("Pod", foreign)
        inv = sub.read_push()
        assert not inv.invalidate_all
        invalidated = set(inv.invalidate_uids)
        assert invalidated  # n0 had at least one cached decision
        expect_n0 = {u for u in by_node.get("n0", []) if u != r0.pod_uid}
        assert invalidated == expect_n0
    finally:
        if sub is not None:
            sub.close()
        client.close()
        srv.close()


def test_reverse_antiaffinity_escalates_domain_events():
    """An EXISTING pod's required anti-affinity constrains future pods
    (existingAntiAffinityCounts, interpodaffinity/filtering.go:155) — so
    once such a pod is in the mirror, a domain event must stale even
    TERMS-FREE cached decisions (they may sit in the constrained domain)."""
    srv, client, _ = _spec_server()
    try:
        client.add("Node", node("n0", labels={"zone": "a"}))
        client.add("Node", node("n1", labels={"zone": "a"}))
        # A bound pod with required anti-affinity against app=web pods.
        guard = (
            make_pod("guard")
            .req({"cpu": "1"})
            .pod_anti_affinity_in("app", ["web"], "zone")
            .node("n0")
            .obj()
        )
        client.add("Pod", guard)
        pods = [pod(f"p{i}") for i in range(4)]
        for p in pods:
            client.add("PendingPod", p)
        (r0,) = client.schedule([pods[0]], drain=False)
        assert r0.node_name
        # A pod delete is a domain event (domains=True in note_remove);
        # with the reverse flag set, the terms-free cached decisions are
        # invalidated too — NOT kept alive by their own empty DepSets.
        client.remove("Pod", guard.uid)
        stats = client.dump()["speculation"]
        assert stats["rolled_back"] >= 1
        for p in pods[1:]:
            (r,) = client.schedule([p], drain=False)
            assert r.node_name
        assert client.dump()["mirror_equal"]
    finally:
        client.close()
        srv.close()


def test_incoming_antiaffinity_bind_full_rollback():
    """A foreign bind CARRYING required anti-affinity imposes a reverse
    constraint no cached DepSet anticipated → full rollback, even for
    decisions on other nodes."""
    srv, client, _ = _spec_server()
    try:
        client.add("Node", node("n0", labels={"zone": "a"}))
        client.add("Node", node("n1", labels={"zone": "a"}))
        pods = [pod(f"p{i}") for i in range(4)]
        for p in pods:
            client.add("PendingPod", p)
        (r0,) = client.schedule([pods[0]], drain=False)
        foreign = (
            make_pod("foreign")
            .req({"cpu": "1"})
            .pod_anti_affinity_in("app", ["web"], "zone")
            .node("n1")
            .obj()
        )
        client.add("Pod", foreign)
        stats = client.dump()["speculation"]
        assert stats["full_invalidations"] == 1
    finally:
        client.close()
        srv.close()


def test_node_add_invalidates_spread_decisions():
    """A new node is a new (empty) topology domain: cached DoNotSchedule
    spread placements can now violate maxSkew and must recompute."""
    srv, client, _ = _spec_server()
    try:
        client.add("Node", node("n0", labels={"zone": "a"}))
        client.add("Node", node("n1", labels={"zone": "b"}))
        spread = [
            make_pod(f"s{i}")
            .req({"cpu": "1"})
            .label("app", "web")
            .spread_constraint(
                1, "zone", "DoNotSchedule",
                label_key="app", label_values=["web"],
            )
            .obj()
            for i in range(4)
        ]
        for p in spread:
            client.add("PendingPod", p)
        (r0,) = client.schedule([spread[0]], drain=False)
        assert r0.node_name
        rolled0 = client.dump()["speculation"]["rolled_back"]
        client.add("Node", node("n2", labels={"zone": "c"}))
        stats = client.dump()["speculation"]
        assert stats["rolled_back"] > rolled0  # spread decisions recompute
        for p in spread[1:]:
            (r,) = client.schedule([p], drain=False)
            assert r.node_name
        # Post-recompute the placements respect maxSkew over 3 zones.
        dump = client.dump()
        zones = {"n0": "a", "n1": "b", "n2": "c"}
        per_zone = {"a": 0, "b": 0, "c": 0}
        for rec in dump["pods"].values():
            per_zone[zones[rec["node"]]] += 1
        assert max(per_zone.values()) - min(per_zone.values()) <= 1
    finally:
        client.close()
        srv.close()


def test_drain_bound_exhaustion_is_counted():
    """VERDICT r4 weak-4: when _run_batch's 64-batch bound runs out with
    the requested pod still queued, the synthesized 'no feasible node' is
    counted as drain_exhausted (the availability lie made visible)."""
    srv, client, _ = _spec_server(batch_size=1, lookahead=128)
    try:
        client.add("Node", node("n0", cpu="256"))
        # 80 higher-priority hints starve the requested pod past the bound.
        for i in range(80):
            client.add("PendingPod", pod(f"vip-{i}", priority=10))
        target = pod("steerage", priority=0)
        (r,) = client.schedule([target], drain=False)
        assert not r.node_name  # under-delivered, not truly infeasible
        stats = client.dump()["speculation"]
        assert stats["drain_exhausted"] == 1
    finally:
        client.close()
        srv.close()


def test_health_surface():
    """healthz/readyz analog over the wire (app/server.go:181–210)."""
    srv, client, _ = _spec_server()
    try:
        client.add("Node", node("n0"))
        h = client.health()
        assert h["healthy"] and h["ready"]
        assert h["nodes"] == 1
        assert h["speculation"] is True
    finally:
        client.close()
        srv.close()
