"""Default-profile decision parity: the device engine in parity mode vs the
full scalar oracle (oracle_full.FullOracleScheduler), decision for decision —
filters, truncation/rotation, fused weighted scoring, seeded tie-breaks,
greedy-reprieve preemption, and the nominated retry (VERDICT r3 next-2;
match: schedule_one.go:411–920, preemption.go:148–470)."""

from dataclasses import replace

from kubernetes_tpu.framework.config import DEFAULT_PROFILE
from kubernetes_tpu.ops.common import registered_subset
from kubernetes_tpu.scheduler import TPUScheduler

from oracle_full import FullOracleScheduler, build_fixture


def test_default_profile_decision_parity_with_preemption():
    nodes, bound, pending, pdbs, _objs = build_fixture()
    prof = replace(
        registered_subset(DEFAULT_PROFILE), percentage_of_nodes_to_score=None
    )
    s = TPUScheduler(profile=prof, batch_size=64, chunk_size=1)
    for n in nodes:
        s.add_node(n)
    for p in bound:
        s.add_pod(p)
    for pdb in pdbs:
        s.add_pdb(pdb)

    import copy

    oracle = FullOracleScheduler(
        nodes,
        pct=None,
        seed=prof.tie_break_seed,
        hard_pod_affinity_weight=prof.hard_pod_affinity_weight,
        batch_size=64,
        pdbs=[copy.deepcopy(p) for p in pdbs],
    )
    for p in bound:
        oracle.add_bound(copy.deepcopy(p))

    # Pre-grow every vocabulary/schema bucket the pending pods will need:
    # featurization interns without committing.  Mid-run schema growth makes
    # the engine defer preemption by one batch (sound, but it shifts the
    # tie-break step counter relative to the oracle).
    from kubernetes_tpu.engine.features import build_pod_batch

    warm = [copy.deepcopy(p) for p in pending]
    build_pod_batch(warm, s.builder, s.profile, len(warm))

    for p in pending:
        s.add_pod(copy.deepcopy(p))
    got_out = s.schedule_all_pending(wait_backoff=True)
    want_out = oracle.run([copy.deepcopy(p) for p in pending])

    got_bind = {o.pod.name: o.node_name for o in got_out if o.node_name}
    want_bind = {d.pod.name: d.node for d in want_out if d.node}
    got_nom = {
        o.pod.name: o.nominated_node for o in got_out if o.nominated_node
    }
    want_nom = {d.pod.name: d.nominated for d in want_out if d.nominated}
    got_vic = {
        o.pod.name: tuple(sorted(o.victim_uids)) for o in got_out if o.victim_uids
    }
    want_vic = {
        d.pod.name: tuple(sorted(d.victims)) for d in want_out if d.victims
    }

    diffs = {
        k: (got_bind.get(k), want_bind.get(k))
        for k in set(got_bind) | set(want_bind)
        if got_bind.get(k) != want_bind.get(k)
    }
    assert not diffs, (
        f"{len(diffs)} binding mismatches, first 5: {dict(list(sorted(diffs.items()))[:5])}"
    )
    assert got_nom == want_nom, (got_nom, want_nom)
    assert got_vic == want_vic, (got_vic, want_vic)
    # The preemption theater actually ran (fixture guard).
    assert want_nom, "fixture no longer exercises preemption"
    assert all(f"vip-{i}" in got_bind for i in range(6))
    assert s.builder.host_mirror_equal()


def test_full_surface_parity_volumes_dra_gates():
    """The r4 full-surface A/B (VERDICT r3 missing-2): volumes (bound PV
    affinity + zones, WFFC static choice, dynamic provisioning topology,
    CSI attach limits, RWOP), counted-device DRA (incl. a missing claim),
    and gated pods — all ACTIVE, zero binding mismatches."""
    import copy

    from oracle_full import RefClaims, RefVolumes

    nodes, bound, pending, pdbs, objs = build_fixture(volumes=True)
    prof = replace(
        registered_subset(DEFAULT_PROFILE), percentage_of_nodes_to_score=None
    )
    s = TPUScheduler(profile=prof, batch_size=64, chunk_size=1)
    # Volume/DRA-active batches gate prefetch off anyway; pinning it off
    # globally gives one deterministic requeue alignment for the A/B
    # (mixed fixtures would otherwise flip per batch composition).
    s._prefetch_enabled = False
    for n in nodes:
        s.add_node(n)
    for sc in objs["classes"]:
        s.add_storage_class(sc)
    for pv in objs["pvs"]:
        s.add_pv(pv)
    for pvc in objs["pvcs"]:
        s.add_pvc(pvc)
    for cn in objs["csinodes"]:
        s.add_csinode(cn)
    for sl in objs["slices"]:
        s.add_resource_slice(sl)
    for cl in objs["dclaims"]:
        s.add_resource_claim(cl)
    for p in bound:
        s.add_pod(p)
    for pdb in pdbs:
        s.add_pdb(pdb)

    oracle = FullOracleScheduler(
        nodes,
        pct=None,
        seed=prof.tie_break_seed,
        hard_pod_affinity_weight=prof.hard_pod_affinity_weight,
        batch_size=64,
        pdbs=[copy.deepcopy(p) for p in pdbs],
        vols=RefVolumes(
            pvs=copy.deepcopy(objs["pvs"]),
            pvcs=copy.deepcopy(objs["pvcs"]),
            classes=copy.deepcopy(objs["classes"]),
            csinodes=copy.deepcopy(objs["csinodes"]),
        ),
        claims=RefClaims(
            claims=copy.deepcopy(objs["dclaims"]),
            slices=copy.deepcopy(objs["slices"]),
        ),
    )
    for p in bound:
        oracle.add_bound(copy.deepcopy(p))

    from kubernetes_tpu.engine.features import build_pod_batch

    warm = [copy.deepcopy(p) for p in pending]
    build_pod_batch(warm, s.builder, s.profile, len(warm))

    for p in pending:
        s.add_pod(copy.deepcopy(p))
    got_out = s.schedule_all_pending(wait_backoff=True)
    want_out = oracle.run([copy.deepcopy(p) for p in pending], prefetch=False)

    got_bind = {o.pod.name: o.node_name for o in got_out if o.node_name}
    want_bind = {d.pod.name: d.node for d in want_out if d.node}
    diffs = {
        k: (got_bind.get(k), want_bind.get(k))
        for k in set(got_bind) | set(want_bind)
        if got_bind.get(k) != want_bind.get(k)
    }
    assert not diffs, (
        f"{len(diffs)} binding mismatches, first 5: "
        f"{dict(list(sorted(diffs.items()))[:5])}"
    )

    # NON-VACUOUS: the volume/DRA plugins visibly constrained placement.
    zone = "topology.kubernetes.io/zone"
    node_by_name = {n.name: n for n in nodes}
    for i in range(6):  # bound-PV pods pinned to the PV's zone
        nd = got_bind[f"vb-{i}"]
        assert node_by_name[nd].metadata.labels[zone] == f"zone-{i % 4}", (i, nd)
    for i in range(4):  # WFFC static PVs pinned to their zone
        nd = got_bind[f"vw-{i}"]
        assert node_by_name[nd].metadata.labels[zone] == f"zone-{i % 4}", (i, nd)
    for i in range(4):  # dynamic provisioning allowedTopologies zone-0/1
        nd = got_bind[f"vd-{i}"]
        assert node_by_name[nd].metadata.labels[zone] in ("zone-0", "zone-1")
    assert "rw-a" in got_bind  # RWOP winner
    assert "rw-b" not in got_bind and "rw-b" not in want_bind  # RWOP loser
    for i in range(6):  # DRA pods only on device-publishing nodes
        assert got_bind[f"dra-{i}"] in {f"node-{j:04d}" for j in range(8)}
    assert "dra-missing" not in got_bind and "dra-missing" not in want_bind
    for uid in objs["gated_uids"]:  # gated pods never scheduled
        name = uid.split("/")[1]
        assert name not in got_bind and name not in want_bind
    assert s.builder.host_mirror_equal()
