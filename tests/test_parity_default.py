"""Default-profile decision parity: the device engine in parity mode vs the
full scalar oracle (oracle_full.FullOracleScheduler), decision for decision —
filters, truncation/rotation, fused weighted scoring, seeded tie-breaks,
greedy-reprieve preemption, and the nominated retry (VERDICT r3 next-2;
match: schedule_one.go:411–920, preemption.go:148–470)."""

from dataclasses import replace

from kubernetes_tpu.framework.config import DEFAULT_PROFILE
from kubernetes_tpu.ops.common import registered_subset
from kubernetes_tpu.scheduler import TPUScheduler

from oracle_full import FullOracleScheduler, build_fixture


def test_default_profile_decision_parity_with_preemption():
    nodes, bound, pending, pdbs = build_fixture()
    prof = replace(
        registered_subset(DEFAULT_PROFILE), percentage_of_nodes_to_score=None
    )
    s = TPUScheduler(profile=prof, batch_size=64, chunk_size=1)
    for n in nodes:
        s.add_node(n)
    for p in bound:
        s.add_pod(p)
    for pdb in pdbs:
        s.add_pdb(pdb)

    import copy

    oracle = FullOracleScheduler(
        nodes,
        pct=None,
        seed=prof.tie_break_seed,
        hard_pod_affinity_weight=prof.hard_pod_affinity_weight,
        batch_size=64,
        pdbs=[copy.deepcopy(p) for p in pdbs],
    )
    for p in bound:
        oracle.add_bound(copy.deepcopy(p))

    # Pre-grow every vocabulary/schema bucket the pending pods will need:
    # featurization interns without committing.  Mid-run schema growth makes
    # the engine defer preemption by one batch (sound, but it shifts the
    # tie-break step counter relative to the oracle).
    from kubernetes_tpu.engine.features import build_pod_batch

    warm = [copy.deepcopy(p) for p in pending]
    build_pod_batch(warm, s.builder, s.profile, len(warm))

    for p in pending:
        s.add_pod(copy.deepcopy(p))
    got_out = s.schedule_all_pending(wait_backoff=True)
    want_out = oracle.run([copy.deepcopy(p) for p in pending])

    got_bind = {o.pod.name: o.node_name for o in got_out if o.node_name}
    want_bind = {d.pod.name: d.node for d in want_out if d.node}
    got_nom = {
        o.pod.name: o.nominated_node for o in got_out if o.nominated_node
    }
    want_nom = {d.pod.name: d.nominated for d in want_out if d.nominated}
    got_vic = {
        o.pod.name: tuple(sorted(o.victim_uids)) for o in got_out if o.victim_uids
    }
    want_vic = {
        d.pod.name: tuple(sorted(d.victims)) for d in want_out if d.victims
    }

    diffs = {
        k: (got_bind.get(k), want_bind.get(k))
        for k in set(got_bind) | set(want_bind)
        if got_bind.get(k) != want_bind.get(k)
    }
    assert not diffs, (
        f"{len(diffs)} binding mismatches, first 5: {dict(list(sorted(diffs.items()))[:5])}"
    )
    assert got_nom == want_nom, (got_nom, want_nom)
    assert got_vic == want_vic, (got_vic, want_vic)
    # The preemption theater actually ran (fixture guard).
    assert want_nom, "fixture no longer exercises preemption"
    assert all(f"vip-{i}" in got_bind for i in range(6))
    assert s.builder.host_mirror_equal()
