"""Config validation (apis/config/validation analog) and metrics
histograms (metrics/metrics.go analog)."""

from dataclasses import replace

from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.framework.config import (
    DEFAULT_PROFILE,
    Profile,
    ScoringStrategy,
    validate_profile,
)
from kubernetes_tpu.framework.metrics import Histogram
from kubernetes_tpu.scheduler import TPUScheduler


def test_default_profile_validates_clean():
    assert validate_profile(DEFAULT_PROFILE) == []


def test_validation_catches_violations():
    bad = Profile(
        name="",
        filters=("NoSuchPlugin", "NodeResourcesFit"),
        scorers=(("NodeResourcesFit", 0), ("NodeResourcesFit", 101)),
        percentage_of_nodes_to_score=150,
        scoring_strategy=ScoringStrategy(type="Bogus", resources=()),
        hard_pod_affinity_weight=-1,
    )
    errs = validate_profile(bad)
    joined = "\n".join(errs)
    for needle in (
        "profile.name", "NoSuchPlugin", "duplicate", "weight 0",
        "percentage_of_nodes_to_score 150", "'Bogus' unknown",
        "resources must be non-empty", "hard_pod_affinity_weight",
    ):
        assert needle in joined, (needle, errs)


def test_ratio_shape_must_be_sorted():
    p = replace(
        DEFAULT_PROFILE,
        scoring_strategy=ScoringStrategy(
            type="RequestedToCapacityRatio", shape=((100, 0), (0, 10))
        ),
    )
    assert any("shape" in e for e in validate_profile(p))


def test_histogram_quantiles():
    h = Histogram()
    for v in [0.001] * 50 + [0.5] * 50:
        h.observe(v)
    s = h.summary()
    assert s["count"] == 100
    assert s["p50"] <= 0.01 < s["p99"]
    assert abs(s["avg"] - 0.2505) < 1e-6


def test_scheduler_records_extension_point_histograms():
    s = TPUScheduler(batch_size=8)
    s.add_node(make_node("n1").capacity({"cpu": "8", "pods": 110}).obj())
    for i in range(4):
        s.add_pod(make_pod(f"p{i}").req({"cpu": "1"}).obj())
    s.schedule_all_pending()
    summary = s.metrics.registry.summary()
    points = summary["extension_point_duration_seconds"]
    assert points["Featurize"]["count"] >= 1
    assert points["DevicePass"]["count"] >= 1
    assert summary["pod_scheduling_sli_duration_seconds"]["count"] == 4


def test_cli_validate_and_config_load(tmp_path):
    import json

    from kubernetes_tpu.__main__ import load_config, main

    cfg = tmp_path / "sched.json"
    cfg.write_text(json.dumps({
        "profiles": [
            {"name": "a", "filters": ["NodeResourcesFit"],
             "scorers": [["NodeResourcesFit", 1]]},
            {"name": "b"},
        ],
        "batch_size": 128,
        "chunk_size": 32,
    }))
    loaded = load_config(str(cfg))
    assert [p.name for p in loaded["profiles"]] == ["a", "b"]
    assert loaded["batch_size"] == 128 and loaded["chunk_size"] == 32
    assert main(["validate", str(cfg)]) == 0

    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({
        "profiles": [{"name": "x", "filters": ["NoSuchPlugin"]}]
    }))
    assert main(["validate", str(bad)]) == 1


def test_dump_state_and_consistency_check():
    s = TPUScheduler(batch_size=8, consistency_check_every=1)
    s.add_node(make_node("n1").capacity({"cpu": "8", "pods": 110}).obj())
    s.add_pod(make_pod("p").req({"cpu": "1"}).obj())
    s.schedule_all_pending()  # the per-batch comparer runs and passes
    d = s.dump_state()
    assert d["mirror_equal"] is True
    assert d["nodes"]["n1"]["pods"] == ["default/p"]
    assert d["pods"]["default/p"]["bound"] is True
    assert d["queue"]["pending"] == 0
    s.check_consistency()


def test_plugin_execution_sampled_metrics():
    """plugin_execution_duration_seconds{plugin, point} (metrics.go:256,
    ~10% sampled like schedule_one.go:48): per-op featurize slices and
    host Reserve plugin calls appear in the registry summary after enough
    batches for the sampling gate to fire."""
    from kubernetes_tpu.api.wrappers import make_node, make_pod
    from kubernetes_tpu.scheduler import TPUScheduler

    s = TPUScheduler(batch_size=2)
    s.add_node(
        make_node("n1").capacity({"cpu": "64", "memory": "64Gi", "pods": 110}).obj()
    )
    for i in range(30):  # ≥10 batches → the 1-in-10 gate fires
        s.add_pod(make_pod(f"p{i}").req({"cpu": "100m"}).label("app", f"a{i}").obj())
        s.schedule_all_pending()
    series = s.metrics.registry.summary()["plugin_execution_duration_seconds"]
    assert any(k.endswith("/Featurize") for k in series), series
    # Each sampled series carries counts and latency quantiles.
    sample = next(iter(series.values()))
    assert sample["count"] >= 1 and "p99" in sample
