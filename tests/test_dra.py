"""DynamicResources (DRA): claim-gated scheduling with counted devices.

Mirrors the scheduler-relevant semantics of
pkg/scheduler/framework/plugins/dynamicresources/: missing claims gate the
pod, allocated claims pin it, unallocated claims demand free devices."""

from kubernetes_tpu.api import types as t
from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.scheduler import TPUScheduler


def gpu_cluster(s: TPUScheduler, counts=(2, 1)):
    for i, cnt in enumerate(counts):
        s.add_node(
            make_node(f"n{i}").capacity({"cpu": "16", "memory": "64Gi", "pods": 110}).obj()
        )
        if cnt:
            s.add_resource_slice(
                t.ResourceSlice(node_name=f"n{i}", device_class="gpu.example.com", count=cnt)
            )


def claim(name: str, count: int = 1) -> t.ResourceClaim:
    return t.ResourceClaim(name=name, device_class="gpu.example.com", count=count)


def claim_pod(name: str, claim_name: str) -> t.Pod:
    return make_pod(name).req({"cpu": "1"}).resource_claim(claim_name).obj()


def test_claims_gate_until_devices_fit():
    s = TPUScheduler(batch_size=8)
    gpu_cluster(s, counts=(2, 0))  # only n0 has devices
    for i in range(3):
        s.add_resource_claim(claim(f"c{i}"))
        s.add_pod(claim_pod(f"p{i}", f"c{i}"))
    out = {o.pod.name: o.node_name for o in s.schedule_all_pending()}
    placed = [n for n in out.values() if n]
    # 2 devices on n0 → exactly 2 pods schedule, both on n0.
    assert len(placed) == 2 and set(placed) == {"n0"}
    assert s.builder.host_mirror_equal()
    # Allocations recorded: both claims pinned to n0.
    allocated = [c for c in s.builder.dra.claims.values() if c.allocated_node]
    assert len(allocated) == 2 and all(c.allocated_node == "n0" for c in allocated)


def test_missing_claim_gates_pod_until_claim_appears():
    s = TPUScheduler(batch_size=8)
    gpu_cluster(s)
    s.add_pod(claim_pod("p", "late-claim"))
    out = s.schedule_all_pending()
    assert out[0].node_name is None
    assert out[0].diagnosis.unschedulable_plugins == {"DynamicResources"}
    # The claim arriving emits CLAIM_ADD → the pod wakes and schedules.
    s.add_resource_claim(claim("late-claim"))
    out2 = s.schedule_all_pending(wait_backoff=True)
    assert [o.node_name for o in out2 if o.node_name]


def test_allocated_claim_pins_second_pod():
    """A shared, already-allocated claim pins later pods to its node."""
    s = TPUScheduler(batch_size=8)
    gpu_cluster(s, counts=(1, 1))
    s.add_resource_claim(claim("shared"))
    s.add_pod(claim_pod("first", "shared"))
    out1 = {o.pod.name: o.node_name for o in s.schedule_all_pending()}
    node = out1["first"]
    assert node is not None
    s.add_pod(claim_pod("second", "shared"))
    out2 = {o.pod.name: o.node_name for o in s.schedule_all_pending()}
    assert out2["second"] == node  # pinned, despite free devices elsewhere


def test_device_freed_on_pod_delete():
    s = TPUScheduler(batch_size=8)
    gpu_cluster(s, counts=(1, 0))
    s.add_resource_claim(claim("c0"))
    s.add_resource_claim(claim("c1"))
    s.add_pod(claim_pod("p0", "c0"))
    assert [o.node_name for o in s.schedule_all_pending()] == ["n0"]
    s.add_pod(claim_pod("p1", "c1"))
    out = s.schedule_all_pending()
    assert out[0].node_name is None  # device occupied
    # Deleting p0 releases its reservation → c0 deallocates → device free.
    s.delete_pod("default/p0")
    out2 = s.schedule_all_pending(wait_backoff=True)
    assert [o.node_name for o in out2 if o.node_name] == ["n0"]
    assert s.builder.host_mirror_equal()


def test_slice_before_node_replays():
    s = TPUScheduler(batch_size=8)
    s.add_resource_slice(
        t.ResourceSlice(node_name="late", device_class="gpu.example.com", count=1)
    )
    s.add_node(make_node("late").capacity({"cpu": "8", "pods": 110}).obj())
    s.add_resource_claim(claim("c"))
    s.add_pod(claim_pod("p", "c"))
    assert [o.node_name for o in s.schedule_all_pending()] == ["late"]


def test_shared_claim_coschedules_and_releases_once():
    """Two pods sharing one count-1 claim co-schedule on a cap-1 node (the
    claim's devices charge once), and the device frees only when the LAST
    sharer leaves (r2 review: per-pod accounting diverged from the claim
    catalog)."""
    s = TPUScheduler(batch_size=8)
    gpu_cluster(s, counts=(1,))
    s.add_resource_claim(claim("shared"))
    s.add_pod(claim_pod("a", "shared"))
    s.add_pod(claim_pod("b", "shared"))
    out = {o.pod.name: o.node_name for o in s.schedule_all_pending(wait_backoff=True)}
    assert out == {"a": "n0", "b": "n0"}
    assert s.builder.host_mirror_equal()
    assert int(s.builder.host["dra_alloc"].max()) == 1  # one claim, one device
    # First sharer leaves: claim still reserved by b, device still taken.
    s.delete_pod("default/a")
    assert int(s.builder.host["dra_alloc"].max()) == 1
    s.add_resource_claim(claim("want"))
    s.add_pod(claim_pod("c", "want"))
    out2 = s.schedule_all_pending(wait_backoff=True)
    assert all(o.node_name is None for o in out2)  # no free device, no livelock
    assert "default/c" not in [o.pod.uid for o in out2 if o.node_name]
    # Last sharer leaves: device frees, c schedules.
    s.delete_pod("default/b")
    out3 = s.schedule_all_pending(wait_backoff=True)
    assert [o.node_name for o in out3 if o.node_name] == ["n0"]


def test_dra_device_shortage_is_preemptible():
    """A node failing only on DRA device shortage IS a preemption candidate
    (r2 review: the resolvable-op contract); victims' claim reservations
    release through the full deletion path."""
    s = TPUScheduler(batch_size=8)
    gpu_cluster(s, counts=(1,))
    s.add_resource_claim(claim("held"))
    s.add_pod(
        make_pod("holder").req({"cpu": "1"}).resource_claim("held").priority(1).obj()
    )
    assert [o.node_name for o in s.schedule_all_pending(wait_backoff=True)] == ["n0"]
    s.add_resource_claim(claim("wanted"))
    s.add_pod(
        make_pod("vip").req({"cpu": "1"}).resource_claim("wanted").priority(100).obj()
    )
    out = s.schedule_all_pending(wait_backoff=True)
    assert {o.pod.name: o.node_name for o in out if o.node_name} == {"vip": "n0"}
    assert "default/holder" not in s.cache.pods
    assert s.builder.dra.claims["default/held"].allocated_node == ""  # released


def test_external_allocation_charges_devices_once():
    """An informer-delivered allocated claim consumes devices immediately,
    and a local pod reserving the SAME claim must not double-charge
    (review findings r4: phantom-reservation accounting)."""
    s = TPUScheduler(batch_size=4)
    gpu_cluster(s, counts=(2,))  # n0 publishes 2 gpu devices
    ext = t.ResourceClaim(
        name="ext", device_class="gpu.example.com", count=1, allocated_node="n0",
        reserved_for=("other-scheduler/pod",),
    )
    s.add_resource_claim(ext)
    # One device consumed externally: a 2-device claim no longer fits.
    s.add_resource_claim(claim("big", count=2))
    s.add_pod(claim_pod("pbig", "big"))
    outs = s.schedule_all_pending()
    assert not [o for o in outs if o.node_name], outs
    # A 1-device claim still fits (free = 2 - 1).
    s.add_resource_claim(claim("one", count=1))
    s.add_pod(claim_pod("pone", "one"))
    (o,) = [o for o in s.schedule_all_pending() if o.pod.name == "pone"]
    assert o.node_name == "n0"
    # A local pod reserving the EXTERNAL claim: no double charge — the
    # node must still show exactly 2 consumed (1 ext + 1 local).
    s.add_pod(claim_pod("pext", "ext"))
    (o2,) = [o for o in s.schedule_all_pending() if o.pod.name == "pext"]
    assert o2.node_name == "n0"
    row = s.cache.nodes["n0"].row
    cid = s.builder.interns.device_classes.id("gpu.example.com")
    assert s.builder.host["dra_alloc"][cid, row] == 2
    # Deleting the local reserver must NOT free the external device.
    s.delete_pod(o2.pod.uid)
    assert s.builder.host["dra_alloc"][cid, row] == 2
    assert s.builder.host_mirror_equal()


def test_allocated_claim_before_node_replays():
    """Claim-before-node informer race: the allocation charge parks and
    replays when the node arrives (review finding r4-2)."""
    s = TPUScheduler(batch_size=4)
    s.add_resource_claim(
        t.ResourceClaim(name="early", device_class="gpu.example.com", count=2,
                        allocated_node="late-node",
                        reserved_for=("elsewhere/pod",))
    )
    s.add_resource_slice(
        t.ResourceSlice(node_name="late-node", device_class="gpu.example.com", count=2)
    )
    s.add_node(
        make_node("late-node").capacity({"cpu": "8", "pods": 110}).obj()
    )
    # Both devices are consumed by the external allocation.
    s.add_resource_claim(claim("want", count=1))
    s.add_pod(claim_pod("p", "want"))
    assert not [o for o in s.schedule_all_pending() if o.node_name]
    assert s.builder.host_mirror_equal()


def test_stale_unallocated_echo_ignored():
    """A watch echo of the pre-allocation claim object must not release a
    locally-reserved allocation (review finding r4-3: assume-cache
    version semantics)."""
    s = TPUScheduler(batch_size=4)
    gpu_cluster(s, counts=(1,))
    s.add_resource_claim(claim("c", count=1))
    s.add_pod(claim_pod("p", "c"))
    (o,) = [o for o in s.schedule_all_pending() if o.pod.name == "p"]
    assert o.node_name == "n0"
    # Stale echo: the claim as it looked BEFORE allocation.
    s.add_resource_claim(claim("c", count=1))
    # The devices stay consumed: another 1-device claim cannot land.
    s.add_resource_claim(claim("c2", count=1))
    s.add_pod(claim_pod("p2", "c2"))
    assert not [
        o for o in s.schedule_all_pending() if o.pod.name == "p2" and o.node_name
    ]
    assert s.builder.host_mirror_equal()
