"""DynamicResources (DRA): claim-gated scheduling with counted devices.

Mirrors the scheduler-relevant semantics of
pkg/scheduler/framework/plugins/dynamicresources/: missing claims gate the
pod, allocated claims pin it, unallocated claims demand free devices."""

from kubernetes_tpu.api import types as t
from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.scheduler import TPUScheduler


def gpu_cluster(s: TPUScheduler, counts=(2, 1)):
    for i, cnt in enumerate(counts):
        s.add_node(
            make_node(f"n{i}").capacity({"cpu": "16", "memory": "64Gi", "pods": 110}).obj()
        )
        if cnt:
            s.add_resource_slice(
                t.ResourceSlice(node_name=f"n{i}", device_class="gpu.example.com", count=cnt)
            )


def claim(name: str, count: int = 1) -> t.ResourceClaim:
    return t.ResourceClaim(name=name, device_class="gpu.example.com", count=count)


def claim_pod(name: str, claim_name: str) -> t.Pod:
    return make_pod(name).req({"cpu": "1"}).resource_claim(claim_name).obj()


def test_claims_gate_until_devices_fit():
    s = TPUScheduler(batch_size=8)
    gpu_cluster(s, counts=(2, 0))  # only n0 has devices
    for i in range(3):
        s.add_resource_claim(claim(f"c{i}"))
        s.add_pod(claim_pod(f"p{i}", f"c{i}"))
    out = {o.pod.name: o.node_name for o in s.schedule_all_pending()}
    placed = [n for n in out.values() if n]
    # 2 devices on n0 → exactly 2 pods schedule, both on n0.
    assert len(placed) == 2 and set(placed) == {"n0"}
    assert s.builder.host_mirror_equal()
    # Allocations recorded: both claims pinned to n0.
    allocated = [c for c in s.builder.dra.claims.values() if c.allocated_node]
    assert len(allocated) == 2 and all(c.allocated_node == "n0" for c in allocated)


def test_missing_claim_gates_pod_until_claim_appears():
    s = TPUScheduler(batch_size=8)
    gpu_cluster(s)
    s.add_pod(claim_pod("p", "late-claim"))
    out = s.schedule_all_pending()
    assert out[0].node_name is None
    assert out[0].diagnosis.unschedulable_plugins == {"DynamicResources"}
    # The claim arriving emits CLAIM_ADD → the pod wakes and schedules.
    s.add_resource_claim(claim("late-claim"))
    out2 = s.schedule_all_pending(wait_backoff=True)
    assert [o.node_name for o in out2 if o.node_name]


def test_allocated_claim_pins_second_pod():
    """A shared, already-allocated claim pins later pods to its node."""
    s = TPUScheduler(batch_size=8)
    gpu_cluster(s, counts=(1, 1))
    s.add_resource_claim(claim("shared"))
    s.add_pod(claim_pod("first", "shared"))
    out1 = {o.pod.name: o.node_name for o in s.schedule_all_pending()}
    node = out1["first"]
    assert node is not None
    s.add_pod(claim_pod("second", "shared"))
    out2 = {o.pod.name: o.node_name for o in s.schedule_all_pending()}
    assert out2["second"] == node  # pinned, despite free devices elsewhere


def test_device_freed_on_pod_delete():
    s = TPUScheduler(batch_size=8)
    gpu_cluster(s, counts=(1, 0))
    s.add_resource_claim(claim("c0"))
    s.add_resource_claim(claim("c1"))
    s.add_pod(claim_pod("p0", "c0"))
    assert [o.node_name for o in s.schedule_all_pending()] == ["n0"]
    s.add_pod(claim_pod("p1", "c1"))
    out = s.schedule_all_pending()
    assert out[0].node_name is None  # device occupied
    # Deleting p0 releases its reservation → c0 deallocates → device free.
    s.delete_pod("default/p0")
    out2 = s.schedule_all_pending(wait_backoff=True)
    assert [o.node_name for o in out2 if o.node_name] == ["n0"]
    assert s.builder.host_mirror_equal()


def test_slice_before_node_replays():
    s = TPUScheduler(batch_size=8)
    s.add_resource_slice(
        t.ResourceSlice(node_name="late", device_class="gpu.example.com", count=1)
    )
    s.add_node(make_node("late").capacity({"cpu": "8", "pods": 110}).obj())
    s.add_resource_claim(claim("c"))
    s.add_pod(claim_pod("p", "c"))
    assert [o.node_name for o in s.schedule_all_pending()] == ["late"]


def test_shared_claim_coschedules_and_releases_once():
    """Two pods sharing one count-1 claim co-schedule on a cap-1 node (the
    claim's devices charge once), and the device frees only when the LAST
    sharer leaves (r2 review: per-pod accounting diverged from the claim
    catalog)."""
    s = TPUScheduler(batch_size=8)
    gpu_cluster(s, counts=(1,))
    s.add_resource_claim(claim("shared"))
    s.add_pod(claim_pod("a", "shared"))
    s.add_pod(claim_pod("b", "shared"))
    out = {o.pod.name: o.node_name for o in s.schedule_all_pending(wait_backoff=True)}
    assert out == {"a": "n0", "b": "n0"}
    assert s.builder.host_mirror_equal()
    assert int(s.builder.host["dra_alloc"].max()) == 1  # one claim, one device
    # First sharer leaves: claim still reserved by b, device still taken.
    s.delete_pod("default/a")
    assert int(s.builder.host["dra_alloc"].max()) == 1
    s.add_resource_claim(claim("want"))
    s.add_pod(claim_pod("c", "want"))
    out2 = s.schedule_all_pending(wait_backoff=True)
    assert all(o.node_name is None for o in out2)  # no free device, no livelock
    assert "default/c" not in [o.pod.uid for o in out2 if o.node_name]
    # Last sharer leaves: device frees, c schedules.
    s.delete_pod("default/b")
    out3 = s.schedule_all_pending(wait_backoff=True)
    assert [o.node_name for o in out3 if o.node_name] == ["n0"]


def test_dra_device_shortage_is_preemptible():
    """A node failing only on DRA device shortage IS a preemption candidate
    (r2 review: the resolvable-op contract); victims' claim reservations
    release through the full deletion path."""
    s = TPUScheduler(batch_size=8)
    gpu_cluster(s, counts=(1,))
    s.add_resource_claim(claim("held"))
    s.add_pod(
        make_pod("holder").req({"cpu": "1"}).resource_claim("held").priority(1).obj()
    )
    assert [o.node_name for o in s.schedule_all_pending(wait_backoff=True)] == ["n0"]
    s.add_resource_claim(claim("wanted"))
    s.add_pod(
        make_pod("vip").req({"cpu": "1"}).resource_claim("wanted").priority(100).obj()
    )
    out = s.schedule_all_pending(wait_backoff=True)
    assert {o.pod.name: o.node_name for o in out if o.node_name} == {"vip": "n0"}
    assert "default/holder" not in s.cache.pods
    assert s.builder.dra.claims["default/held"].allocated_node == ""  # released
