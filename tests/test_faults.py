"""Fault-injection harness: the dispatch path must DEGRADE, not die.

The acceptance claims of the robustness tentpole (ISSUE 2):

- a HUNG sidecar (alive process, wedged dispatch) trips the per-call
  deadline and then the circuit breaker, and the host completes the same
  workload in degraded mode — host-side evaluation on the mirrored store
  — with IDENTICAL bindings;
- a POISON pod (engine dispatch raises whenever its batch contains it)
  is quarantined while the rest of its batch binds;
- a SECOND crash during the resync replay is retried, not fatal;
- a malformed frame gets an error response and the connection keeps
  serving its healthy sibling requests;
- the whole fault matrix (scripts/run_fault_matrix.py) leaves binding
  decisions unchanged — the fast subset runs here in tier-1.
"""

import os
import socket
import struct
import sys
import tempfile
import time

import pytest

from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.faults import EngineFault, FaultPlan
from kubernetes_tpu.framework.config import fit_only_profile
from kubernetes_tpu.scheduler import TPUScheduler
from kubernetes_tpu.queue import SchedulingQueue
from kubernetes_tpu.sidecar import server as sidecar
from kubernetes_tpu.sidecar import sidecar_pb2 as pb
from kubernetes_tpu.sidecar.host import ResyncingClient
from kubernetes_tpu.sidecar.server import SidecarClient, SidecarServer

_LEN = struct.Struct(">I")


def _node(name, cpu="4"):
    return make_node(name).capacity(
        {"cpu": cpu, "memory": "16Gi", "pods": 110}
    ).obj()


def _mk_sched(**kw):
    kw.setdefault("profile", fit_only_profile())
    kw.setdefault("batch_size", 8)
    kw.setdefault("chunk_size", 1)
    return TPUScheduler(**kw)


def _serve(**kw):
    path = tempfile.mktemp(suffix=".sock")
    srv = SidecarServer(path, scheduler=_mk_sched(**kw))
    srv.serve_background()
    return path, srv


# ---------------------------------------------------------------------------
# FaultPlan determinism


def _frame(op):
    env = pb.Envelope()
    if op == "add":
        env.add.kind = "Node"
        env.add.object_json = b"{}"
    else:
        getattr(env, op).SetInParent()
    payload = env.SerializeToString()
    return _LEN.pack(len(payload)) + payload


def _drive_plan(plan):
    a, b = socket.socketpair()
    wrapped = plan.wrap(a)
    try:
        for op in ("add", "add", "schedule", "schedule", "add"):
            try:
                wrapped.sendall(_frame(op))
            except OSError:
                pass  # a crash rule severed the socket; keep counting ops
    finally:
        for s in (a, b):
            try:
                s.close()
            except OSError:
                pass
    return list(plan.fired)


def test_fault_plan_fires_deterministically_and_replays():
    plan = (
        FaultPlan(seed=3)
        .add_rule("slow", op="add", nth=2, delay_s=0.0)
        .add_rule("crash", op="schedule", nth=2)
    )
    fired = _drive_plan(plan)
    assert fired == [("slow", "add", 2), ("crash", "schedule", 2)]
    # replay(): same rules + seed → identical firing sequence.
    assert _drive_plan(plan.replay()) == fired


def test_fault_rule_every_with_times_cap():
    plan = FaultPlan().add_rule("hang", op="add", nth=1, every=True, times=2)
    a, b = socket.socketpair()
    wrapped = plan.wrap(a)
    b.settimeout(0.5)
    try:
        wrapped.sendall(_frame("add"))  # swallowed (1)
        wrapped.sendall(_frame("add"))  # swallowed (2)
        wrapped.sendall(_frame("add"))  # delivered: cap exhausted
        data = b.recv(1 << 16)
        assert data == _frame("add")  # exactly one frame came through
    finally:
        a.close()
        b.close()
    assert plan.fired == [("hang", "add", 1), ("hang", "add", 2)]


# ---------------------------------------------------------------------------
# Hung sidecar → deadline + breaker → degraded mode, identical bindings


def _workload(client, n_nodes=3, n_pods=5):
    for i in range(n_nodes):
        client.add("Node", _node(f"n{i}"))
    pods = [make_pod(f"p{i}").req({"cpu": "2"}).obj() for i in range(n_pods)]
    res = client.schedule(pods, drain=True)
    return {r.pod_uid: r.node_name for r in res}


def test_hung_sidecar_trips_breaker_and_degrades_with_identical_bindings():
    # Baseline: healthy wire dispatch.
    path, srv = _serve()
    client = ResyncingClient(path, deadline_s=30.0)
    try:
        baseline = _workload(client)
    finally:
        client.close()
        srv.close()
    assert all(baseline.values())  # 5×2cpu over 3×4cpu nodes: all bind

    # Same workload against a sidecar whose schedule dispatch hangs
    # forever (health hangs too, so the background probe cannot recover
    # mid-test and every dispatch stays host-side).
    plan = (
        FaultPlan(seed=1)
        .add_rule("hang", op="schedule", every=True)
        .add_rule("hang", op="health", every=True)
    )
    path, srv = _serve()
    client = ResyncingClient(
        path,
        deadline_s=0.4,
        retry_interval_s=0.01,
        probe_interval_s=0.05,
        breaker_threshold=3,
        socket_wrapper=plan.wrap,
        fallback_factory=_mk_sched,
    )
    try:
        degraded = _workload(client)
        assert client.degraded
        assert degraded == baseline  # bit-identical decisions, host-side
        reg = client.registry
        assert reg.counter("scheduler_degraded_dispatches_total").total() == 1
        assert reg.counter("scheduler_sidecar_breaker_trips_total").total() == 1
        assert reg.counter("scheduler_sidecar_call_timeouts_total").total() >= 3
        assert reg.gauge("scheduler_sidecar_state").get(state="degraded") == 1
        assert reg.gauge("scheduler_sidecar_state").get(state="healthy") == 0
        # Still making progress while degraded: capacity accounting holds
        # (6th 2-cpu pod takes the last slot, the 7th finds none).
        (r6,) = client.schedule([make_pod("p5").req({"cpu": "2"}).obj()])
        assert r6.node_name
        (r7,) = client.schedule([make_pod("p6").req({"cpu": "2"}).obj()])
        assert r7.node_name == ""
        assert reg.counter("scheduler_degraded_dispatches_total").total() == 3
    finally:
        client.close()
        srv.close()


def test_degraded_host_recovers_when_sidecar_heals():
    # The hang clears after 3 schedule frames (times=3): the breaker
    # opens, the workload completes host-side, the background probe finds
    # the sidecar answering, and the next dispatch replays the store —
    # including the bindings made WHILE degraded — and resumes the wire.
    plan = FaultPlan(seed=2).add_rule(
        "hang", op="schedule", nth=1, every=True, times=3
    )
    path, srv = _serve()
    client = ResyncingClient(
        path,
        deadline_s=0.4,
        retry_interval_s=0.01,
        probe_interval_s=0.05,
        breaker_threshold=3,
        socket_wrapper=plan.wrap,
        fallback_factory=_mk_sched,
    )
    try:
        bound = _workload(client)  # degrades mid-call, completes host-side
        assert client.degraded and all(bound.values())
        deadline = time.monotonic() + 5.0
        while client.degraded and time.monotonic() < deadline:
            time.sleep(0.05)
            client.add("Node", _node("late", cpu="2"))  # any call recovers
        assert not client.degraded
        assert client.registry.gauge(
            "scheduler_sidecar_state"
        ).get(state="healthy") == 1
        # The aggressive 0.4s deadline existed to trip the breaker fast;
        # the recovered sidecar's FIRST batch pays its XLA compile, which
        # must not be misread as another hang.
        client.deadline_s = 30.0
        client._client.sock.settimeout(30.0)
        # Wire dispatch resumed AND the degraded-mode bindings were
        # replayed: 3×4cpu held 5×2cpu pods, so exactly one 2-cpu slot
        # remains (plus the late 2-cpu node's one slot).
        r = client.schedule(
            [make_pod(f"q{i}").req({"cpu": "2"}).obj() for i in range(3)]
        )
        placed = [x for x in r if x.node_name]
        assert len(placed) == 2, [(x.pod_uid, x.node_name) for x in r]
        # The sidecar agrees with the host store about every binding.
        dump = client.dump()
        for uid, node in bound.items():
            assert dump["pods"][uid]["node"] == node
    finally:
        client.close()
        srv.close()


def test_breaker_trip_on_a_remove_degrades_without_crashing():
    # The breaker can open on the REMOVE call itself.  The store already
    # dropped the node before dispatch, so the just-built fallback never
    # contained it — the degraded removal must tolerate that, not crash
    # the resilience path with a KeyError.
    plan = (
        FaultPlan(seed=6)
        .add_rule("hang", op="remove", every=True)
        .add_rule("hang", op="health", every=True)
    )
    path, srv = _serve()
    client = ResyncingClient(
        path,
        deadline_s=0.4,
        retry_interval_s=0.01,
        probe_interval_s=0.05,
        breaker_threshold=3,
        socket_wrapper=plan.wrap,
        fallback_factory=_mk_sched,
    )
    try:
        client.add("Node", _node("n0"))
        client.add("Node", _node("n1"))
        client.remove("Node", "n1")  # hangs → breaker → degraded, no raise
        assert client.degraded
        # An observability scrape while degraded keeps the host series.
        text = client.metrics()
        assert "scheduler_sidecar_breaker_trips_total 1" in text
        assert 'scheduler_sidecar_state{state="degraded"} 1' in text
        # The removal took effect host-side: only n0 remains to bind on.
        res = client.schedule(
            [make_pod(f"p{i}").req({"cpu": "2"}).obj() for i in range(3)]
        )
        assert sorted(r.node_name for r in res) == ["", "n0", "n0"]
    finally:
        client.close()
        srv.close()


def test_degraded_window_removals_reconciled_on_recovery():
    # A HUNG sidecar keeps its state: deletes applied while the breaker
    # was open never reached it, so the recovery replay must reconcile
    # them — otherwise a later batch can bind onto a phantom node.
    plan = FaultPlan(seed=5).add_rule(
        "hang", op="schedule", nth=1, every=True, times=3
    )
    path, srv = _serve()
    client = ResyncingClient(
        path,
        deadline_s=0.4,
        retry_interval_s=0.01,
        probe_interval_s=0.05,
        breaker_threshold=3,
        socket_wrapper=plan.wrap,
        fallback_factory=_mk_sched,
    )
    try:
        client.add("Node", _node("n0", cpu="8"))
        client.add("Node", _node("n1", cpu="1"))  # too small for any pod
        res = client.schedule(
            [make_pod(f"p{i}").req({"cpu": "2"}).obj() for i in range(2)]
        )
        assert client.degraded
        assert all(r.node_name == "n0" for r in res)
        client.remove("Node", "n1")  # sidecar never hears this
        deadline = time.monotonic() + 5.0
        while client.degraded and time.monotonic() < deadline:
            time.sleep(0.05)
            client.events()
        assert not client.degraded
        dump = client.dump()
        assert set(dump["nodes"]) == {"n0"}, dump["nodes"]  # no phantom n1
        for i in range(2):
            assert dump["pods"][f"default/p{i}"]["node"] == "n0"
    finally:
        client.close()
        srv.close()


def test_node_removal_purges_its_bound_pods_from_the_replay_store():
    # remove_node vaporizes the node's pods from scheduling state; the
    # host store must mirror that, or the post-restart replay re-adds
    # pods bound to a node that no longer exists and the replay wedges
    # on a server-side error.
    path, srv = _serve()
    client = ResyncingClient(path, max_reconnect_s=5.0, deadline_s=30.0)
    try:
        client.add("Node", _node("gone"))
        (r,) = client.schedule([make_pod("rider").req({"cpu": "2"}).obj()])
        assert r.node_name == "gone"
        client.remove("Node", "gone")
        srv.close()
        srv = SidecarServer(path, scheduler=_mk_sched())
        srv.serve_background()
        dump = client.dump()  # triggers the resync replay
        assert client.resyncs == 1
        assert dump["nodes"] == {} and dump["pods"] == {}
    finally:
        client.close()
        srv.close()


# ---------------------------------------------------------------------------
# Poison-batch quarantine


@pytest.mark.parametrize("attributed", [True, False])
def test_poison_pod_quarantined_and_healthy_batch_binds(attributed):
    s = _mk_sched(queue=SchedulingQueue(initial_backoff_s=0.02))
    s.add_node(_node("n0", cpu="8"))
    s.add_node(_node("n1", cpu="8"))
    plan = FaultPlan().add_rule(
        "engine", pod="default/bad", attributed=attributed
    )
    plan.install_engine(s)
    pods = [make_pod(f"p{i}").req({"cpu": "1"}).obj() for i in range(4)]
    pods.insert(2, make_pod("bad").req({"cpu": "1"}).obj())
    for p in pods:
        s.add_pod(p)
    out = s.schedule_all_pending()
    by_uid = {o.pod.uid: o for o in out}
    assert by_uid["default/bad"].node_name is None
    assert by_uid["default/bad"].diagnosis.unschedulable_plugins == {
        "EngineFault"
    }
    for i in range(4):
        assert by_uid[f"default/p{i}"].node_name, f"p{i} did not bind"
    assert s.queue.depths()["quarantine"] == 1
    assert s.queue.quarantined() == ["default/bad"]
    reg = s.metrics.registry
    assert reg.counter("scheduler_quarantined_pods_total").total() == 1
    faults = reg.counter("scheduler_engine_faults_total").total()
    # Attribution short-circuits the bisect; anonymous exceptions pay
    # one recovery per failing sub-batch on the way down.
    assert faults == 1 if attributed else faults > 1
    ev = [e for e in s.events.list() if e["reason"] == "FailedScheduling"]
    assert any("quarantined" in e["note"] and "default/bad" in e["object"]
               for e in ev)

    # Release: the pod re-enters through the backoff machinery; with the
    # fault gone it binds like any other pod.
    plan.rules.clear()
    assert s.queue.release_quarantine() == 1
    time.sleep(0.05)
    out2 = s.schedule_all_pending(wait_backoff=True)
    assert {o.pod.uid: o.node_name for o in out2}["default/bad"]
    assert s.queue.depths()["quarantine"] == 0


def test_poison_pod_quarantined_over_the_wire():
    path = tempfile.mktemp(suffix=".sock")
    sched = _mk_sched()
    FaultPlan().add_rule("engine", pod="default/bad").install_engine(sched)
    srv = SidecarServer(path, scheduler=sched)
    srv.serve_background()
    client = SidecarClient(path)
    try:
        client.add("Node", _node("n0", cpu="8"))
        pods = [make_pod(f"p{i}").req({"cpu": "1"}).obj() for i in range(3)]
        pods.append(make_pod("bad").req({"cpu": "1"}).obj())
        results = {r.pod_uid: r for r in client.schedule(pods, drain=True)}
        assert results["default/bad"].node_name == ""
        assert list(results["default/bad"].unschedulable_plugins) == [
            "EngineFault"
        ]
        for i in range(3):
            assert results[f"default/p{i}"].node_name
        dump = client.dump()
        assert dump["queue"]["quarantine"] == ["default/bad"]
        assert 'scheduler_pending_pods{queue="quarantine"} 1' in client.metrics()
    finally:
        client.close()
        srv.close()


def test_transient_engine_fault_does_not_quarantine_whole_batch():
    # An UNKEYED one-shot engine fault (n-th dispatch raises once, e.g. a
    # flaky allocator): the bisect retries succeed and nobody is
    # quarantined.
    s = _mk_sched()
    s.add_node(_node("n0", cpu="8"))
    plan = FaultPlan().add_rule("engine", nth=1)
    plan.install_engine(s)
    for i in range(4):
        s.add_pod(make_pod(f"p{i}").req({"cpu": "1"}).obj())
    out = s.schedule_all_pending()
    assert all(o.node_name for o in out)
    assert s.queue.depths()["quarantine"] == 0
    assert s.metrics.registry.counter(
        "scheduler_engine_faults_total"
    ).total() == 1


def test_engine_fault_carries_pod_attribution():
    exc = EngineFault("boom", ("u1", "u2"))
    assert exc.pod_uids == ("u1", "u2")


# ---------------------------------------------------------------------------
# Mid-replay second crash (satellite: bounded reconnect loop)


def test_second_crash_during_replay_still_recovers():
    path, srv = _serve()
    # The 5th add frame crosses the wire during the post-restart REPLAY
    # (2 setup adds + the replay's 2 node adds precede it, so it is the
    # first bound-pod replay): the connection is severed mid-replay — the
    # old single-retry hole — and the bounded loop must reconnect and
    # replay again instead of surfacing OSError.
    plan = FaultPlan(seed=4).add_rule("crash", op="add", nth=5)
    client = ResyncingClient(
        path,
        max_reconnect_s=5.0,
        retry_interval_s=0.01,
        deadline_s=30.0,
        socket_wrapper=plan.wrap,
    )
    try:
        client.add("Node", _node("n0"))
        client.add("Node", _node("n1"))
        pods = [make_pod(f"a{i}").req({"cpu": "2"}).obj() for i in range(2)]
        bound1 = {r.pod_uid: r.node_name for r in client.schedule(pods)}
        assert all(bound1.values())

        srv.close()
        srv = SidecarServer(path, scheduler=_mk_sched())
        srv.serve_background()

        res = client.schedule([make_pod("b0").req({"cpu": "2"}).obj()])
        assert {r.pod_uid: r.node_name for r in res}["default/b0"]
        # The crash DID fire mid-replay (resyncs counts only COMPLETED
        # replays: the torn one doesn't, its successful retry does).
        assert plan.fired == [("crash", "add", 5)]
        assert client.resyncs == 1
        assert not client.degraded  # two failures < breaker threshold
        # Accounting survived both the restart and the torn replay.
        dump = client.dump()
        for uid, node in bound1.items():
            assert dump["pods"][uid]["node"] == node
        assert dump["mirror_equal"]
    finally:
        client.close()
        srv.close()


def test_reissued_schedule_reports_committed_bindings():
    # At-least-once completion: the host times out, loses the response,
    # and re-issues the call for pods the first execution already bound.
    # The re-issued call must answer with the COMMITTED placement, not
    # silently drop the pod (and never double-bind it).
    path, srv = _serve()
    client = SidecarClient(path)
    try:
        client.add("Node", _node("n0"))
        p = make_pod("dup").req({"cpu": "2"}).obj()
        (r1,) = client.schedule([p], drain=True)
        assert r1.node_name
        (r2,) = client.schedule([p], drain=True)
        assert r2.pod_uid == p.uid and r2.node_name == r1.node_name
        # Bound once: the node holds one copy of the delta.
        dump = client.dump()
        assert dump["nodes"]["n0"]["pods"] == [p.uid]
        assert dump["mirror_equal"]
    finally:
        client.close()
        srv.close()


def test_bound_pod_upsert_with_different_node_relocates():
    # Host truth can REBIND a pod the local engine placed elsewhere (a
    # stale buffered schedule frame processed after the host already
    # bound the pod in degraded mode; the recovery replay then ships the
    # authoritative binding).  The upsert must relocate — accounting
    # moves with it and the device mirror follows.
    s = _mk_sched()
    s.add_node(_node("a", cpu="4"))
    s.add_node(_node("b", cpu="4"))
    p = make_pod("mv").req({"cpu": "2"}).node("a").obj()
    s.add_pod(p)
    assert s.cache.pods["default/mv"].node_name == "a"
    import copy

    moved = copy.deepcopy(p)
    moved.spec.node_name = "b"
    s.update_pod(moved)
    assert s.cache.pods["default/mv"].node_name == "b"
    assert "default/mv" not in s.cache.nodes["a"].pods
    assert "default/mv" in s.cache.nodes["b"].pods
    assert s.builder.host_mirror_equal()
    # Capacity followed the move: a 4-cpu pod fits only the vacated "a"
    # ("b" holds mv's 2 of 4); a second one fits nowhere.
    for i in range(2):
        s.add_pod(make_pod(f"f{i}").req({"cpu": "4"}).obj())
    placed = {o.pod.uid: o.node_name for o in s.schedule_all_pending()}
    assert placed["default/f0"] == "a" and placed["default/f1"] is None, placed


# ---------------------------------------------------------------------------
# Malformed frames: error response + resynchronization (satellite)


def _raw_call(sock, env):
    payload = env.SerializeToString()
    sock.sendall(_LEN.pack(len(payload)) + payload)
    return _read_response(sock)


def _read_response(sock):
    header = b""
    while len(header) < 4:
        chunk = sock.recv(4 - len(header))
        assert chunk, "connection severed"
        header += chunk
    (n,) = _LEN.unpack(header)
    buf = b""
    while len(buf) < n:
        buf += sock.recv(n - len(buf))
    env = pb.Envelope()
    env.ParseFromString(buf)
    return env


def test_garbage_frame_gets_error_response_and_siblings_survive():
    path, srv = _serve()
    sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    sock.connect(path)
    sock.settimeout(5.0)
    try:
        # A framing-intact but unparseable payload: error response, not a
        # severed connection.
        junk = b"\xff\xff\xff\xff\xff"
        sock.sendall(_LEN.pack(len(junk)) + junk)
        resp = _read_response(sock)
        assert "bad frame" in resp.response.error
        # The healthy sibling request on the SAME connection still works.
        env = pb.Envelope(seq=1)
        env.health.SetInParent()
        resp = _raw_call(sock, env)
        assert resp.seq == 1 and resp.response.health_json
        assert (
            srv.scheduler.metrics.registry.counter(
                "sidecar_malformed_frames_total"
            ).total() == 1
        )
    finally:
        sock.close()
        srv.close()


def test_oversized_frame_discarded_then_resynchronized(monkeypatch):
    monkeypatch.setattr(sidecar, "MAX_FRAME", 1024)
    monkeypatch.setattr(sidecar, "MAX_DISCARD", 4096)
    path, srv = _serve()
    sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    sock.connect(path)
    sock.settimeout(5.0)
    try:
        # Oversized but discardable: the server streams past it and keeps
        # the connection.
        sock.sendall(_LEN.pack(2000) + b"\x00" * 2000)
        resp = _read_response(sock)
        assert "frame too large" in resp.response.error
        env = pb.Envelope(seq=1)
        env.health.SetInParent()
        resp = _raw_call(sock, env)
        assert resp.seq == 1 and resp.response.health_json
        # Beyond the discard bound: a garbage header, connection drops
        # (clean EOF or RST depending on what the kernel buffered).
        sock.sendall(_LEN.pack(100_000) + b"\x00" * 16)
        try:
            data = sock.recv(4)
        except OSError:
            data = b""
        assert data == b""
    finally:
        sock.close()
        srv.close()


# ---------------------------------------------------------------------------
# Fault matrix (fast subset; the full grid lives in
# scripts/run_fault_matrix.py)


@pytest.mark.faults
def test_fault_matrix_fast_subset():
    sys.path.insert(
        0, os.path.join(os.path.dirname(__file__), "..", "scripts")
    )
    from run_fault_matrix import matrix_cases, run_matrix

    cases = matrix_cases(
        fault_kinds=("crash", "partial_write"), frame_kinds=("schedule",)
    ) + matrix_cases(fault_kinds=("slow",), frame_kinds=("add",))
    assert run_matrix(cases, verbose=False) == []
