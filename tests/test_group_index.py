"""Vectorized selector/term matching (intern.GroupIndex / TermIndex) must be
equivalent to the scalar reference paths (label_selector_matches /
groups_matching) — the same oracle pattern SURVEY §4 prescribes for the
device ops, applied to the featurization hot path."""

import random

import numpy as np

from kubernetes_tpu.api import types as t
from kubernetes_tpu.intern import InternTable
from kubernetes_tpu.ops.podtopologyspread import groups_matching
from kubernetes_tpu.snapshot import SnapshotBuilder


def _random_selector(rng) -> t.LabelSelector:
    # Vocabulary wide enough to cross the 64-column initial matrix capacity
    # (the incremental-growth/boundary paths are where the bugs live).
    kind = rng.randrange(5)
    key = f"k{rng.randrange(12)}"
    vals = tuple(f"v{rng.randrange(10)}" for _ in range(rng.randrange(1, 3)))
    if kind == 0:
        return t.LabelSelector(match_labels=((key, vals[0]),))
    if kind == 1:
        return t.LabelSelector(
            match_expressions=(t.LabelSelectorRequirement(key, t.OP_IN, vals),)
        )
    if kind == 2:
        return t.LabelSelector(
            match_expressions=(t.LabelSelectorRequirement(key, t.OP_NOT_IN, vals),)
        )
    if kind == 3:
        return t.LabelSelector(
            match_expressions=(t.LabelSelectorRequirement(key, t.OP_EXISTS),)
        )
    return t.LabelSelector(
        match_expressions=(
            t.LabelSelectorRequirement(key, t.OP_IN, vals),
            t.LabelSelectorRequirement(f"k{rng.randrange(4)}", t.OP_DOES_NOT_EXIST),
        )
    )


def _random_labels(rng) -> dict:
    return {
        f"k{i}": f"v{rng.randrange(10)}"
        for i in range(12)
        if rng.random() < 0.4
    }


def test_group_index_matches_scalar_reference():
    rng = random.Random(7)
    b = SnapshotBuilder()
    it = b.interns
    # Interleave group creation and matching so the incremental growth paths
    # (new pairs, new keys, capacity doubling) are all exercised.
    for round_ in range(30):
        for _ in range(5):
            ns = f"ns{rng.randrange(3)}"
            it.group_id(ns, _random_labels(rng))
        sel = _random_selector(rng)
        ns_ids = {it.namespaces.id(f"ns{rng.randrange(3)}")} if rng.random() < 0.5 else None
        want = groups_matching(it, len(it.groups), ns_ids, sel)
        got = b.group_index.match_selector(sel, ns_ids)
        assert np.array_equal(got, want[: got.shape[0]]), (round_, sel)
    # None selector selects nothing; empty selector selects everything.
    assert not b.group_index.match_selector(None).any()
    assert b.group_index.match_selector(t.LabelSelector()).all()


def test_match_selector_pair_interned_outside_sync():
    """A label pair interned past the matrix capacity by a NON-group path
    (term encoding, node rows) must read as carried-by-no-group, not crash
    (r3 review: IndexError at the power-of-two column boundary)."""
    b = SnapshotBuilder()
    it = b.interns
    it.group_id("default", {"app": "web"})
    b.group_index.sync()
    # Fill the pair vocabulary to (past) the initial 64-column capacity
    # without creating any new group.
    for i in range(70):
        it.label_pairs.id(("boundary", f"v{i}"))
    sel = t.LabelSelector(
        match_expressions=(
            t.LabelSelectorRequirement("boundary", t.OP_IN, ("v65",)),
        )
    )
    assert not b.group_index.match_selector(sel).any()
    sel2 = t.LabelSelector(match_labels=(("boundary", "v66"),))
    assert not b.group_index.match_selector(sel2).any()


def test_term_index_empty_in_values():
    """In with an empty value set matches nothing, regardless of whether
    the group was interned before or after the term."""
    b = SnapshotBuilder()
    it = b.interns
    g_before = it.group_id("default", {"app": "web"})
    term = t.PodAffinityTerm(
        label_selector=t.LabelSelector(
            match_expressions=(t.LabelSelectorRequirement("app", t.OP_IN, ()),)
        ),
        topology_key="z",
        namespaces=("default",),
    )
    tid = it.term_id(1, 1, term, "default")
    b.term_index.sync(b.ns_epoch)
    g_after = it.group_id("default", {"app": "db"})
    b.term_index.sync(b.ns_epoch)
    assert not b.term_index.column(g_before)[0][tid]
    assert not b.term_index.column(g_after)[0][tid]


def _scalar_term_match(it, builder, tid, gid) -> bool:
    from kubernetes_tpu.ops.interpodaffinity import _term_matches_pod

    ns, labels = it.group_labels(gid)
    pod = t.Pod(metadata=t.ObjectMeta(name="x", namespace=ns, labels=labels))
    return _term_matches_pod(it.terms.value(tid), pod, builder.namespace_labels)


def test_term_index_matches_scalar_reference():
    rng = random.Random(11)
    b = SnapshotBuilder()
    it = b.interns
    b.set_namespace_labels("ns0", {"team": "red"})
    b.set_namespace_labels("ns1", {"team": "blue"})
    for round_ in range(20):
        # New groups and terms arrive interleaved (the mid-batch pattern).
        for _ in range(4):
            it.group_id(f"ns{rng.randrange(3)}", _random_labels(rng))
        for _ in range(3):
            term = t.PodAffinityTerm(
                label_selector=_random_selector(rng),
                topology_key="topology.kubernetes.io/zone",
                namespaces=(f"ns{rng.randrange(3)}",) if rng.random() < 0.7 else (),
                namespace_selector=(
                    t.LabelSelector(match_labels=(("team", "red"),))
                    if rng.random() < 0.3
                    else None
                ),
            )
            it.term_id(rng.randrange(4), rng.randrange(1, 100), term, "ns0")
        b.term_index.sync(b.ns_epoch)
        for gid in range(len(it.groups)):
            col, _cats, _w = b.term_index.column(gid)
            for tid in range(len(it.terms)):
                want = _scalar_term_match(it, b, tid, gid)
                assert col[tid] == want, (round_, tid, gid, it.terms.value(tid))


def test_term_index_ns_epoch_invalidation():
    b = SnapshotBuilder()
    it = b.interns
    gid = it.group_id("ns0", {"app": "web"})
    term = t.PodAffinityTerm(
        label_selector=t.LabelSelector(match_labels=(("app", "web"),)),
        topology_key="z",
        namespace_selector=t.LabelSelector(match_labels=(("team", "red"),)),
    )
    tid = it.term_id(1, 1, term, "ns0")
    b.term_index.sync(b.ns_epoch)
    assert not b.term_index.column(gid)[0][tid]  # ns0 has no labels yet
    b.set_namespace_labels("ns0", {"team": "red"})
    b.term_index.sync(b.ns_epoch)
    assert b.term_index.column(gid)[0][tid]
