"""Scalar (pure-Python) reference implementations of the plugin semantics,
written straight from the Go sources — the oracle the vectorized device ops
are tested against (SURVEY.md §4: "table-driven plugin-semantics unit tests
comparing vectorized ops against scalar reference implementations").

Each function takes plain Pod/Node objects plus explicit cluster state
(pods-per-node etc.) and returns what the corresponding Go code returns."""

from __future__ import annotations

import math
from dataclasses import dataclass, field

from kubernetes_tpu.api import types as t

MAX_NODE_SCORE = 100


@dataclass
class RefNodeState:
    """Scalar mirror of NodeInfo (framework/types.go:714)."""

    node: t.Node
    pods: list[t.Pod] = field(default_factory=list)

    @property
    def requested(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for p in self.pods:
            for k, v in p.resource_request().items():
                out[k] = out.get(k, 0) + v
        return out

    @property
    def nonzero_requested(self) -> tuple[int, int]:
        cpu = sum(p.non_zero_request()[0] for p in self.pods)
        mem = sum(p.non_zero_request()[1] for p in self.pods)
        return cpu, mem


def fits_request(pod: t.Pod, ns: RefNodeState) -> list[str]:
    """fitsRequest (noderesources/fit.go:488): list of insufficient resources."""
    insufficient = []
    alloc = ns.node.status.allocatable
    allowed = alloc.get(t.PODS, 110)
    if len(ns.pods) + 1 > allowed:
        insufficient.append("Too many pods")
    req = pod.resource_request()
    interesting = {k: v for k, v in req.items() if k != t.PODS and v > 0}
    if not interesting:
        return insufficient
    used = ns.requested
    for rname, rq in interesting.items():
        if rq > alloc.get(rname, 0) - used.get(rname, 0):
            insufficient.append(f"Insufficient {rname}")
    return insufficient


def least_requested_score(requested: int, capacity: int) -> int:
    # least_allocated.go:97
    if capacity == 0:
        return 0
    if requested > capacity:
        return 0
    return ((capacity - requested) * MAX_NODE_SCORE) // capacity


def most_requested_score(requested: int, capacity: int) -> int:
    if capacity == 0:
        return 0
    if requested > capacity:
        return 0
    return (requested * MAX_NODE_SCORE) // capacity


def fit_score(
    pod: t.Pod,
    ns: RefNodeState,
    strategy: str = "LeastAllocated",
    resources: tuple[tuple[str, int], ...] = (("cpu", 1), ("memory", 1)),
) -> int:
    """resourceAllocationScorer.score with the given strategy
    (resource_allocation.go:55)."""
    node_score = 0
    weight_sum = 0
    pod_cpu, pod_mem = pod.non_zero_request()
    pod_req = pod.resource_request()
    nz_cpu, nz_mem = ns.nonzero_requested
    for rname, weight in resources:
        alloc = ns.node.status.allocatable.get(rname, 0)
        if rname == t.CPU:
            reqd = nz_cpu + pod_cpu
        elif rname == t.MEMORY:
            reqd = nz_mem + pod_mem
        else:
            reqd = ns.requested.get(rname, 0) + pod_req.get(rname, 0)
        if alloc == 0:
            continue
        scorer = least_requested_score if strategy == "LeastAllocated" else most_requested_score
        node_score += scorer(reqd, alloc) * weight
        weight_sum += weight
    if weight_sum == 0:
        return 0
    return node_score // weight_sum


def balanced_allocation_score(
    pod: t.Pod,
    ns: RefNodeState,
    resources: tuple[str, ...] = ("cpu", "memory"),
) -> int:
    """balancedResourceScorer (balanced_allocation.go:138): plain Requested."""
    pod_req = pod.resource_request()
    used = ns.requested
    fractions = []
    for rname in resources:
        alloc = ns.node.status.allocatable.get(rname, 0)
        if alloc == 0:
            continue
        fr = (used.get(rname, 0) + pod_req.get(rname, 0)) / alloc
        fractions.append(min(fr, 1.0))
    if len(fractions) == 2:
        std = abs(fractions[0] - fractions[1]) / 2
    elif len(fractions) > 2:
        mean = sum(fractions) / len(fractions)
        std = math.sqrt(sum((f - mean) ** 2 for f in fractions) / len(fractions))
    else:
        std = 0.0
    return int((1 - std) * MAX_NODE_SCORE)


def taint_toleration_filter(pod: t.Pod, node: t.Node) -> bool:
    """TaintToleration Filter (tainttoleration/taint_toleration.go:110):
    every NoSchedule/NoExecute taint must be tolerated."""
    for taint in node.spec.taints:
        if taint.effect not in (t.EFFECT_NO_SCHEDULE, t.EFFECT_NO_EXECUTE):
            continue
        if not any(tol.tolerates(taint) for tol in pod.spec.tolerations):
            return False
    return True


def taint_toleration_score_raw(pod: t.Pod, node: t.Node) -> int:
    """CountIntolerableTaintsPreferNoSchedule (taint_toleration.go:171):
    the raw per-node count before NormalizeScore inverts it."""
    n = 0
    for taint in node.spec.taints:
        if taint.effect != t.EFFECT_PREFER_NO_SCHEDULE:
            continue
        if not any(tol.tolerates(taint) for tol in pod.spec.tolerations):
            n += 1
    return n


def node_affinity_filter(pod: t.Pod, node: t.Node) -> bool:
    """NodeAffinity Filter: nodeSelector AND required node affinity
    (nodeaffinity/node_affinity.go:146 + GetRequiredNodeAffinity)."""
    labels = node.metadata.labels
    for k, v in pod.spec.node_selector.items():
        if labels.get(k) != v:
            return False
    aff = pod.spec.affinity
    if aff and aff.node_affinity and aff.node_affinity.required:
        return t.node_selector_matches(aff.node_affinity.required, labels, node.name)
    return True


def node_affinity_score_raw(pod: t.Pod, node: t.Node) -> int:
    """Sum of matching preferred term weights (node_affinity.go Score)."""
    aff = pod.spec.affinity
    if not aff or not aff.node_affinity:
        return 0
    total = 0
    for pref in aff.node_affinity.preferred:
        if pref.weight and t.node_selector_term_matches(
            pref.preference, node.metadata.labels, node.name
        ):
            total += pref.weight
    return total


def node_ports_filter(pod: t.Pod, existing: list[t.Pod]) -> bool:
    """NodePorts Filter (nodeports/node_ports.go): no host-port conflicts."""
    used: set[tuple[str, str, int]] = set()
    for p in existing:
        used.update(p.host_ports())

    for proto, ip, port in pod.host_ports():
        for uproto, uip, uport in used:
            if proto != uproto or port != uport:
                continue
            if ip == uip or ip == "0.0.0.0" or uip == "0.0.0.0":
                return False
    return True


# ---------------------------------------------------------------------------
# PodTopologySpread (plugins/podtopologyspread/filtering.go, scoring.go)
# ---------------------------------------------------------------------------


def _spread_count(c, pod, pods_on_node) -> int:
    """countPodsMatchSelector: same namespace + selector match."""
    return sum(
        1
        for p in pods_on_node
        if p.namespace == pod.namespace
        and t.label_selector_matches(c.label_selector, p.metadata.labels)
    )


def _spread_eligible(c, pod, node, all_keys: list[str]) -> bool:
    """processNode eligibility: all constraint topo keys present + per-
    constraint node inclusion policies (matchNodeInclusionPolicies)."""
    if any(k not in node.metadata.labels for k in all_keys):
        return False
    if c.node_affinity_policy == t.POLICY_HONOR and not node_affinity_filter(pod, node):
        return False
    if c.node_taints_policy == t.POLICY_HONOR and not taint_toleration_filter(pod, node):
        return False
    return True


def _spread_pair_counts(cons, pod, nodes, pods_on) -> dict:
    keys = [c.topology_key for c in cons]
    out = {}
    for c in cons:
        d: dict[str, int] = {}
        for n in nodes:
            if not _spread_eligible(c, pod, n, keys):
                continue
            v = n.metadata.labels[c.topology_key]
            d[v] = d.get(v, 0) + _spread_count(c, pod, pods_on.get(n.name, []))
        out[id(c)] = d
    return out


def _spread_with_mlk(pod, cons):
    """matchLabelKeys → effective selector (mergeLabelSetWithSelector),
    via the same shared helper the engine featurizer uses."""
    import dataclasses

    return [
        dataclasses.replace(
            c,
            label_selector=t.spread_effective_selector(
                c, pod.metadata.labels
            ),
            match_label_keys=(),
        )
        for c in cons
    ]


def spread_filter(pod, nodes, pods_on: dict) -> dict[str, bool]:
    """PodTopologySpread Filter for every node (filtering.go:283)."""
    cons = _spread_with_mlk(pod, [
        c
        for c in pod.spec.topology_spread_constraints
        if c.when_unsatisfiable == t.DO_NOT_SCHEDULE
    ])
    if not cons:
        return {n.name: True for n in nodes}
    pair = _spread_pair_counts(cons, pod, nodes, pods_on)
    result = {}
    for n in nodes:
        ok = True
        for c in cons:
            v = n.metadata.labels.get(c.topology_key)
            if v is None:
                ok = False
                break
            d = pair[id(c)]
            min_match = min(d.values()) if d else 2**31 - 1
            if len(d) < (c.min_domains or 1):
                min_match = 0
            self_match = 1 if t.label_selector_matches(c.label_selector, pod.metadata.labels) else 0
            if d.get(v, 0) + self_match - min_match > c.max_skew:
                ok = False
                break
        result[n.name] = ok
    return result


def spread_score(pod, nodes, pods_on: dict, feasible: dict[str, bool]) -> dict[str, int]:
    """PodTopologySpread Score + NormalizeScore over feasible nodes
    (scoring.go).  Returns the final normalized per-node scores."""
    cons = _spread_with_mlk(pod, [
        c
        for c in pod.spec.topology_spread_constraints
        if c.when_unsatisfiable == t.SCHEDULE_ANYWAY
    ])
    if not cons:
        return {n.name: 0 for n in nodes}
    keys = [c.topology_key for c in cons]
    hostname = "kubernetes.io/hostname"
    pair = _spread_pair_counts(cons, pod, nodes, pods_on)
    candidates = [n for n in nodes if feasible.get(n.name)]
    ignored = {n.name for n in candidates if any(k not in node_labels(n) for k in keys)}
    scored = [n for n in candidates if n.name not in ignored]
    raws: dict[str, int] = {}
    for n in scored:
        total = 0.0
        for c in cons:
            v = n.metadata.labels.get(c.topology_key)
            if v is None:
                continue
            if c.topology_key == hostname:
                cnt = _spread_count(c, pod, pods_on.get(n.name, []))
                size = len(scored)
            else:
                cnt = pair[id(c)].get(v, 0)
                size = len(
                    {
                        node_labels(m)[c.topology_key]
                        for m in scored
                        if c.topology_key in node_labels(m)
                    }
                )
            total += cnt * math.log(size + 2) + (c.max_skew - 1)
        raws[n.name] = int(math.floor(total + 0.5))
    out = {n.name: 0 for n in nodes}
    if raws:
        mx, mn = max(raws.values()), min(raws.values())
        for name, s in raws.items():
            out[name] = MAX_NODE_SCORE if mx == 0 else MAX_NODE_SCORE * (mx + mn - s) // mx
    return out


def node_labels(n) -> dict[str, str]:
    return n.metadata.labels


# ---------------------------------------------------------------------------
# InterPodAffinity (plugins/interpodaffinity/filtering.go, scoring.go)
# ---------------------------------------------------------------------------


def _ipa_term_matches(term, owner_ns: str, target, ns_labels: dict) -> bool:
    """AffinityTerm.Matches with newAffinityTerm's namespace defaulting."""
    ns = set(term.namespaces)
    if not ns and term.namespace_selector is None:
        ns = {owner_ns}
    ns_ok = target.namespace in ns or (
        term.namespace_selector is not None
        and t.label_selector_matches(
            term.namespace_selector, ns_labels.get(target.namespace, {})
        )
    )
    return ns_ok and t.label_selector_matches(term.label_selector, target.metadata.labels)


def _ipa_terms(pod):
    aff = pod.spec.affinity
    pa = aff.pod_affinity if aff else None
    paa = aff.pod_anti_affinity if aff else None
    return (
        list(pa.required) if pa else [],
        list(paa.required) if paa else [],
        list(pa.preferred) if pa else [],
        list(paa.preferred) if paa else [],
    )


def ipa_filter(pod, nodes, pods_on: dict, ns_labels: dict | None = None) -> dict[str, bool]:
    """InterPodAffinity Filter for every node (filtering.go:354–383)."""
    ns_labels = ns_labels or {}
    req_aff, req_anti, _, _ = _ipa_terms(pod)

    # existingAntiAffinityCounts: pairs forbidden by existing pods' terms.
    existing_anti: dict[tuple[str, str], int] = {}
    incoming_aff: dict[tuple[str, str], int] = {}
    incoming_anti: dict[tuple[str, str], int] = {}
    for n in nodes:
        for e in pods_on.get(n.name, []):
            e_req_aff, e_req_anti, _, _ = _ipa_terms(e)
            for term in e_req_anti:
                if _ipa_term_matches(term, e.namespace, pod, ns_labels):
                    v = n.metadata.labels.get(term.topology_key)
                    if v is not None:
                        existing_anti[(term.topology_key, v)] = (
                            existing_anti.get((term.topology_key, v), 0) + 1
                        )
            if req_aff and all(
                _ipa_term_matches(term2, pod.namespace, e, ns_labels) for term2 in req_aff
            ):
                for term2 in req_aff:
                    v = n.metadata.labels.get(term2.topology_key)
                    if v is not None:
                        incoming_aff[(term2.topology_key, v)] = (
                            incoming_aff.get((term2.topology_key, v), 0) + 1
                        )
            for term2 in req_anti:
                if _ipa_term_matches(term2, pod.namespace, e, ns_labels):
                    v = n.metadata.labels.get(term2.topology_key)
                    if v is not None:
                        incoming_anti[(term2.topology_key, v)] = (
                            incoming_anti.get((term2.topology_key, v), 0) + 1
                        )

    self_match = bool(req_aff) and all(
        _ipa_term_matches(term, pod.namespace, pod, ns_labels) for term in req_aff
    )
    out = {}
    for n in nodes:
        labels = n.metadata.labels
        # (1) existing pods' anti-affinity: any of the node's own pairs hit.
        ok = not any(existing_anti.get((k, v), 0) > 0 for k, v in labels.items())
        # (2) incoming required affinity.
        if ok and req_aff:
            pods_exist = True
            for term in req_aff:
                v = labels.get(term.topology_key)
                if v is None:
                    ok = False
                    break
                if incoming_aff.get((term.topology_key, v), 0) <= 0:
                    pods_exist = False
            if ok and not pods_exist:
                ok = not incoming_aff and self_match
        # (3) incoming required anti-affinity.
        if ok:
            for term in req_anti:
                v = labels.get(term.topology_key)
                if v is not None and incoming_anti.get((term.topology_key, v), 0) > 0:
                    ok = False
                    break
        out[n.name] = ok
    return out


def ipa_score(
    pod,
    nodes,
    pods_on: dict,
    feasible: dict[str, bool],
    hard_weight: int = 1,
    ns_labels: dict | None = None,
) -> dict[str, int]:
    """InterPodAffinity Score + NormalizeScore (scoring.go:80–124, 265)."""
    ns_labels = ns_labels or {}
    _, _, pref_aff, pref_anti = _ipa_terms(pod)
    topo: dict[tuple[str, str], int] = {}

    def bump(node, key, w):
        v = node.metadata.labels.get(key)
        if v is not None:
            topo[(key, v)] = topo.get((key, v), 0) + w

    for n in nodes:
        for e in pods_on.get(n.name, []):
            for wt in pref_aff:
                if _ipa_term_matches(wt.term, pod.namespace, e, ns_labels):
                    bump(n, wt.term.topology_key, wt.weight)
            for wt in pref_anti:
                if _ipa_term_matches(wt.term, pod.namespace, e, ns_labels):
                    bump(n, wt.term.topology_key, -wt.weight)
            e_req_aff, _, e_pref_aff, e_pref_anti = _ipa_terms(e)
            if hard_weight > 0:
                for term in e_req_aff:
                    if _ipa_term_matches(term, e.namespace, pod, ns_labels):
                        bump(n, term.topology_key, hard_weight)
            for wt in e_pref_aff:
                if _ipa_term_matches(wt.term, e.namespace, pod, ns_labels):
                    bump(n, wt.term.topology_key, wt.weight)
            for wt in e_pref_anti:
                if _ipa_term_matches(wt.term, e.namespace, pod, ns_labels):
                    bump(n, wt.term.topology_key, -wt.weight)

    raws = {}
    for n in nodes:
        if not feasible.get(n.name):
            continue
        raws[n.name] = sum(
            topo.get((k, v), 0) for k, v in n.metadata.labels.items()
        )
    out = {n.name: 0 for n in nodes}
    if raws:
        mx, mn = max(raws.values()), min(raws.values())
        diff = mx - mn
        for name, s in raws.items():
            out[name] = MAX_NODE_SCORE * (s - mn) // diff if diff > 0 else 0
    return out


# ---------------------------------------------------------------------------
# Volume plugins (plugins/volumebinding, volumezone, volumerestrictions,
# nodevolumelimits) — scalar references over a plain-dict catalog mirror,
# independent of kubernetes_tpu.volumes.VolumeCatalog.
# ---------------------------------------------------------------------------

_ZONE_KEYS = (
    "topology.kubernetes.io/zone",
    "failure-domain.beta.kubernetes.io/zone",
)
_REGION_KEYS = (
    "topology.kubernetes.io/region",
    "failure-domain.beta.kubernetes.io/region",
)
_NO_PROVISIONER = "kubernetes.io/no-provisioner"


class RefVolumes:
    """Scalar PV/PVC/StorageClass/CSINode state for the oracle."""

    def __init__(self, pvs=(), pvcs=(), classes=(), csinodes=()):
        self.pvs = {pv.name: pv for pv in pvs}
        self.pvcs = {pvc.uid: pvc for pvc in pvcs}
        self.classes = {sc.name: sc for sc in classes}
        self.csinodes = {cn.name: cn for cn in csinodes}

    def pod_pvcs(self, pod):
        return [
            self.pvcs.get(f"{pod.namespace}/{v.pvc}")
            for v in pod.spec.volumes
            if v.pvc
        ]

    def classify(self, pvc):
        if pvc.volume_name:
            pv = self.pvs.get(pvc.volume_name)
            return ("bound", pv) if pv is not None else ("lost", None)
        sc = self.classes.get(pvc.storage_class)
        if sc is not None and sc.binding_mode == t.BINDING_WAIT_FOR_FIRST_CONSUMER:
            return ("delayed", self.candidates_for(pvc), sc)
        return ("unbound_immediate", None)

    def candidates_for(self, pvc):
        out = []
        for pv in self.pvs.values():
            if pv.claim_ref or pv.storage_class != pvc.storage_class:
                continue
            if not set(pvc.access_modes) <= set(pv.access_modes):
                continue
            if pv.capacity < pvc.request:
                continue
            out.append(pv)
        return out

    def pvc_driver(self, pvc):
        if pvc.volume_name:
            pv = self.pvs.get(pvc.volume_name)
            return pv.csi_driver if pv is not None else ""
        sc = self.classes.get(pvc.storage_class)
        if sc is not None and sc.provisioner != _NO_PROVISIONER:
            return sc.provisioner
        return ""


def _pv_fits_node(pv, node) -> bool:
    return t.node_selector_matches(
        pv.node_affinity, node.metadata.labels, node.name
    )


def volume_binding_filter(pod, node, vols: RefVolumes) -> bool:
    """VolumeBinding Filter (volume_binding.go): bound claims need their
    PV's node affinity to match; delayed (WFFC) claims need a matching
    unbound PV or a provisioner whose allowedTopologies fit; unbound
    Immediate / lost claims fail everywhere."""
    for pvc in vols.pod_pvcs(pod):
        if pvc is None:
            return False
        kind, *rest = vols.classify(pvc)
        if kind in ("lost", "unbound_immediate"):
            return False
        if kind == "bound":
            if not _pv_fits_node(rest[0], node):
                return False
            continue
        candidates, sc = rest
        ok = any(_pv_fits_node(pv, node) for pv in candidates)
        if not ok and sc.provisioner != _NO_PROVISIONER:
            ok = sc.allowed_topologies is None or t.node_selector_matches(
                sc.allowed_topologies, node.metadata.labels, node.name
            )
        if not ok:
            return False
    return True


def volume_zone_filter(pod, node, vols: RefVolumes) -> bool:
    """VolumeZone (volume_zone.go): each bound PV's zone/region labels —
    possibly ``__``-separated value sets — must match the node."""
    for pvc in vols.pod_pvcs(pod):
        if pvc is None:
            return False
        kind, *rest = vols.classify(pvc)
        if kind in ("lost", "unbound_immediate"):
            return False
        if kind != "bound":
            continue
        pv = rest[0]
        for key in _ZONE_KEYS + _REGION_KEYS:
            v = pv.labels.get(key)
            if v is None:
                continue
            if node.metadata.labels.get(key) not in v.split("__"):
                return False
    return True


def volume_restrictions_filter(pod, node_pods, vols: RefVolumes,
                               pvc_users: dict) -> bool:
    """VolumeRestrictions (volume_restrictions.go): in-tree device volume
    conflicts (both-read-only exempt) + ReadWriteOncePod exclusivity."""
    for pvc in vols.pod_pvcs(pod):
        if pvc is not None and t.RWOP in pvc.access_modes:
            if pvc_users.get(pvc.uid, 0) > 0:
                return False
    for v in pod.spec.volumes:
        if not v.device_id:
            continue
        for p in node_pods:
            for v2 in p.spec.volumes:
                if v2.device_id != v.device_id:
                    continue
                if not (v.read_only and v2.read_only):
                    return False
    return True


def node_volume_limits_filter(pod, node, node_pods, vols: RefVolumes) -> bool:
    """NodeVolumeLimits CSI (nodevolumelimits/csi.go): per driver, distinct
    attached volumes + the pod's genuinely NEW volumes must stay within the
    CSINode allocatable count.  Volume identity = bound PV name or the
    unbound claim's uid (one attach per distinct volume)."""
    cn = vols.csinodes.get(node.name)
    if cn is None or not cn.driver_limits:
        return True

    def pod_vols(p):
        out = {}
        for pvc in vols.pod_pvcs(p):
            if pvc is None:
                continue
            drv = vols.pvc_driver(pvc)
            if not drv:
                continue
            vol_id = pvc.volume_name or pvc.uid
            out[(drv, vol_id)] = True
        return out

    attached = {}
    for p in node_pods:
        attached.update(pod_vols(p))
    new = {k: True for k in pod_vols(pod) if k not in attached}
    per_driver: dict[str, int] = {}
    for (drv, _vid) in attached:
        per_driver[drv] = per_driver.get(drv, 0) + 1
    for (drv, _vid) in new:
        per_driver[drv] = per_driver.get(drv, 0) + 1
        limit = cn.driver_limits.get(drv)
        if limit is not None and per_driver[drv] > limit:
            return False
    return True


# ---------------------------------------------------------------------------
# DynamicResources (plugins/dynamicresources/, counted-device form)
# ---------------------------------------------------------------------------


class RefClaims:
    """Scalar DRA state: claims + per-(node, class) published/allocated."""

    def __init__(self, claims=(), slices=()):
        self.claims = {c.uid: c for c in claims}
        self.slices: dict[tuple[str, str], int] = {}
        for s in slices:
            key = (s.node_name, s.device_class)
            self.slices[key] = self.slices.get(key, 0) + s.count
        self.allocated: dict[tuple[str, str], int] = {}
        # Pre-allocated claims consume their devices the moment they
        # arrive (the engine's external-allocation phantom charge,
        # dra.ClaimCatalog.add_claim).
        for c in self.claims.values():
            if c.allocated_node:
                key = (c.allocated_node, c.device_class)
                self.allocated[key] = self.allocated.get(key, 0) + c.count

    def pod_claims(self, pod):
        return [
            self.claims.get(f"{pod.namespace}/{name}")
            for name in pod.spec.resource_claims
        ]

    def free(self, node, cls):
        return self.slices.get((node, cls), 0) - self.allocated.get((node, cls), 0)


def dra_filter(pod, node, claims: RefClaims) -> bool:
    """DynamicResources Filter: every claim either allocated on THIS node
    or satisfiable from the node's free devices (per-class sums)."""
    need: dict[str, int] = {}
    for claim in claims.pod_claims(pod):
        if claim is None:
            return False
        if claim.allocated_node:
            if claim.allocated_node != node.name:
                return False
            continue
        need[claim.device_class] = need.get(claim.device_class, 0) + claim.count
    for cls, cnt in need.items():
        if claims.free(node.name, cls) < cnt:
            return False
    return True


def dra_commit(pod, node_name, claims: RefClaims) -> None:
    """Allocate the pod's claims on the chosen node (PreBind)."""
    for claim in claims.pod_claims(pod):
        if claim is None:
            continue
        if not claim.allocated_node:
            claim.allocated_node = node_name
            key = (node_name, claim.device_class)
            claims.allocated[key] = claims.allocated.get(key, 0) + claim.count
        if pod.uid not in claim.reserved_for:
            claim.reserved_for += (pod.uid,)


def volume_commit(pod, node, vols: RefVolumes, pvc_users: dict) -> None:
    """Bind the pod's delayed claims on the chosen node (PreBind,
    volume_binding.go:521): smallest fitting PV, else dynamic provisioning;
    bump RWOP usage counts."""
    for pvc in vols.pod_pvcs(pod):
        if pvc is None:
            continue
        pvc_users[pvc.uid] = pvc_users.get(pvc.uid, 0) + 1
        kind, *rest = vols.classify(pvc)
        if kind != "delayed":
            continue
        candidates, sc = rest
        fitting = [pv for pv in candidates if _pv_fits_node(pv, node)]
        if fitting:
            pv = min(fitting, key=lambda p: p.capacity)
            pv.claim_ref = pvc.uid
            pvc.volume_name = pv.name
        elif sc.provisioner != _NO_PROVISIONER:
            name = f"provisioned-{pvc.namespace}-{pvc.name}"
            vols.pvs[name] = t.PersistentVolume(
                name=name, capacity=pvc.request, access_modes=pvc.access_modes,
                storage_class=pvc.storage_class, claim_ref=pvc.uid,
                csi_driver=vols.pvc_driver(pvc),
            )
            pvc.volume_name = name


class RefStructuredClaims:
    """Scalar structured-parameters DRA state (staging
    dynamic-resource-allocation/structured/allocator.go): named devices
    with attributes per (node, class); request selectors are supplied by
    the TEST as plain predicates over an attribute dict — deliberately
    independent of the engine's CEL compiler (dra_cel.py), so the parity
    test cross-checks both the compilation and the vectorized pools."""

    def __init__(self, claims=(), slices=(), predicates=None):
        self.claims = {c.uid: c for c in claims}
        # (node, class) → {device name → attrs}
        self.devices: dict[tuple[str, str], dict[str, dict]] = {}
        for s in slices:
            key = (s.node_name, s.device_class)
            devs = self.devices.setdefault(key, {})
            if s.devices:
                for d in s.devices:
                    # Capacity quantities join the attr dict under the
                    # same reserved prefix the engine uses, so test
                    # predicates can read them; the predicates themselves
                    # stay plain Python (independent of dra_cel).
                    attrs = dict(d.attributes)
                    for ck, cv in getattr(d, "capacity", {}).items():
                        attrs[f"capacity://{ck}"] = cv
                    devs[d.name] = attrs
            else:
                base = len(devs)
                for i in range(s.count):
                    devs[f"{s.device_class}-{base + i}"] = {}
        # claim uid → {request name → predicate(attrs) -> bool}
        self.predicates = predicates or {}
        self.owner: dict[tuple[str, str], dict[str, str]] = {}

    def pod_claims(self, pod):
        return [
            self.claims.get(f"{pod.namespace}/{name}")
            for name in pod.spec.resource_claims
        ]

    def _free_matching(self, node, req, claim_uid):
        key = (node, req.device_class)
        owners = self.owner.get(key, {})
        pred = self.predicates.get(claim_uid, {}).get(
            req.name, lambda attrs: True
        )
        return sorted(
            name
            for name, attrs in self.devices.get(key, {}).items()
            if name not in owners and pred(attrs)
        )

    def filter(self, pod, node) -> bool:
        """Every claim either allocated on THIS node or satisfiable from
        the node's free matching devices (per-request, all-or-nothing)."""
        taken: dict[tuple[str, str], set] = {}
        for claim in self.pod_claims(pod):
            if claim is None:
                return False
            if claim.allocated_node:
                if claim.allocated_node != node.name:
                    return False
                continue
            for req in claim.device_requests():
                free = [
                    n
                    for n in self._free_matching(node.name, req, claim.uid)
                    if n not in taken.get((node.name, req.device_class), set())
                ]
                if len(free) < req.count:
                    return False
                taken.setdefault((node.name, req.device_class), set()).update(
                    free[: req.count]
                )
        return True

    def commit(self, pod, node_name) -> None:
        """Allocate the pod's claims (sorted-name greedy pick — mirrors the
        catalog's deterministic order)."""
        for claim in self.pod_claims(pod):
            if claim is None:
                continue
            if not claim.allocated_node:
                claim.allocated_node = node_name
                chosen = []
                for req in claim.device_requests():
                    names = self._free_matching(node_name, req, claim.uid)[
                        : req.count
                    ]
                    for n in names:
                        self.owner.setdefault(
                            (node_name, req.device_class), {}
                        )[n] = claim.uid
                        chosen.append((req.name, n))
                claim.allocated_devices = tuple(chosen)
            if pod.uid not in claim.reserved_for:
                claim.reserved_for += (pod.uid,)
