"""Scalar (pure-Python) reference implementations of the plugin semantics,
written straight from the Go sources — the oracle the vectorized device ops
are tested against (SURVEY.md §4: "table-driven plugin-semantics unit tests
comparing vectorized ops against scalar reference implementations").

Each function takes plain Pod/Node objects plus explicit cluster state
(pods-per-node etc.) and returns what the corresponding Go code returns."""

from __future__ import annotations

import math
from dataclasses import dataclass, field

from kubernetes_tpu.api import types as t

MAX_NODE_SCORE = 100


@dataclass
class RefNodeState:
    """Scalar mirror of NodeInfo (framework/types.go:714)."""

    node: t.Node
    pods: list[t.Pod] = field(default_factory=list)

    @property
    def requested(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for p in self.pods:
            for k, v in p.resource_request().items():
                out[k] = out.get(k, 0) + v
        return out

    @property
    def nonzero_requested(self) -> tuple[int, int]:
        cpu = sum(p.non_zero_request()[0] for p in self.pods)
        mem = sum(p.non_zero_request()[1] for p in self.pods)
        return cpu, mem


def fits_request(pod: t.Pod, ns: RefNodeState) -> list[str]:
    """fitsRequest (noderesources/fit.go:488): list of insufficient resources."""
    insufficient = []
    alloc = ns.node.status.allocatable
    allowed = alloc.get(t.PODS, 110)
    if len(ns.pods) + 1 > allowed:
        insufficient.append("Too many pods")
    req = pod.resource_request()
    interesting = {k: v for k, v in req.items() if k != t.PODS and v > 0}
    if not interesting:
        return insufficient
    used = ns.requested
    for rname, rq in interesting.items():
        if rq > alloc.get(rname, 0) - used.get(rname, 0):
            insufficient.append(f"Insufficient {rname}")
    return insufficient


def least_requested_score(requested: int, capacity: int) -> int:
    # least_allocated.go:97
    if capacity == 0:
        return 0
    if requested > capacity:
        return 0
    return ((capacity - requested) * MAX_NODE_SCORE) // capacity


def most_requested_score(requested: int, capacity: int) -> int:
    if capacity == 0:
        return 0
    if requested > capacity:
        return 0
    return (requested * MAX_NODE_SCORE) // capacity


def fit_score(
    pod: t.Pod,
    ns: RefNodeState,
    strategy: str = "LeastAllocated",
    resources: tuple[tuple[str, int], ...] = (("cpu", 1), ("memory", 1)),
) -> int:
    """resourceAllocationScorer.score with the given strategy
    (resource_allocation.go:55)."""
    node_score = 0
    weight_sum = 0
    pod_cpu, pod_mem = pod.non_zero_request()
    pod_req = pod.resource_request()
    nz_cpu, nz_mem = ns.nonzero_requested
    for rname, weight in resources:
        alloc = ns.node.status.allocatable.get(rname, 0)
        if rname == t.CPU:
            reqd = nz_cpu + pod_cpu
        elif rname == t.MEMORY:
            reqd = nz_mem + pod_mem
        else:
            reqd = ns.requested.get(rname, 0) + pod_req.get(rname, 0)
        if alloc == 0:
            continue
        scorer = least_requested_score if strategy == "LeastAllocated" else most_requested_score
        node_score += scorer(reqd, alloc) * weight
        weight_sum += weight
    if weight_sum == 0:
        return 0
    return node_score // weight_sum


def balanced_allocation_score(
    pod: t.Pod,
    ns: RefNodeState,
    resources: tuple[str, ...] = ("cpu", "memory"),
) -> int:
    """balancedResourceScorer (balanced_allocation.go:138): plain Requested."""
    pod_req = pod.resource_request()
    used = ns.requested
    fractions = []
    for rname in resources:
        alloc = ns.node.status.allocatable.get(rname, 0)
        if alloc == 0:
            continue
        fr = (used.get(rname, 0) + pod_req.get(rname, 0)) / alloc
        fractions.append(min(fr, 1.0))
    if len(fractions) == 2:
        std = abs(fractions[0] - fractions[1]) / 2
    elif len(fractions) > 2:
        mean = sum(fractions) / len(fractions)
        std = math.sqrt(sum((f - mean) ** 2 for f in fractions) / len(fractions))
    else:
        std = 0.0
    return int((1 - std) * MAX_NODE_SCORE)


def taint_toleration_filter(pod: t.Pod, node: t.Node) -> bool:
    """TaintToleration Filter (tainttoleration/taint_toleration.go:110):
    every NoSchedule/NoExecute taint must be tolerated."""
    for taint in node.spec.taints:
        if taint.effect not in (t.EFFECT_NO_SCHEDULE, t.EFFECT_NO_EXECUTE):
            continue
        if not any(tol.tolerates(taint) for tol in pod.spec.tolerations):
            return False
    return True


def taint_toleration_score_raw(pod: t.Pod, node: t.Node) -> int:
    """CountIntolerableTaintsPreferNoSchedule (taint_toleration.go:171):
    the raw per-node count before NormalizeScore inverts it."""
    n = 0
    for taint in node.spec.taints:
        if taint.effect != t.EFFECT_PREFER_NO_SCHEDULE:
            continue
        if not any(tol.tolerates(taint) for tol in pod.spec.tolerations):
            n += 1
    return n


def node_affinity_filter(pod: t.Pod, node: t.Node) -> bool:
    """NodeAffinity Filter: nodeSelector AND required node affinity
    (nodeaffinity/node_affinity.go:146 + GetRequiredNodeAffinity)."""
    labels = node.metadata.labels
    for k, v in pod.spec.node_selector.items():
        if labels.get(k) != v:
            return False
    aff = pod.spec.affinity
    if aff and aff.node_affinity and aff.node_affinity.required:
        return t.node_selector_matches(aff.node_affinity.required, labels, node.name)
    return True


def node_affinity_score_raw(pod: t.Pod, node: t.Node) -> int:
    """Sum of matching preferred term weights (node_affinity.go Score)."""
    aff = pod.spec.affinity
    if not aff or not aff.node_affinity:
        return 0
    total = 0
    for pref in aff.node_affinity.preferred:
        if pref.weight and t.node_selector_term_matches(
            pref.preference, node.metadata.labels, node.name
        ):
            total += pref.weight
    return total


def node_ports_filter(pod: t.Pod, existing: list[t.Pod]) -> bool:
    """NodePorts Filter (nodeports/node_ports.go): no host-port conflicts."""
    used: set[tuple[str, str, int]] = set()
    for p in existing:
        used.update(p.host_ports())

    for proto, ip, port in pod.host_ports():
        for uproto, uip, uport in used:
            if proto != uproto or port != uport:
                continue
            if ip == uip or ip == "0.0.0.0" or uip == "0.0.0.0":
                return False
    return True


# ---------------------------------------------------------------------------
# PodTopologySpread (plugins/podtopologyspread/filtering.go, scoring.go)
# ---------------------------------------------------------------------------


def _spread_count(c, pod, pods_on_node) -> int:
    """countPodsMatchSelector: same namespace + selector match."""
    return sum(
        1
        for p in pods_on_node
        if p.namespace == pod.namespace
        and t.label_selector_matches(c.label_selector, p.metadata.labels)
    )


def _spread_eligible(c, pod, node, all_keys: list[str]) -> bool:
    """processNode eligibility: all constraint topo keys present + per-
    constraint node inclusion policies (matchNodeInclusionPolicies)."""
    if any(k not in node.metadata.labels for k in all_keys):
        return False
    if c.node_affinity_policy == t.POLICY_HONOR and not node_affinity_filter(pod, node):
        return False
    if c.node_taints_policy == t.POLICY_HONOR and not taint_toleration_filter(pod, node):
        return False
    return True


def _spread_pair_counts(cons, pod, nodes, pods_on) -> dict:
    keys = [c.topology_key for c in cons]
    out = {}
    for c in cons:
        d: dict[str, int] = {}
        for n in nodes:
            if not _spread_eligible(c, pod, n, keys):
                continue
            v = n.metadata.labels[c.topology_key]
            d[v] = d.get(v, 0) + _spread_count(c, pod, pods_on.get(n.name, []))
        out[id(c)] = d
    return out


def _spread_with_mlk(pod, cons):
    """matchLabelKeys → effective selector (mergeLabelSetWithSelector),
    via the same shared helper the engine featurizer uses."""
    import dataclasses

    return [
        dataclasses.replace(
            c,
            label_selector=t.spread_effective_selector(
                c, pod.metadata.labels
            ),
            match_label_keys=(),
        )
        for c in cons
    ]


def spread_filter(pod, nodes, pods_on: dict) -> dict[str, bool]:
    """PodTopologySpread Filter for every node (filtering.go:283)."""
    cons = _spread_with_mlk(pod, [
        c
        for c in pod.spec.topology_spread_constraints
        if c.when_unsatisfiable == t.DO_NOT_SCHEDULE
    ])
    if not cons:
        return {n.name: True for n in nodes}
    pair = _spread_pair_counts(cons, pod, nodes, pods_on)
    result = {}
    for n in nodes:
        ok = True
        for c in cons:
            v = n.metadata.labels.get(c.topology_key)
            if v is None:
                ok = False
                break
            d = pair[id(c)]
            min_match = min(d.values()) if d else 2**31 - 1
            if len(d) < (c.min_domains or 1):
                min_match = 0
            self_match = 1 if t.label_selector_matches(c.label_selector, pod.metadata.labels) else 0
            if d.get(v, 0) + self_match - min_match > c.max_skew:
                ok = False
                break
        result[n.name] = ok
    return result


def spread_score(pod, nodes, pods_on: dict, feasible: dict[str, bool]) -> dict[str, int]:
    """PodTopologySpread Score + NormalizeScore over feasible nodes
    (scoring.go).  Returns the final normalized per-node scores."""
    cons = _spread_with_mlk(pod, [
        c
        for c in pod.spec.topology_spread_constraints
        if c.when_unsatisfiable == t.SCHEDULE_ANYWAY
    ])
    if not cons:
        return {n.name: 0 for n in nodes}
    keys = [c.topology_key for c in cons]
    hostname = "kubernetes.io/hostname"
    pair = _spread_pair_counts(cons, pod, nodes, pods_on)
    candidates = [n for n in nodes if feasible.get(n.name)]
    ignored = {n.name for n in candidates if any(k not in node_labels(n) for k in keys)}
    scored = [n for n in candidates if n.name not in ignored]
    raws: dict[str, int] = {}
    for n in scored:
        total = 0.0
        for c in cons:
            v = n.metadata.labels.get(c.topology_key)
            if v is None:
                continue
            if c.topology_key == hostname:
                cnt = _spread_count(c, pod, pods_on.get(n.name, []))
                size = len(scored)
            else:
                cnt = pair[id(c)].get(v, 0)
                size = len(
                    {
                        node_labels(m)[c.topology_key]
                        for m in scored
                        if c.topology_key in node_labels(m)
                    }
                )
            total += cnt * math.log(size + 2) + (c.max_skew - 1)
        raws[n.name] = int(math.floor(total + 0.5))
    out = {n.name: 0 for n in nodes}
    if raws:
        mx, mn = max(raws.values()), min(raws.values())
        for name, s in raws.items():
            out[name] = MAX_NODE_SCORE if mx == 0 else MAX_NODE_SCORE * (mx + mn - s) // mx
    return out


def node_labels(n) -> dict[str, str]:
    return n.metadata.labels


# ---------------------------------------------------------------------------
# InterPodAffinity (plugins/interpodaffinity/filtering.go, scoring.go)
# ---------------------------------------------------------------------------


def _ipa_term_matches(term, owner_ns: str, target, ns_labels: dict) -> bool:
    """AffinityTerm.Matches with newAffinityTerm's namespace defaulting."""
    ns = set(term.namespaces)
    if not ns and term.namespace_selector is None:
        ns = {owner_ns}
    ns_ok = target.namespace in ns or (
        term.namespace_selector is not None
        and t.label_selector_matches(
            term.namespace_selector, ns_labels.get(target.namespace, {})
        )
    )
    return ns_ok and t.label_selector_matches(term.label_selector, target.metadata.labels)


def _ipa_terms(pod):
    aff = pod.spec.affinity
    pa = aff.pod_affinity if aff else None
    paa = aff.pod_anti_affinity if aff else None
    return (
        list(pa.required) if pa else [],
        list(paa.required) if paa else [],
        list(pa.preferred) if pa else [],
        list(paa.preferred) if paa else [],
    )


def ipa_filter(pod, nodes, pods_on: dict, ns_labels: dict | None = None) -> dict[str, bool]:
    """InterPodAffinity Filter for every node (filtering.go:354–383)."""
    ns_labels = ns_labels or {}
    req_aff, req_anti, _, _ = _ipa_terms(pod)

    # existingAntiAffinityCounts: pairs forbidden by existing pods' terms.
    existing_anti: dict[tuple[str, str], int] = {}
    incoming_aff: dict[tuple[str, str], int] = {}
    incoming_anti: dict[tuple[str, str], int] = {}
    for n in nodes:
        for e in pods_on.get(n.name, []):
            e_req_aff, e_req_anti, _, _ = _ipa_terms(e)
            for term in e_req_anti:
                if _ipa_term_matches(term, e.namespace, pod, ns_labels):
                    v = n.metadata.labels.get(term.topology_key)
                    if v is not None:
                        existing_anti[(term.topology_key, v)] = (
                            existing_anti.get((term.topology_key, v), 0) + 1
                        )
            if req_aff and all(
                _ipa_term_matches(term2, pod.namespace, e, ns_labels) for term2 in req_aff
            ):
                for term2 in req_aff:
                    v = n.metadata.labels.get(term2.topology_key)
                    if v is not None:
                        incoming_aff[(term2.topology_key, v)] = (
                            incoming_aff.get((term2.topology_key, v), 0) + 1
                        )
            for term2 in req_anti:
                if _ipa_term_matches(term2, pod.namespace, e, ns_labels):
                    v = n.metadata.labels.get(term2.topology_key)
                    if v is not None:
                        incoming_anti[(term2.topology_key, v)] = (
                            incoming_anti.get((term2.topology_key, v), 0) + 1
                        )

    self_match = bool(req_aff) and all(
        _ipa_term_matches(term, pod.namespace, pod, ns_labels) for term in req_aff
    )
    out = {}
    for n in nodes:
        labels = n.metadata.labels
        # (1) existing pods' anti-affinity: any of the node's own pairs hit.
        ok = not any(existing_anti.get((k, v), 0) > 0 for k, v in labels.items())
        # (2) incoming required affinity.
        if ok and req_aff:
            pods_exist = True
            for term in req_aff:
                v = labels.get(term.topology_key)
                if v is None:
                    ok = False
                    break
                if incoming_aff.get((term.topology_key, v), 0) <= 0:
                    pods_exist = False
            if ok and not pods_exist:
                ok = not incoming_aff and self_match
        # (3) incoming required anti-affinity.
        if ok:
            for term in req_anti:
                v = labels.get(term.topology_key)
                if v is not None and incoming_anti.get((term.topology_key, v), 0) > 0:
                    ok = False
                    break
        out[n.name] = ok
    return out


def ipa_score(
    pod,
    nodes,
    pods_on: dict,
    feasible: dict[str, bool],
    hard_weight: int = 1,
    ns_labels: dict | None = None,
) -> dict[str, int]:
    """InterPodAffinity Score + NormalizeScore (scoring.go:80–124, 265)."""
    ns_labels = ns_labels or {}
    _, _, pref_aff, pref_anti = _ipa_terms(pod)
    topo: dict[tuple[str, str], int] = {}

    def bump(node, key, w):
        v = node.metadata.labels.get(key)
        if v is not None:
            topo[(key, v)] = topo.get((key, v), 0) + w

    for n in nodes:
        for e in pods_on.get(n.name, []):
            for wt in pref_aff:
                if _ipa_term_matches(wt.term, pod.namespace, e, ns_labels):
                    bump(n, wt.term.topology_key, wt.weight)
            for wt in pref_anti:
                if _ipa_term_matches(wt.term, pod.namespace, e, ns_labels):
                    bump(n, wt.term.topology_key, -wt.weight)
            e_req_aff, _, e_pref_aff, e_pref_anti = _ipa_terms(e)
            if hard_weight > 0:
                for term in e_req_aff:
                    if _ipa_term_matches(term, e.namespace, pod, ns_labels):
                        bump(n, term.topology_key, hard_weight)
            for wt in e_pref_aff:
                if _ipa_term_matches(wt.term, e.namespace, pod, ns_labels):
                    bump(n, wt.term.topology_key, wt.weight)
            for wt in e_pref_anti:
                if _ipa_term_matches(wt.term, e.namespace, pod, ns_labels):
                    bump(n, wt.term.topology_key, -wt.weight)

    raws = {}
    for n in nodes:
        if not feasible.get(n.name):
            continue
        raws[n.name] = sum(
            topo.get((k, v), 0) for k, v in n.metadata.labels.items()
        )
    out = {n.name: 0 for n in nodes}
    if raws:
        mx, mn = max(raws.values()), min(raws.values())
        diff = mx - mn
        for name, s in raws.items():
            out[name] = MAX_NODE_SCORE * (s - mn) // diff if diff > 0 else 0
    return out
