"""Batched preemption vs the reference's victim-selection semantics."""

import numpy as np

from kubernetes_tpu.api import types as t
from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.framework.config import Profile, fit_only_profile
from kubernetes_tpu.scheduler import TPUScheduler


def sched(batch_size=8, profile=None):
    return TPUScheduler(profile=profile or fit_only_profile(), batch_size=batch_size)


def test_preempts_lower_priority_pod():
    s = sched()
    s.add_node(make_node("n1").capacity({"cpu": "2", "pods": 110}).obj())
    s.add_pod(make_pod("victim").req({"cpu": "2"}).priority(1).node("n1").obj())
    s.add_pod(make_pod("vip").req({"cpu": "2"}).priority(100).obj())
    out = s.schedule_all_pending(wait_backoff=True)
    by_name = {o.pod.name: o for o in out}
    assert by_name["vip"].nominated_node == "n1" or by_name["vip"].node_name == "n1"
    final = [o for o in out if o.pod.name == "vip" and o.node_name]
    assert final and final[0].node_name == "n1"
    assert "default/victim" not in s.cache.pods


def test_no_preemption_of_equal_or_higher_priority():
    s = sched()
    s.add_node(make_node("n1").capacity({"cpu": "2", "pods": 110}).obj())
    s.add_pod(make_pod("incumbent").req({"cpu": "2"}).priority(100).node("n1").obj())
    s.add_pod(make_pod("peer").req({"cpu": "2"}).priority(100).obj())
    out = s.schedule_all_pending(wait_backoff=True)
    assert all(o.node_name is None for o in out if o.pod.name == "peer")
    assert "default/incumbent" in s.cache.pods


def test_preemption_policy_never():
    s = sched()
    s.add_node(make_node("n1").capacity({"cpu": "2", "pods": 110}).obj())
    s.add_pod(make_pod("victim").req({"cpu": "2"}).priority(1).node("n1").obj())
    s.add_pod(
        make_pod("meek").req({"cpu": "2"}).priority(100)
        .preemption_policy(t.PREEMPT_NEVER).obj()
    )
    out = s.schedule_all_pending(wait_backoff=True)
    assert all(o.node_name is None for o in out if o.pod.name == "meek")
    assert "default/victim" in s.cache.pods


def test_minimal_victim_set():
    """Only as many victims as needed are removed, least important first."""
    s = sched()
    s.add_node(make_node("n1").capacity({"cpu": "4", "pods": 110}).obj())
    s.add_pod(make_pod("v-lo").req({"cpu": "2"}).priority(1).node("n1").obj())
    s.add_pod(make_pod("v-hi").req({"cpu": "2"}).priority(50).node("n1").obj())
    s.add_pod(make_pod("vip").req({"cpu": "2"}).priority(100).obj())
    out = s.schedule_all_pending(wait_backoff=True)
    assert any(o.node_name == "n1" for o in out if o.pod.name == "vip")
    assert "default/v-lo" not in s.cache.pods  # lowest priority evicted
    assert "default/v-hi" in s.cache.pods  # reprieved


def test_picks_node_with_lowest_max_victim_priority():
    s = sched()
    s.add_node(make_node("cheap").capacity({"cpu": "2", "pods": 110}).obj())
    s.add_node(make_node("dear").capacity({"cpu": "2", "pods": 110}).obj())
    s.add_pod(make_pod("low").req({"cpu": "2"}).priority(5).node("cheap").obj())
    s.add_pod(make_pod("high").req({"cpu": "2"}).priority(50).node("dear").obj())
    s.add_pod(make_pod("vip").req({"cpu": "2"}).priority(100).obj())
    out = s.schedule_all_pending(wait_backoff=True)
    vip = [o for o in out if o.pod.name == "vip"]
    assert vip[0].nominated_node == "cheap"
    assert "default/low" not in s.cache.pods and "default/high" in s.cache.pods


def test_fewest_victims_tiebreak():
    s = sched()
    s.add_node(make_node("many").capacity({"cpu": "2", "pods": 110}).obj())
    s.add_node(make_node("one").capacity({"cpu": "2", "pods": 110}).obj())
    for i in range(2):
        s.add_pod(make_pod(f"m{i}").req({"cpu": "1"}).priority(5).node("many").obj())
    s.add_pod(make_pod("solo").req({"cpu": "2"}).priority(5).node("one").obj())
    s.add_pod(make_pod("vip").req({"cpu": "2"}).priority(100).obj())
    out = s.schedule_all_pending(wait_backoff=True)
    vip = [o for o in out if o.pod.name == "vip"]
    assert vip[0].nominated_node == "one"
    assert vip[0].victims == 1


def test_unresolvable_nodes_excluded():
    """Preemption cannot fix a missing node-affinity label."""
    prof = Profile(
        name="na-fit",
        filters=("NodeResourcesFit", "NodeAffinity"),
        scorers=(("NodeResourcesFit", 1),),
    )
    s = sched(profile=prof)
    s.add_node(make_node("wrong").capacity({"cpu": "4", "pods": 110}).obj())
    s.add_node(make_node("right").capacity({"cpu": "2", "pods": 110}).label("disk", "ssd").obj())
    s.add_pod(make_pod("v1").req({"cpu": "2"}).priority(1).node("right").obj())
    s.add_pod(make_pod("v2").req({"cpu": "4"}).priority(1).node("wrong").obj())
    s.add_pod(
        make_pod("vip").req({"cpu": "2"}).priority(100)
        .node_affinity_in("disk", ["ssd"]).obj()
    )
    out = s.schedule_all_pending(wait_backoff=True)
    vip = [o for o in out if o.pod.name == "vip"]
    assert vip[0].nominated_node == "right"
    assert "default/v2" in s.cache.pods  # the unresolvable node's pod untouched
    assert any(o.node_name == "right" for o in out if o.pod.name == "vip")


def test_preemption_randomized_resource_only():
    """Chosen node must satisfy the lexicographic criteria vs a scalar oracle."""
    rng = np.random.default_rng(31)
    s = sched(batch_size=16)
    n_nodes = 10
    caps = {}
    for i in range(n_nodes):
        cpu = int(rng.integers(2, 8))
        caps[f"n{i}"] = cpu * 1000
        s.add_node(make_node(f"n{i}").capacity({"cpu": cpu, "pods": 110}).obj())
    pods_on = {f"n{i}": [] for i in range(n_nodes)}
    uid = 0
    for name in pods_on:
        free = caps[name]
        while free >= 1000 and rng.integers(0, 4):
            cpu = int(rng.integers(1, max(free // 1000, 2))) * 1000
            prio = int(rng.integers(1, 50))
            p = (
                make_pod(f"bg{uid}").req({"cpu": f"{cpu}m"}).priority(prio)
                .start_time(float(uid)).node(name).obj()
            )
            s.add_pod(p)
            pods_on[name].append((prio, cpu, f"bg{uid}"))
            free -= cpu
            uid += 1

    vip_cpu = 2000
    s.add_pod(make_pod("vip").req({"cpu": f"{vip_cpu}m"}).priority(100).obj())
    out = s.schedule_all_pending(wait_backoff=True)
    vip = [o for o in out if o.pod.name == "vip"]

    # Oracle: minimal victim prefix per node (priority asc), then criteria.
    def plan(name):
        used = sum(c for _, c, _ in pods_on[name])
        free = caps[name] - used
        if free >= vip_cpu:
            return None  # no preemption needed — would have scheduled
        vics = sorted(pods_on[name], key=lambda v: v[0])
        rel, chosen = 0, []
        for prio, cpu, uid_ in vics:
            if free + rel >= vip_cpu:
                break
            rel += cpu
            chosen.append((prio, cpu, uid_))
        if free + rel < vip_cpu:
            return None
        return chosen

    plans = {name: plan(name) for name in pods_on}
    direct = [n for n, used in plans.items() if used is None and
              caps[n] - sum(c for _, c, _ in pods_on[n]) >= vip_cpu]
    if direct:
        assert vip[0].node_name in direct
        return
    viable = {n: p for n, p in plans.items() if p}
    assert viable, "oracle says nothing viable"
    assert vip[0].nominated_node in viable
    got = viable[vip[0].nominated_node]
    best_maxprio = min(max(pr for pr, _, _ in p) for p in viable.values())
    assert max(pr for pr, _, _ in got) == best_maxprio


def test_latest_start_tiebreak_uses_highest_priority_victims():
    """Criterion 5 compares earliest start among HIGHEST-priority victims
    (GetEarliestPodStartTime), not among all victims."""
    s = sched()
    s.add_node(make_node("a").capacity({"cpu": "2", "pods": 110}).obj())
    s.add_node(make_node("b").capacity({"cpu": "2", "pods": 110}).obj())
    # Node a: prio-5 victim started at 10, prio-1 victim started at 1.
    s.add_pod(make_pod("a5").req({"cpu": "1"}).priority(5).start_time(10.0).node("a").obj())
    s.add_pod(make_pod("a1").req({"cpu": "1"}).priority(1).start_time(1.0).node("a").obj())
    # Node b: prio-5 victim started at 5, prio-1 victim started at 2.
    s.add_pod(make_pod("b5").req({"cpu": "1"}).priority(5).start_time(5.0).node("b").obj())
    s.add_pod(make_pod("b1").req({"cpu": "1"}).priority(1).start_time(2.0).node("b").obj())
    s.add_pod(make_pod("vip").req({"cpu": "2"}).priority(100).obj())
    out = s.schedule_all_pending(wait_backoff=True)
    vip = [o for o in out if o.pod.name == "vip"]
    # Ties on criteria 1-4 (max prio 5, sum 6, two victims); highest-priority
    # victims' earliest starts are 10 (a) vs 5 (b) → latest wins → node a.
    assert vip[0].nominated_node == "a"


def test_pdb_violations_decide_winner():
    """pickOneNodeForPreemption criterion 1: with two otherwise-identical
    candidates, the node whose victims violate a PDB loses."""
    s = sched()
    s.add_node(make_node("n1").capacity({"cpu": "2", "pods": 110}).obj())
    s.add_node(make_node("n2").capacity({"cpu": "2", "pods": 110}).obj())
    # Same priority/start on both nodes; n1's victim is PDB-protected.
    s.add_pod(
        make_pod("protected").req({"cpu": "2"}).priority(5)
        .label("app", "db").start_time(10.0).node("n1").obj()
    )
    s.add_pod(
        make_pod("plain").req({"cpu": "2"}).priority(5)
        .start_time(10.0).node("n2").obj()
    )
    s.add_pdb(
        t.PodDisruptionBudget(
            name="db-pdb",
            selector=t.LabelSelector(match_labels=(("app", "db"),)),
            disruptions_allowed=0,
        )
    )
    s.add_pod(make_pod("vip").req({"cpu": "2"}).priority(100).obj())
    out = s.schedule_all_pending(wait_backoff=True)
    vip = [o for o in out if o.pod.name == "vip" and o.node_name]
    assert vip and vip[0].node_name == "n2"
    assert "default/protected" in s.cache.pods
    assert "default/plain" not in s.cache.pods


def test_pdb_budget_consumed_across_preemptions():
    """A PDB with one allowed disruption protects its second pod."""
    s = sched()
    for i in (1, 2):
        s.add_node(make_node(f"n{i}").capacity({"cpu": "2", "pods": 110}).obj())
        s.add_pod(
            make_pod(f"db-{i}").req({"cpu": "2"}).priority(5)
            .label("app", "db").node(f"n{i}").obj()
        )
    s.add_pdb(
        t.PodDisruptionBudget(
            name="db-pdb",
            selector=t.LabelSelector(match_labels=(("app", "db"),)),
            disruptions_allowed=1,
        )
    )
    s.add_pod(make_pod("vip-1").req({"cpu": "2"}).priority(100).obj())
    s.schedule_all_pending(wait_backoff=True)
    # One db pod evicted, budget now 0; preferring the protected victim's
    # node would violate, so count the survivors.
    assert sum(1 for uid in s.cache.pods if uid.startswith("default/db")) == 1
    assert s.pdbs["db-pdb"].disruptions_allowed == 0


def test_port_conflict_preemption_nominates():
    """The r1 false negative: the node has spare CPU but a lower-priority
    pod holds the host port the preemptor needs.  The full-filter dry-run
    must nominate the node and evict the port holder."""
    prof = Profile(
        name="fit-ports",
        filters=("NodeUnschedulable", "NodeName", "NodePorts", "NodeResourcesFit"),
        scorers=(("NodeResourcesFit", 1),),
    )
    s = sched(profile=prof)
    s.add_node(make_node("n1").capacity({"cpu": "8", "pods": 110}).obj())
    s.add_pod(
        make_pod("holder").req({"cpu": "1"}).priority(1)
        .host_port(8080).node("n1").obj()
    )
    s.add_pod(
        make_pod("vip").req({"cpu": "1"}).priority(100).host_port(8080).obj()
    )
    out = s.schedule_all_pending(wait_backoff=True)
    vip = [o for o in out if o.pod.name == "vip" and o.node_name]
    assert vip and vip[0].node_name == "n1"
    assert "default/holder" not in s.cache.pods


def test_nominated_node_not_stolen_by_next_batch():
    """After preemption frees a node for a nominated pod, a lower-priority
    pod arriving before the retry must not steal the capacity
    (RunFilterPluginsWithNominatedPods, framework.go:973)."""
    s = sched(batch_size=4)
    s.add_node(make_node("n1").capacity({"cpu": "2", "pods": 110}).obj())
    s.add_pod(make_pod("victim").req({"cpu": "2"}).priority(1).node("n1").obj())
    s.add_pod(make_pod("vip").req({"cpu": "2"}).priority(100).obj())
    out1 = s.schedule_batch()  # vip fails, preempts, nominates n1
    assert out1[0].nominated_node == "n1"
    assert "default/vip" in s.nominator
    # A lower-priority pod shows up before vip's retry: it must NOT fit on
    # n1 (the nominated resources are counted against it).
    s.add_pod(make_pod("sneak").req({"cpu": "2"}).priority(1).obj())
    out = s.schedule_all_pending(wait_backoff=True)
    landed = {o.pod.name: o.node_name for o in out if o.node_name}
    assert landed.get("vip") == "n1"
    assert "sneak" not in landed


def test_greedy_reprieve_keeps_mid_priority_victim():
    """SelectVictimsOnNode's most-important-first reprieve keeps a
    mid-priority pod whose eviction would not help — the old minimal-PREFIX
    rule would have evicted it (r2 VERDICT missing-5 done criterion;
    preemption.go:541 reprieve loop).

    Node (cpu 4, mem 16Gi) holds A(prio 1, cpu 2), B(prio 2, mem 8Gi),
    C(prio 3, cpu 2); the preemptor needs cpu 4.  The prefix rule must take
    [A, B, C] (contiguous least-important-first until 4 cpu free); the
    reprieve re-admits B (its memory frees no cpu) and evicts only {A, C}."""
    s = sched()
    s.add_node(
        make_node("n1").capacity({"cpu": "4", "memory": "16Gi", "pods": 110}).obj()
    )
    s.add_pod(make_pod("a").req({"cpu": "2"}).priority(1).node("n1").obj())
    s.add_pod(make_pod("b").req({"memory": "8Gi"}).priority(2).node("n1").obj())
    s.add_pod(make_pod("c").req({"cpu": "2"}).priority(3).node("n1").obj())
    s.add_pod(make_pod("vip").req({"cpu": "4"}).priority(100).obj())
    out = s.schedule_all_pending(wait_backoff=True)
    landed = {o.pod.name: o.node_name for o in out if o.node_name}
    assert landed.get("vip") == "n1"
    assert "default/b" in s.cache.pods, "mid-priority B must be reprieved"
    assert "default/a" not in s.cache.pods
    assert "default/c" not in s.cache.pods


def test_reprieve_order_prefers_keeping_pdb_covered_victims():
    """PDB-violating victims are reprieved FIRST (filterPodsWithPDBViolation
    + the two reprieve loops): with capacity to spare one victim, the
    PDB-covered pod survives even when a same-priority uncovered pod could
    have been kept instead."""
    s = sched()
    s.add_node(make_node("n1").capacity({"cpu": "4", "pods": 110}).obj())
    s.add_pdb(
        t.PodDisruptionBudget(
            name="guard",
            namespace="default",
            selector=t.LabelSelector(match_labels=(("app", "guarded"),)),
            disruptions_allowed=0,
        )
    )
    s.add_pod(
        make_pod("covered").req({"cpu": "2"}).priority(1)
        .label("app", "guarded").start_time(1.0).node("n1").obj()
    )
    s.add_pod(
        make_pod("plain").req({"cpu": "2"}).priority(1)
        .start_time(2.0).node("n1").obj()
    )
    s.add_pod(make_pod("vip").req({"cpu": "2"}).priority(100).obj())
    out = s.schedule_all_pending(wait_backoff=True)
    landed = {o.pod.name: o.node_name for o in out if o.node_name}
    assert landed.get("vip") == "n1"
    assert "default/covered" in s.cache.pods, "PDB-covered victim reprieved first"
    assert "default/plain" not in s.cache.pods


def test_pdb_budget_simulation_in_violation_classification():
    """filterPodsWithPDBViolation consumes the remaining budget walking
    most-important-first: with disruptions_allowed=1 over two equal-priority
    pods, the MORE important one claims the budget (non-violating) and the
    LESS important one is violating — so the less important pod is
    reprieved first and survives, and the more important one is evicted."""
    s = sched()
    s.add_node(make_node("n1").capacity({"cpu": "4", "pods": 110}).obj())
    s.add_pdb(
        t.PodDisruptionBudget(
            name="one-left",
            namespace="default",
            selector=t.LabelSelector(match_labels=(("app", "db"),)),
            disruptions_allowed=1,
        )
    )
    # x is more important (earlier start) at equal priority.
    s.add_pod(
        make_pod("x").req({"cpu": "2"}).priority(1).label("app", "db")
        .start_time(1.0).node("n1").obj()
    )
    s.add_pod(
        make_pod("y").req({"cpu": "2"}).priority(1).label("app", "db")
        .start_time(2.0).node("n1").obj()
    )
    s.add_pod(make_pod("vip").req({"cpu": "2"}).priority(100).obj())
    out = s.schedule_all_pending(wait_backoff=True)
    landed = {o.pod.name: o.node_name for o in out if o.node_name}
    assert landed.get("vip") == "n1"
    # y was violating (budget claimed by x) -> reprieved first -> survives.
    assert "default/y" in s.cache.pods
    assert "default/x" not in s.cache.pods


def test_inline_commit_spends_stale_nomination():
    """A pod committing inline must pop its nominator claim (review
    finding: a bound pod would otherwise hold a phantom claim forever)."""
    s = TPUScheduler(batch_size=4, chunk_size=2)
    assert s.inline_preempt_commit
    s.add_node(make_node("n0").capacity({"cpu": "4", "pods": 110}).obj())
    s.add_pod(make_pod("bg").req({"cpu": "4"}).priority(1).obj())
    s.schedule_all_pending()
    vip = make_pod("vip").req({"cpu": "2"}).priority(100).obj()
    # Seed a stale nomination claim as if an earlier nominate round ran.
    s.nominator[vip.uid] = ("n0", {"req": __import__("numpy").zeros(4, "int64")}, 100)
    s.add_pod(vip)
    outs = s.schedule_all_pending(wait_backoff=True)
    ok = [o for o in outs if o.pod.uid == vip.uid and o.node_name]
    assert ok, outs
    assert vip.uid not in s.nominator


def test_preemption_with_more_pdbs_than_nodes():
    """pdb_allowed rides inside the victim mega-buffer only while
    n_pdbs <= node rows; beyond that it takes its own transfer (review
    r4: the inline stash must not crash tiny clusters with many PDBs)."""
    s = TPUScheduler(batch_size=4)
    s.add_node(
        make_node("n1").capacity({"cpu": "2", "memory": "4Gi", "pods": 10}).obj()
    )
    for i in range(20):  # n_pdbs buckets past the 8-row node axis
        s.add_pdb(
            t.PodDisruptionBudget(
                name=f"pdb-{i}", namespace="default",
                selector=t.LabelSelector(match_labels=(("app", f"a{i}"),)),
                disruptions_allowed=1,
            )
        )
    low = make_pod("low").req({"cpu": "2"}).priority(1).label("app", "a0").obj()
    s.add_pod(low)
    s.schedule_all_pending()
    assert low.spec.node_name == "n1"
    vip = make_pod("vip").req({"cpu": "2"}).priority(100).obj()
    s.add_pod(vip)
    s.schedule_all_pending(wait_backoff=True)
    assert vip.spec.node_name == "n1"
    assert s.metrics.preemptions == 1


def test_speculative_chained_preemption_mixed_batch():
    """The chained dry-run (dispatch_speculative): a batch mixing a pod
    that PLACES with pods that need preemption must still preempt — the
    rank-split's representative is the first VALID mate, not index 0
    (which may have placed and carries valid=False)."""
    s = TPUScheduler(profile=fit_only_profile(), batch_size=8, chunk_size=4)
    for i in range(3):
        s.add_node(
            make_node(f"n{i}").capacity(
                {"cpu": "4", "memory": "16Gi", "pods": 20}
            ).obj()
        )
    for i in range(2):  # fill n-two nodes; one node keeps room
        s.add_pod(make_pod(f"bg-{i}").req({"cpu": "3900m"}).priority(1).obj())
    s.schedule_all_pending(wait_backoff=True)
    s.preemption.expect_failures = True  # speculate on the next batch
    fits = make_pod("fits").req({"cpu": "1"}).priority(100).obj()
    vips = [
        make_pod(f"vip-{i}").req({"cpu": "3"}).priority(100).obj()
        for i in range(2)
    ]
    s.add_pod(fits)
    for p in vips:
        s.add_pod(p)
    # ONE batch: with the index-0 representative bug the failed vip's
    # speculative dry-run deferred (None) and no preemption happened this
    # batch; the fix preempts inline within it.
    s.schedule_batch()
    assert fits.spec.node_name  # placed without eviction
    assert s.metrics.preemptions >= 1
    placed = [p for p in vips if p.spec.node_name]
    assert placed, "no vip placed in the speculative batch"
    s.schedule_all_pending(wait_backoff=True)
    assert all(p.spec.node_name for p in vips)
    assert s.builder.host_mirror_equal()


# ---------------------------------------------------------------------------
# Volume/DRA release in the what-if (VERDICT r4 missing-6): a node feasible
# ONLY via a volume/DRA victim is found, with the reference's MINIMAL
# victim set — bystander pods reprieve instead of the old evict-all.


def _vol_profile():
    return Profile(
        name="vol",
        filters=("NodeResourcesFit", "VolumeRestrictions"),
        scorers=(("NodeResourcesFit", 1),),
    )


def test_device_conflict_victim_minimal_set():
    s = sched(profile=_vol_profile())
    s.add_node(make_node("n1").capacity({"cpu": "64", "pods": 110}).obj())
    s.add_pod(
        make_pod("holder").req({"cpu": "1"}).priority(1)
        .device_volume("disk-1").node("n1").obj()
    )
    s.add_pod(
        make_pod("bystander").req({"cpu": "1"}).priority(1).node("n1").obj()
    )
    s.add_pod(
        make_pod("vip").req({"cpu": "1"}).priority(100)
        .device_volume("disk-1").obj()
    )
    out = s.schedule_all_pending(wait_backoff=True)
    vip = [o for o in out if o.pod.name == "vip" and (o.victims or o.node_name)]
    assert vip, out
    # Only the device holder is evicted; the bystander reprieves (the node
    # has cpu to spare — eviction exists solely to free the device).
    assert vip[0].victim_uids == ("default/holder",)
    assert "default/bystander" in s.cache.pods
    assert "default/holder" not in s.cache.pods


def _csi_setup(s):
    from kubernetes_tpu.api.wrappers import make_pvc

    s.add_node(make_node("n1").capacity({"cpu": "64", "pods": 110}).obj())
    s.add_csinode(t.CSINode(name="n1", driver_limits={"ebs.csi.aws.com": 1}))
    s.add_storage_class(
        t.StorageClass(
            name="ebs", provisioner="ebs.csi.aws.com",
            binding_mode=t.BINDING_WAIT_FOR_FIRST_CONSUMER,
        )
    )
    for name in ("c-held", "c-new"):
        s.add_pvc(make_pvc(name, storage_class="ebs"))


def test_csi_attach_victim_minimal_set():
    s = sched(
        profile=Profile(
            name="csi",
            filters=(
                "NodeResourcesFit", "VolumeBinding", "NodeVolumeLimits",
            ),
            scorers=(("NodeResourcesFit", 1),),
        )
    )
    _csi_setup(s)
    s.add_pod(
        make_pod("holder").req({"cpu": "1"}).priority(1)
        .pvc_volume("c-held").obj()
    )
    assert s.schedule_all_pending()[0].node_name == "n1"
    s.add_pod(
        make_pod("bystander").req({"cpu": "1"}).priority(1).node("n1").obj()
    )
    s.add_pod(
        make_pod("vip").req({"cpu": "1"}).priority(100)
        .pvc_volume("c-new").obj()
    )
    out = s.schedule_all_pending(wait_backoff=True)
    vip = [o for o in out if o.pod.name == "vip" and (o.victims or o.node_name)]
    assert vip, out
    # The driver's single attach slot is held by "holder"; only it goes.
    assert vip[0].victim_uids == ("default/holder",)
    assert "default/bystander" in s.cache.pods


def test_dra_device_victim_minimal_set():
    s = sched(
        profile=Profile(
            name="dra",
            filters=("NodeResourcesFit", "DynamicResources"),
            scorers=(("NodeResourcesFit", 1),),
        )
    )
    s.add_node(make_node("n1").capacity({"cpu": "64", "pods": 110}).obj())
    s.add_resource_slice(
        t.ResourceSlice(node_name="n1", device_class="gpu", count=1)
    )
    s.add_resource_claim(t.ResourceClaim(name="held", requests=(
        t.DeviceRequest("r0", "gpu", count=1),
    )))
    s.add_resource_claim(t.ResourceClaim(name="wanted", requests=(
        t.DeviceRequest("r0", "gpu", count=1),
    )))
    s.add_pod(
        make_pod("holder").req({"cpu": "1"}).priority(1)
        .resource_claim("held").obj()
    )
    assert s.schedule_all_pending()[0].node_name == "n1"
    s.add_pod(
        make_pod("bystander").req({"cpu": "1"}).priority(1).node("n1").obj()
    )
    s.add_pod(
        make_pod("vip").req({"cpu": "1"}).priority(100)
        .resource_claim("wanted").obj()
    )
    out = s.schedule_all_pending(wait_backoff=True)
    vip = [o for o in out if o.pod.name == "vip" and (o.victims or o.node_name)]
    assert vip, out
    # The single gpu is held by "holder"'s claim; only it goes.
    assert vip[0].victim_uids == ("default/holder",)
    assert "default/bystander" in s.cache.pods


def test_external_claim_release_not_doubled():
    """Review finding: the phantom compensator must move only the claim
    COUNT — a cnt-carrying duplicate would release the pool charge twice
    and nominate a node that post-eviction truth cannot satisfy."""
    s = sched(
        profile=Profile(
            name="dra",
            filters=("NodeResourcesFit", "DynamicResources"),
            scorers=(("NodeResourcesFit", 1),),
        )
    )
    s.add_node(make_node("n1").capacity({"cpu": "64", "pods": 110}).obj())
    s.add_resource_slice(
        t.ResourceSlice(node_name="n1", device_class="gpu", count=3)
    )
    # External claim (cnt=2) solely reserved by the bound victim.
    s.add_resource_claim(t.ResourceClaim(
        name="held2", device_class="gpu", count=2,
        allocated_node="n1", reserved_for=("default/holder",),
    ))
    # A higher-priority survivor holds one more device.
    s.add_resource_claim(t.ResourceClaim(name="sheld", device_class="gpu", count=1))
    s.add_pod(
        make_pod("holder").req({"cpu": "1"}).priority(1)
        .resource_claim("held2").node("n1").obj()
    )
    s.add_pod(
        make_pod("survivor").req({"cpu": "1"}).priority(100)
        .resource_claim("sheld").obj()
    )
    assert s.schedule_all_pending()[0].node_name == "n1"
    # Preemptor needs 3 devices: truth after evicting holder = 2 free
    # (survivor keeps 1 of 3) — infeasible.  A doubled release would see
    # 3 free and nominate.
    s.add_resource_claim(t.ResourceClaim(name="want3", device_class="gpu", count=3))
    s.add_pod(
        make_pod("vip").req({"cpu": "1"}).priority(50)
        .resource_claim("want3").obj()
    )
    out = s.schedule_all_pending(wait_backoff=True)
    vip = [o for o in out if o.pod.name == "vip"]
    assert all(o.node_name is None and not o.nominated_node for o in vip), out
    assert "default/holder" in s.cache.pods  # nobody evicted


def test_incremental_repack_survives_slot_width_growth():
    # Regression (r5 review): the incremental victim-staging cache must
    # rebuild the mega-buffer when a dirty victim widens a per-victim slot
    # dim (here: device volumes 1 → 2) — the scatter path would otherwise
    # write a 2-wide slice into the staged 1-wide column span (crash), or
    # silently corrupt adjacent columns.
    s = sched(profile=_vol_profile())
    s.add_node(make_node("n1").capacity({"cpu": "64", "pods": 110}).obj())
    s.add_node(make_node("n2").capacity({"cpu": "64", "pods": 110}).obj())
    # n2 is pinned shut by an unevictable filler so every vip must fight
    # for n1.
    s.add_pod(
        make_pod("filler").req({"cpu": "64"}).priority(1000).node("n2").obj()
    )
    s.add_pod(
        make_pod("holder").req({"cpu": "1"}).priority(1)
        .device_volume("disk-1").node("n1").obj()
    )
    # First preemption stages the pack with 1-wide device-volume slots.
    s.add_pod(
        make_pod("vip1").req({"cpu": "1"}).priority(100)
        .device_volume("disk-1").obj()
    )
    s.schedule_all_pending(wait_backoff=True)
    assert "default/holder" not in s.cache.pods
    # A new victim with TWO device volumes dirties n1 and widens the slot
    # dim; the repack must take the full-rebuild branch.
    s.add_pod(
        make_pod("holder2").req({"cpu": "1"}).priority(1)
        .device_volume("disk-2").device_volume("disk-3").node("n1").obj()
    )
    s.add_pod(
        make_pod("vip2").req({"cpu": "1"}).priority(100)
        .device_volume("disk-2").obj()
    )
    out = s.schedule_all_pending(wait_backoff=True)
    vip2 = [o for o in out if o.pod.name == "vip2" and o.node_name]
    assert vip2 and vip2[0].node_name == "n1"
    assert "default/holder2" not in s.cache.pods
    assert "default/vip1" in s.cache.pods  # reprieved bystander
