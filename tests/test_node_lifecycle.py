"""The failure-response loop's durable half (ISSUE 9): journaled taint
writes and evict-with-requeue records replay deterministically, the
recovered-taints overlay survives a LIST reconcile, and Leases flow over
the wire."""

import os

from kubernetes_tpu.api import types as t
from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.controllers import (
    NODE_NOT_READY,
    NOT_READY_TAINT_KEY,
    UNREACHABLE_TAINT_KEY,
)
from kubernetes_tpu.framework.config import Profile
from kubernetes_tpu.journal import Journal, recover
from kubernetes_tpu.scheduler import TPUScheduler


def _sched():
    s = TPUScheduler(
        profile=Profile(
            name="fit-taints",
            filters=(
                "NodeUnschedulable", "NodeName", "TaintToleration",
                "NodeResourcesFit",
            ),
            scorers=(("NodeResourcesFit", 1),),
        ),
        batch_size=8,
    )
    s.node_lifecycle.arm(grace_period_s=5.0, unreachable_after_s=12.0)
    s.pod_gc.arm(gc_horizon_s=20.0)
    return s


def _graced_pod(name, seconds, node="n1"):
    return (
        make_pod(name).req({"cpu": "1"})
        .toleration(NOT_READY_TAINT_KEY, op=t.TOLERATION_OP_EXISTS,
                    effect=t.EFFECT_NO_EXECUTE, seconds=seconds)
        .toleration(UNREACHABLE_TAINT_KEY, op=t.TOLERATION_OP_EXISTS,
                    effect=t.EFFECT_NO_EXECUTE, seconds=seconds)
        .node(node).obj()
    )


def _checkpoint(s):
    """Snapshot the pre-incident world so the taint/evict RECORDS (not a
    later snapshot) are what recovery replays."""
    from kubernetes_tpu import journal as journal_mod

    s.journal.snapshot(journal_mod.scheduler_state(s))


def _drive_to_eviction(s):
    """n1 goes silent; n2 renews to logical 10 — NotReady taint written
    (journaled) and the graced pod evicted + requeued (journaled)."""
    s.add_node(make_node("n1").capacity({"cpu": "8", "pods": 110}).obj())
    s.add_node(make_node("n2").capacity({"cpu": "8", "pods": 110}).obj())
    s.add_pod(_graced_pod("p", 3))
    _checkpoint(s)
    s.renew_node_lease(t.Lease("n1", 0.0))
    s.renew_node_lease(t.Lease("n2", 0.0))
    for ts in (2.0, 4.0, 6.0, 8.0, 10.0):
        s.renew_node_lease(t.Lease("n2", ts))
    assert s.node_lifecycle.states == {"n1": NODE_NOT_READY}
    assert "default/p" not in s.cache.pods  # evicted (grace 6+3 <= 10)
    assert s.taint_eviction.evictions == 1


def test_taint_and_evict_records_replay(tmp_path):
    jdir = str(tmp_path / "j")
    s = _sched()
    s.attach_journal(Journal(jdir, fsync=False))
    _drive_to_eviction(s)
    s.journal.close()
    # A fresh process recovers from the journal alone: the taint record
    # re-applies through the same update path (lifecycle state adopted),
    # the evict record re-queues the pod, and the incident counters
    # survive the crash.
    s2 = _sched()
    j2 = Journal(jdir, fsync=False)
    recover(s2, j2)
    assert s2.node_lifecycle.states == {"n1": NODE_NOT_READY}
    keys = {ta.key for ta in s2.cache.nodes["n1"].node.spec.taints}
    assert keys == {NOT_READY_TAINT_KEY}
    assert "default/p" in s2.queue._info  # requeued, unbound
    assert "default/p" not in s2.cache.pods
    assert s2.taint_eviction.evictions == 1  # restored from the record
    # The requeued pod reschedules onto the survivor.
    out = s2.schedule_all_pending(wait_backoff=True)
    placed = [o for o in out if o.pod.uid == "default/p" and o.node_name]
    assert placed and placed[0].node_name == "n2"


def test_reconcile_overlay_preserves_journaled_taints(tmp_path):
    # Host truth relists the dead node in its ORIGINAL untainted shape
    # (the apiserver analog never saw our in-process taint write) — the
    # recovered-taints overlay must keep the journal-authored lifecycle
    # taints, or the LIST-replace would heal the dead node.
    from kubernetes_tpu.informers import (
        FakeSource,
        Reflector,
        reconcile_after_recovery,
    )

    jdir = str(tmp_path / "j")
    s = _sched()
    s.attach_journal(Journal(jdir, fsync=False))
    s.add_node(make_node("n1").capacity({"cpu": "8", "pods": 110}).obj())
    s.add_node(make_node("n2").capacity({"cpu": "8", "pods": 110}).obj())
    s.add_pod(_graced_pod("slow", 60))  # armed but far from due
    _checkpoint(s)
    s.renew_node_lease(t.Lease("n1", 0.0))
    s.renew_node_lease(t.Lease("n2", 0.0))
    s.renew_node_lease(t.Lease("n2", 7.0))  # NotReady taint written
    assert "default/slow" in s.taint_eviction.pending
    s.journal.close()
    s2 = _sched()
    recover(s2, Journal(jdir, fsync=False))
    nsrc, psrc = FakeSource(), FakeSource()
    nsrc.add("n1", make_node("n1").capacity({"cpu": "8", "pods": 110}).obj())
    nsrc.add("n2", make_node("n2").capacity({"cpu": "8", "pods": 110}).obj())
    psrc.add("default/slow", _graced_pod("slow", 60))
    reconcile_after_recovery(
        s2,
        Reflector(s2, "Node", nsrc.lister, nsrc.watcher),
        Reflector(s2, "Pod", psrc.lister, psrc.watcher),
    )
    keys = {ta.key for ta in s2.cache.nodes["n1"].node.spec.taints}
    assert keys == {NOT_READY_TAINT_KEY}  # the overlay held
    assert "default/slow" in s2.taint_eviction.pending  # still armed
    assert s2.cache.nodes["n2"].node.spec.taints == ()


def test_recovery_continues_logical_clock_without_instant_evictions(tmp_path):
    # Review regression: the feed's clock keeps running across a restart.
    # The snapshot carries heartbeats + the clock high-water mark and the
    # taint records carry their write ts, so a recovered process re-arms
    # pending graces against the INCIDENT's clock — the first
    # post-restart renewal (ts ≈ where the feed left off) must not fire
    # a restored 60s grace instantly.
    jdir = str(tmp_path / "j")
    s = _sched()
    s.attach_journal(Journal(jdir, fsync=False))
    s.add_node(make_node("n1").capacity({"cpu": "8", "pods": 110}).obj())
    s.add_node(make_node("n2").capacity({"cpu": "8", "pods": 110}).obj())
    s.add_pod(_graced_pod("slow", 60))
    _checkpoint(s)  # heartbeats empty at the barrier
    s.renew_node_lease(t.Lease("n1", 1000.0))
    s.renew_node_lease(t.Lease("n2", 1000.0))
    s.renew_node_lease(t.Lease("n2", 1007.0))  # NotReady written at 1007
    assert s.taint_eviction.pending["default/slow"][1] >= 1067.0
    _checkpoint(s)  # clock + heartbeats now in the snapshot
    s.renew_node_lease(t.Lease("n2", 1008.0))
    s.journal.close()
    s2 = _sched()
    recover(s2, Journal(jdir, fsync=False))
    assert s2.node_lifecycle.now() >= 1007.0  # clock continued, not 0
    assert s2.node_lifecycle.heartbeats.get("n2", 0.0) >= 1007.0
    # The feed resumes where it left off: no instant eviction.
    s2.renew_node_lease(t.Lease("n2", 1010.0))
    assert "default/slow" in s2.cache.pods
    assert "default/slow" in s2.taint_eviction.pending
    # n1 crosses Unreachable at 1014: the taint SWAP re-arms the grace
    # (per-taint clocks — the new taint starts fresh at ~1014).
    s2.renew_node_lease(t.Lease("n2", 1014.0))
    assert "default/slow" in s2.cache.pods
    # The grace still fires when genuinely due on the same clock.
    s2.renew_node_lease(t.Lease("n2", 1014.0 + 61.0))
    assert "default/slow" not in s2.cache.pods


def test_recovered_orphan_binding_requeues_through_gc(tmp_path):
    # A journaled bind whose node never relists: the armed pod-GC
    # requeues the pod (journaled evict) instead of dropping it.
    from kubernetes_tpu.informers import (
        FakeSource,
        Reflector,
        reconcile_after_recovery,
    )

    jdir = str(tmp_path / "j")
    s = _sched()
    s.attach_journal(Journal(jdir, fsync=False))
    s.add_node(make_node("gone").capacity({"cpu": "8", "pods": 110}).obj())
    s.add_node(make_node("n2").capacity({"cpu": "8", "pods": 110}).obj())
    s.add_pod(make_pod("orphan").req({"cpu": "1"}).obj())
    out = s.schedule_all_pending(wait_backoff=True)
    assert any(o.pod.name == "orphan" and o.node_name for o in out)
    s.journal.close()
    s2 = _sched()
    j2 = Journal(jdir, fsync=False)
    recover(s2, j2)  # before attach — replay must not re-journal
    s2.attach_journal(j2)
    nsrc, psrc = FakeSource(), FakeSource()
    nsrc.add("n2", make_node("n2").capacity({"cpu": "8", "pods": 110}).obj())
    psrc.add("default/orphan", make_pod("orphan").req({"cpu": "1"}).obj())
    stats = reconcile_after_recovery(
        s2,
        Reflector(s2, "Node", nsrc.lister, nsrc.watcher),
        Reflector(s2, "Pod", psrc.lister, psrc.watcher),
    )
    # The bind parked (node gone) and the GC requeued it.
    assert (
        stats["late_bindings_requeued"] == 1
        or "default/orphan" in s2.queue._info
    )
    assert s2.pod_gc.collected["orphaned"] >= 0
    out = s2.schedule_all_pending(wait_backoff=True)
    placed = [o for o in out if o.pod.uid == "default/orphan" and o.node_name]
    assert placed and placed[0].node_name == "n2"


def test_lease_flows_over_the_wire(tmp_path):
    # The Lease kind rides the sidecar's AddObject surface end to end:
    # renewals over the socket drive the server's lifecycle controller.
    from kubernetes_tpu.sidecar.server import SidecarClient, SidecarServer

    path = os.path.join(str(tmp_path), "sidecar.sock")
    srv = SidecarServer(path, scheduler=_sched())
    srv.serve_background()
    try:
        client = SidecarClient(path)
        client.add(
            "Node", make_node("w1").capacity({"cpu": "8", "pods": 110}).obj()
        )
        client.add(
            "Node", make_node("w2").capacity({"cpu": "8", "pods": 110}).obj()
        )
        client.add("Lease", t.Lease("w1", 0.0))
        client.add("Lease", t.Lease("w2", 0.0))
        client.add("Lease", t.Lease("w2", 7.0))
        dump = client.dump()
        assert dump["node_lifecycle"]["states"]["notready"] == 1
        assert dump["node_lifecycle"]["armed"] is True
        client.close()
    finally:
        srv.close()


def test_evict_pod_with_supplied_object_requeues_unknown_uid():
    s = _sched()
    s.add_node(make_node("n2").capacity({"cpu": "8", "pods": 110}).obj())
    ghost = make_pod("ghost").req({"cpu": "1"}).node("gone-node").obj()
    assert s.evict_pod("default/ghost") is False  # unknown, no object
    assert s.evict_pod("default/ghost", pod=ghost) is True
    qp = s.queue._info.get("default/ghost")
    assert qp is not None and qp.pod.spec.node_name == ""
