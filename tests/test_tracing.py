"""utiltrace analog: cycle spans logged only past the threshold
(schedule_one.go:412 LogIfLong)."""

import logging

from kubernetes_tpu.framework.tracing import Trace


def test_trace_silent_when_fast(caplog):
    with caplog.at_level(logging.INFO, logger="kubernetes_tpu"):
        with Trace("fast", threshold_s=10.0, pods=3) as tr:
            tr.step("a")
    assert not caplog.records


def test_trace_logs_steps_when_slow(caplog):
    with caplog.at_level(logging.INFO, logger="kubernetes_tpu"):
        tr = Trace("slow", threshold_s=0.0, pods=3)
        tr.step("featurized")
        tr.step("dispatched")
        assert tr.log_if_long()
    text = caplog.text
    assert "slow" in text and "pods=3" in text
    assert "featurized" in text and "dispatched" in text


def test_scheduler_batch_emits_span_when_slow(caplog):
    from kubernetes_tpu.api.wrappers import make_node, make_pod
    from kubernetes_tpu.scheduler import TPUScheduler

    s = TPUScheduler(batch_size=4)
    s.trace_threshold_s = 0.0  # everything is "long"
    s.add_node(make_node("n1").capacity({"cpu": "4", "memory": "8Gi"}).obj())
    s.add_pod(make_pod("p").req({"cpu": "1"}).obj())
    with caplog.at_level(logging.INFO, logger="kubernetes_tpu"):
        s.schedule_all_pending()
    assert any("ScheduleBatch" in r.message for r in caplog.records)
