"""Sidecar crash/restart: the host replays its informer-store truth into a
fresh sidecar (app/server.go:249–271 resync-on-restart), and a live
scheduler can rebuild its device mirror from host staging on demand."""

import tempfile

from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.scheduler import TPUScheduler
from kubernetes_tpu.sidecar import SidecarServer
from kubernetes_tpu.sidecar.host import ResyncingClient


def small_node(name: str, cpu: str = "4"):
    return make_node(name).capacity(
        {"cpu": cpu, "memory": "16Gi", "pods": 110}
    ).obj()


def test_sidecar_restart_resyncs_and_keeps_accounting():
    path = tempfile.mktemp(suffix=".sock")
    srv = SidecarServer(path, scheduler=TPUScheduler(batch_size=8))
    srv.serve_background()
    client = ResyncingClient(path, max_reconnect_s=5.0)
    try:
        # Two small nodes; fill n0 almost completely before the crash.
        client.add("Node", small_node("n0"))
        client.add("Node", small_node("n1"))
        pods1 = [make_pod(f"a{i}").req({"cpu": "2"}).obj() for i in range(2)]
        res1 = client.schedule(pods1)
        bound1 = {r.pod_uid: r.node_name for r in res1}
        assert sorted(bound1.values()).count("") == 0
        per_node = {}
        for n in bound1.values():
            per_node[n] = per_node.get(n, 0) + 1

        # KILL the sidecar mid-workload and bring up a FRESH one (empty
        # scheduler) on the same socket.
        srv.close()
        srv = SidecarServer(path, scheduler=TPUScheduler(batch_size=8))
        srv.serve_background()

        # The next call fails on the dead connection, reconnects, replays
        # the store (nodes + bound pods), and re-issues.
        res2 = client.schedule([make_pod("b0").req({"cpu": "2"}).obj()])
        assert client.resyncs == 1
        b0 = {r.pod_uid: r.node_name for r in res2}["default/b0"]
        assert b0  # scheduled somewhere

        # Accounting survived the restart: each 4-cpu node holds at most
        # two 2-cpu pods across both generations.
        dump = client.dump()
        pods_per_node = {}
        for uid, rec in dump["pods"].items():
            pods_per_node.setdefault(rec["node"], []).append(uid)
        for node, uids in pods_per_node.items():
            assert len(uids) <= 2, (node, uids)
        # Every pre-crash binding is present in the restarted sidecar with
        # the SAME node (replayed as bound adds, not rescheduled).
        for uid, node in bound1.items():
            assert dump["pods"][uid]["node"] == node
        assert dump["mirror_equal"]

        # Exactly one 2-cpu slot remains (2 nodes × 2 slots − a0,a1,b0):
        # capacity math across the restart stays consistent.
        res3 = client.schedule([make_pod("c0").req({"cpu": "2"}).obj()])
        assert {r.pod_uid: r.node_name for r in res3}["default/c0"]
        res4 = client.schedule([make_pod("c1").req({"cpu": "2"}).obj()])
        assert {r.pod_uid: r.node_name for r in res4}["default/c1"] == ""
    finally:
        client.close()
        srv.close()


def test_resync_drops_removed_objects():
    path = tempfile.mktemp(suffix=".sock")
    srv = SidecarServer(path, scheduler=TPUScheduler(batch_size=8))
    srv.serve_background()
    client = ResyncingClient(path, max_reconnect_s=5.0)
    try:
        client.add("Node", small_node("n0"))
        client.add("Node", small_node("gone"))
        client.remove("Node", "gone")
        srv.close()
        srv = SidecarServer(path, scheduler=TPUScheduler(batch_size=8))
        srv.serve_background()
        dump = client.dump()  # triggers resync
        assert client.resyncs == 1
        assert set(dump["nodes"]) == {"n0"}
    finally:
        client.close()
        srv.close()


def test_live_device_rebuild_from_host_truth():
    s = TPUScheduler(batch_size=4)
    s.add_node(small_node("n0"))
    s.add_pod(make_pod("p0").req({"cpu": "2"}).obj())
    out = s.schedule_all_pending()
    assert [o.node_name for o in out] == ["n0"]
    # Simulate suspect device state, then rebuild from host staging.
    s.rebuild_device_state()
    assert s.builder._dirty_all
    s.add_pod(make_pod("p1").req({"cpu": "2"}).obj())
    out2 = s.schedule_all_pending()
    assert [o.node_name for o in out2] == ["n0"]
    # Rebuilt mirror agrees with host truth and keeps prior accounting.
    assert s.builder.host_mirror_equal()
    s.add_pod(make_pod("p2").req({"cpu": "2"}).obj())
    out3 = s.schedule_all_pending()
    assert out3[0].node_name is None  # node full: 2+2 of 4 cpu used
