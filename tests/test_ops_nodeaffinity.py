"""NodeAffinity vectorized op vs scalar reference semantics."""

import numpy as np

from kubernetes_tpu.api import types as t
from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.framework.config import Profile
from kubernetes_tpu.scheduler import TPUScheduler

from reference_impl import node_affinity_filter, node_affinity_score_raw


def na_profile():
    return Profile(
        name="na", filters=("NodeAffinity",), scorers=(("NodeAffinity", 2),)
    )


def sched(profile=None, batch_size=16):
    return TPUScheduler(profile=profile or na_profile(), batch_size=batch_size)


def _with_required(pod, *terms):
    pod.spec.affinity = t.Affinity(
        node_affinity=t.NodeAffinity(required=t.NodeSelector(terms=tuple(terms)))
    )
    return pod


def test_node_selector_map():
    s = sched()
    s.add_node(make_node("gpu").capacity({"cpu": "4", "pods": 110}).label("accel", "tpu").obj())
    s.add_node(make_node("plain").capacity({"cpu": "4", "pods": 110}).obj())
    s.add_pod(make_pod("p").req({"cpu": "1"}).node_selector({"accel": "tpu"}).obj())
    assert s.schedule_all_pending()[0].node_name == "gpu"


def test_required_in_operator():
    s = sched()
    s.add_node(make_node("a").capacity({"cpu": "4", "pods": 110}).label("disk", "ssd").obj())
    s.add_node(make_node("b").capacity({"cpu": "4", "pods": 110}).label("disk", "hdd").obj())
    s.add_pod(make_pod("p").req({"cpu": "1"}).node_affinity_in("disk", ["ssd"]).obj())
    assert s.schedule_all_pending()[0].node_name == "a"


def test_terms_are_ored():
    s = sched()
    s.add_node(make_node("a").capacity({"cpu": "4", "pods": 110}).label("disk", "hdd").obj())
    term1 = t.NodeSelectorTerm(
        match_expressions=(t.NodeSelectorRequirement("disk", t.OP_IN, ("ssd",)),)
    )
    term2 = t.NodeSelectorTerm(
        match_expressions=(t.NodeSelectorRequirement("disk", t.OP_IN, ("hdd",)),)
    )
    pod = _with_required(make_pod("p").req({"cpu": "1"}).obj(), term1, term2)
    s.add_pod(pod)
    assert s.schedule_all_pending()[0].node_name == "a"


def test_gt_lt_operators():
    s = sched()
    s.add_node(make_node("big").capacity({"cpu": "4", "pods": 110}).label("cores", "64").obj())
    s.add_node(make_node("small").capacity({"cpu": "4", "pods": 110}).label("cores", "8").obj())
    s.add_node(make_node("weird").capacity({"cpu": "4", "pods": 110}).label("cores", "banana").obj())
    term = t.NodeSelectorTerm(
        match_expressions=(t.NodeSelectorRequirement("cores", t.OP_GT, ("16",)),)
    )
    s.add_pod(_with_required(make_pod("p").req({"cpu": "1"}).obj(), term))
    out = s.schedule_all_pending()
    assert out[0].node_name == "big"
    assert out[0].feasible_nodes == 1  # non-integer label can never satisfy Gt


def test_match_fields_node_name():
    s = sched()
    for i in range(3):
        s.add_node(make_node(f"n{i}").capacity({"cpu": "4", "pods": 110}).obj())
    term = t.NodeSelectorTerm(
        match_fields=(t.NodeSelectorRequirement("metadata.name", t.OP_IN, ("n1",)),)
    )
    s.add_pod(_with_required(make_pod("p").req({"cpu": "1"}).obj(), term))
    assert s.schedule_all_pending()[0].node_name == "n1"


def test_empty_required_terms_match_nothing():
    s = sched()
    s.add_node(make_node("n0").capacity({"cpu": "4", "pods": 110}).obj())
    pod = make_pod("p").req({"cpu": "1"}).obj()
    pod.spec.affinity = t.Affinity(node_affinity=t.NodeAffinity(required=t.NodeSelector(terms=())))
    s.add_pod(pod)
    assert s.schedule_all_pending()[0].node_name is None


def test_unknown_label_key_selector():
    """Selecting on a key no node carries is simply infeasible (and must not
    crash interning)."""
    s = sched()
    s.add_node(make_node("n0").capacity({"cpu": "4", "pods": 110}).obj())
    s.add_pod(make_pod("p").req({"cpu": "1"}).node_selector({"never-seen": "x"}).obj())
    assert s.schedule_all_pending()[0].node_name is None


def test_preferred_weights_pick_heavier_match():
    s = sched()
    s.add_node(make_node("a").capacity({"cpu": "4", "pods": 110}).label("tier", "gold").obj())
    s.add_node(make_node("b").capacity({"cpu": "4", "pods": 110}).label("tier", "silver").obj())
    s.add_pod(
        make_pod("p")
        .req({"cpu": "1"})
        .preferred_node_affinity_in("tier", ["gold"], weight=10)
        .preferred_node_affinity_in("tier", ["silver"], weight=3)
        .obj()
    )
    assert s.schedule_all_pending()[0].node_name == "a"


def _random_requirement(rng) -> t.NodeSelectorRequirement:
    keys = [f"k{i}" for i in range(4)] + ["num"]
    ops = [t.OP_IN, t.OP_NOT_IN, t.OP_EXISTS, t.OP_DOES_NOT_EXIST, t.OP_GT, t.OP_LT]
    op = ops[int(rng.integers(0, len(ops)))]
    key = keys[int(rng.integers(0, len(keys)))]
    if op in (t.OP_GT, t.OP_LT):
        return t.NodeSelectorRequirement("num", op, (str(int(rng.integers(0, 100))),))
    vals = tuple(f"v{int(rng.integers(0, 4))}" for _ in range(int(rng.integers(1, 3))))
    return t.NodeSelectorRequirement(key, op, vals if op in (t.OP_IN, t.OP_NOT_IN) else ())


def test_matches_reference_randomized():
    rng = np.random.default_rng(3)
    nodes = []
    for i in range(30):
        w = make_node(f"n{i}").capacity({"cpu": "64", "pods": 110})
        for k in range(4):
            if rng.integers(0, 2):
                w = w.label(f"k{k}", f"v{int(rng.integers(0, 4))}")
        if rng.integers(0, 2):
            w = w.label("num", str(int(rng.integers(0, 100))))
        nodes.append(w.obj())

    pods = []
    for i in range(40):
        w = make_pod(f"p{i}").req({"cpu": "1m"})
        pod = w.obj()
        n_terms = int(rng.integers(0, 3))
        terms = []
        for _ in range(n_terms):
            reqs = tuple(_random_requirement(rng) for _ in range(int(rng.integers(1, 3))))
            terms.append(t.NodeSelectorTerm(match_expressions=reqs))
        preferred = []
        for _ in range(int(rng.integers(0, 3))):
            reqs = tuple(_random_requirement(rng) for _ in range(int(rng.integers(1, 3))))
            preferred.append(
                t.PreferredSchedulingTerm(
                    weight=int(rng.integers(1, 20)),
                    preference=t.NodeSelectorTerm(match_expressions=reqs),
                )
            )
        if terms or preferred:
            pod.spec.affinity = t.Affinity(
                node_affinity=t.NodeAffinity(
                    required=t.NodeSelector(terms=tuple(terms)) if terms else None,
                    preferred=tuple(preferred),
                )
            )
        pods.append(pod)

    s = sched(batch_size=64)
    for n in nodes:
        s.add_node(n)
    for p in pods:
        s.add_pod(p)
    out = {o.pod.name: o for o in s.schedule_all_pending()}

    for p in pods:
        feas = [n for n in nodes if node_affinity_filter(p, n)]
        o = out[p.name]
        assert o.feasible_nodes == len(feas), (p.name, o.feasible_nodes, len(feas))
        if feas:
            raws = {n.name: node_affinity_score_raw(p, n) for n in feas}
            best = max(raws.values())
            assert raws[o.node_name] == best, (p.name, o.node_name, raws)
        else:
            assert o.node_name is None
