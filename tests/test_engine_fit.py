"""End-to-end slice: NodeResourcesFit-only profile through the device pass,
validated against the scalar reference implementation (sequential-equivalent:
the scan must behave exactly like scheduling the pods one at a time)."""

import numpy as np
import pytest

from kubernetes_tpu.api import types as t
from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.framework.config import Profile, ScoringStrategy, fit_only_profile
from kubernetes_tpu.scheduler import TPUScheduler

from reference_impl import RefNodeState, fit_score, fits_request


def mk_sched(profile=None, batch_size=64):
    return TPUScheduler(profile=profile or fit_only_profile(), batch_size=batch_size)


def splitmix32(x: int) -> int:
    """The engine's deterministic tie-break hash (engine/pass_.py:_hash_u32)."""
    x &= 0xFFFFFFFF
    x = ((x ^ (x >> 16)) * 0x7FEB352D) & 0xFFFFFFFF
    x = ((x ^ (x >> 15)) * 0x846CA68B) & 0xFFFFFFFF
    return x ^ (x >> 16)


def seq_reference(nodes, pods, strategy="LeastAllocated", seed=0):
    """Schedule pods sequentially with the scalar reference semantics and the
    engine's deterministic tie-break: among max-score feasible nodes in row
    order, pick the (splitmix32(seed*2654435761 + step) % m)-th."""
    states = {n.name: RefNodeState(node=n) for n in nodes}
    order = [n.name for n in nodes]
    out = []
    for step, pod in enumerate(pods):
        scores = {}
        for name in order:
            ns = states[name]
            if fits_request(pod, ns):
                continue
            scores[name] = fit_score(pod, ns, strategy=strategy)
        if not scores:
            out.append(None)
            continue
        best_score = max(scores.values())
        ties = [n for n in order if scores.get(n) == best_score]
        k = splitmix32((seed * 2654435761 + step) & 0xFFFFFFFF) % len(ties)
        best = ties[k]
        states[best].pods.append(pod)
        out.append(best)
    return out


def test_single_pod_single_node():
    s = mk_sched()
    s.add_node(make_node("n1").capacity({"cpu": "4", "memory": "8Gi", "pods": 110}).obj())
    s.add_pod(make_pod("p1").req({"cpu": "1", "memory": "1Gi"}).obj())
    out = s.schedule_all_pending()
    assert len(out) == 1
    assert out[0].node_name == "n1"


def test_unschedulable_when_too_big():
    s = mk_sched()
    s.add_node(make_node("n1").capacity({"cpu": "1", "memory": "1Gi", "pods": 110}).obj())
    s.add_pod(make_pod("p1").req({"cpu": "2"}).obj())
    out = s.schedule_all_pending()
    assert out[0].node_name is None
    assert s.queue.pending_count() == 1  # parked in unschedulable pool


def test_pod_count_limit():
    s = mk_sched()
    s.add_node(make_node("n1").capacity({"cpu": "64", "memory": "64Gi", "pods": 2}).obj())
    for i in range(3):
        s.add_pod(make_pod(f"p{i}").req({"cpu": "100m"}).obj())
    out = s.schedule_all_pending()
    placed = [o for o in out if o.node_name]
    assert len(placed) == 2


def test_zero_request_pod_only_pod_count_matters():
    s = mk_sched()
    # Node with zero free cpu but pod slots available.
    s.add_node(make_node("n1").capacity({"cpu": "1", "memory": "1Gi", "pods": 10}).obj())
    s.add_pod(make_pod("big").req({"cpu": "1", "memory": "1Gi"}).obj())
    s.add_pod(make_pod("empty").obj())  # requests nothing
    out = s.schedule_all_pending()
    assert all(o.node_name == "n1" for o in out)


def test_least_allocated_prefers_empty_node():
    s = mk_sched()
    s.add_node(make_node("busy").capacity({"cpu": "4", "memory": "8Gi", "pods": 110}).obj())
    s.add_node(make_node("idle").capacity({"cpu": "4", "memory": "8Gi", "pods": 110}).obj())
    # Pre-load "busy" with an assigned pod.
    s.add_pod(make_pod("existing").req({"cpu": "3", "memory": "6Gi"}).node("busy").obj())
    s.add_pod(make_pod("new").req({"cpu": "1", "memory": "1Gi"}).obj())
    out = s.schedule_all_pending()
    assert out[0].node_name == "idle"


def test_most_allocated_packs():
    prof = Profile(
        name="pack",
        filters=("NodeResourcesFit",),
        scorers=(("NodeResourcesFit", 1),),
        scoring_strategy=ScoringStrategy(type="MostAllocated"),
    )
    s = mk_sched(profile=prof)
    s.add_node(make_node("busy").capacity({"cpu": "4", "memory": "8Gi", "pods": 110}).obj())
    s.add_node(make_node("idle").capacity({"cpu": "4", "memory": "8Gi", "pods": 110}).obj())
    s.add_pod(make_pod("existing").req({"cpu": "2", "memory": "4Gi"}).node("busy").obj())
    s.add_pod(make_pod("new").req({"cpu": "1", "memory": "1Gi"}).obj())
    out = s.schedule_all_pending()
    assert out[0].node_name == "busy"


def test_sequential_equivalence_within_batch():
    """The whole batch commits sequentially on device: later pods must see
    earlier pods' resources."""
    s = mk_sched(batch_size=8)
    s.add_node(make_node("n1").capacity({"cpu": "2", "memory": "4Gi", "pods": 110}).obj())
    s.add_node(make_node("n2").capacity({"cpu": "2", "memory": "4Gi", "pods": 110}).obj())
    for i in range(4):
        s.add_pod(make_pod(f"p{i}").req({"cpu": "1", "memory": "1Gi"}).obj())
    out = s.schedule_all_pending()
    # 4 pods of 1 cpu over 2 nodes of 2 cpu: all must fit, 2 per node.
    placed = [o.node_name for o in out]
    assert all(placed)
    assert sorted(placed) == ["n1", "n1", "n2", "n2"]


@pytest.mark.parametrize("strategy", ["LeastAllocated", "MostAllocated"])
def test_matches_scalar_reference_randomized(strategy):
    rng = np.random.default_rng(42)
    nodes = []
    for i in range(20):
        cpu = int(rng.integers(2, 16))
        mem_gi = int(rng.integers(2, 32))
        nodes.append(
            make_node(f"n{i}")
            .capacity({"cpu": cpu, "memory": f"{mem_gi}Gi", "pods": 32})
            .obj()
        )
    pods = []
    for i in range(60):
        cpu_m = int(rng.integers(1, 40)) * 97  # odd numbers → distinct scores
        mem = int(rng.integers(1, 2000)) * 1048573
        pods.append(make_pod(f"p{i}").req({"cpu": f"{cpu_m}m", "memory": mem}).obj())

    prof = Profile(
        name=f"ref-{strategy}",
        filters=("NodeResourcesFit",),
        scorers=(("NodeResourcesFit", 1),),
        scoring_strategy=ScoringStrategy(type=strategy),
    )
    s = mk_sched(profile=prof, batch_size=64)
    for n in nodes:
        s.add_node(n)
    for p in pods:
        s.add_pod(p)
    got = {o.pod.name: o.node_name for o in s.schedule_all_pending()}

    want = seq_reference(nodes, pods, strategy=strategy)
    mismatches = []
    for pod, w in zip(pods, want):
        g = got[pod.name]
        if g != w:
            mismatches.append((pod.name, g, w))
    # Tie-break differences are legitimate (device picks hash-uniform among
    # ties, scalar picks first); with odd-prime requests ties are rare but
    # possible — allow none for unschedulable mismatches and assert equality
    # of the multiset of feasibility decisions.
    assert [(g is None) for g in [got[p.name] for p in pods]] == [
        (w is None) for w in want
    ]
    assert not mismatches, mismatches[:5]


def test_host_device_mirror_consistency():
    """After a batch the host staging arrays must equal the device tensors
    (the cache-comparer analog, backend/cache/debugger)."""
    s = mk_sched()
    for i in range(4):
        s.add_node(make_node(f"n{i}").capacity({"cpu": "8", "memory": "16Gi", "pods": 64}).obj())
    for i in range(10):
        s.add_pod(make_pod(f"p{i}").req({"cpu": "500m", "memory": "256Mi"}).obj())
    s.schedule_all_pending()
    assert s.builder.host_mirror_equal()


def test_pinned_template_without_nodeaffinity_op():
    """Name-pinned pods under a profile WITHOUT NodeAffinity (pin enforced
    by the host-side pin_row, not the filter): template hits must not
    inject na_req_vals into dicts that never had it (review finding —
    heterogeneous dicts crash the stack step)."""
    from kubernetes_tpu.framework.config import fit_only_profile

    s = TPUScheduler(profile=fit_only_profile(), batch_size=8)
    for i in range(8):
        s.add_node(
            make_node(f"node-{i}").capacity(
                {"cpu": "8", "memory": "32Gi", "pods": 10}
            ).obj()
        )
    for i in range(8):
        s.add_pod(
            make_pod(f"ds-{i}")
            .req({"cpu": "1"})
            .node_name_affinity(f"node-{i}")
            .obj()
        )
    outs = s.schedule_all_pending()
    assert len(outs) == 8
    # NodeAffinity is not in the profile, so the pin is enforced by the
    # pinned pass itself.
    for o in outs:
        assert o.node_name == f"node-{o.pod.name.split('-')[1]}"
