"""InterPodAffinity vectorized op vs scalar reference semantics."""

import numpy as np

from kubernetes_tpu.api import types as t
from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.framework.config import Profile
from kubernetes_tpu.scheduler import TPUScheduler

from reference_impl import ipa_filter, ipa_score


def ipa_profile(with_score=True):
    return Profile(
        name="ipa",
        filters=("NodeResourcesFit", "InterPodAffinity"),
        scorers=(("InterPodAffinity", 2),) if with_score else (),
    )


def zones(s, per_zone=2, names=("a", "b", "c")):
    nodes = []
    for z in names:
        for i in range(per_zone):
            n = (
                make_node(f"n-{z}{i}")
                .capacity({"cpu": "64", "pods": 110})
                .zone(z)
                .obj()
            )
            s.add_node(n)
            nodes.append(n)
    return nodes


ZONE = "topology.kubernetes.io/zone"


def test_required_affinity_needs_matching_pod():
    s = TPUScheduler(profile=ipa_profile(False), batch_size=8)
    zones(s)
    s.add_pod(make_pod("existing").req({"cpu": "1"}).label("app", "db").node("n-b0").obj())
    s.add_pod(
        make_pod("p").req({"cpu": "1"}).pod_affinity_in("app", ["db"], ZONE).obj()
    )
    out = s.schedule_all_pending()
    assert out[0].node_name in ("n-b0", "n-b1")
    assert out[0].feasible_nodes == 2


def test_lonely_first_pod_self_match():
    """A pod with affinity to its own labels schedules when no pods match."""
    s = TPUScheduler(profile=ipa_profile(False), batch_size=8)
    zones(s)
    s.add_pod(
        make_pod("p")
        .req({"cpu": "1"})
        .label("app", "web")
        .pod_affinity_in("app", ["web"], ZONE)
        .obj()
    )
    out = s.schedule_all_pending()
    assert out[0].node_name is not None
    assert out[0].feasible_nodes == 6


def test_lonely_first_pod_without_self_match_stays_pending():
    s = TPUScheduler(profile=ipa_profile(False), batch_size=8)
    zones(s)
    s.add_pod(
        make_pod("p").req({"cpu": "1"}).pod_affinity_in("app", ["db"], ZONE).obj()
    )
    out = s.schedule_all_pending()
    assert out[0].node_name is None


def test_required_anti_affinity_blocks_domain():
    s = TPUScheduler(profile=ipa_profile(False), batch_size=8)
    zones(s)
    s.add_pod(make_pod("existing").req({"cpu": "1"}).label("app", "db").node("n-a0").obj())
    s.add_pod(
        make_pod("p").req({"cpu": "1"}).pod_anti_affinity_in("app", ["db"], ZONE).obj()
    )
    out = s.schedule_all_pending()
    assert out[0].node_name is not None
    assert not out[0].node_name.startswith("n-a")
    assert out[0].feasible_nodes == 4


def test_existing_pod_anti_affinity_repels_incoming():
    """An existing pod's required anti-affinity keeps matching pods away."""
    s = TPUScheduler(profile=ipa_profile(False), batch_size=8)
    zones(s)
    s.add_pod(
        make_pod("guard")
        .req({"cpu": "1"})
        .pod_anti_affinity_in("app", ["web"], ZONE)
        .node("n-c0")
        .obj()
    )
    s.add_pod(make_pod("p").req({"cpu": "1"}).label("app", "web").obj())
    out = s.schedule_all_pending()
    assert out[0].node_name is not None
    assert not out[0].node_name.startswith("n-c")
    assert out[0].feasible_nodes == 4


def test_within_batch_anti_affinity_sequencing():
    """Pods committed earlier in the same batch repel later ones."""
    s = TPUScheduler(profile=ipa_profile(False), batch_size=8)
    zones(s, per_zone=1)
    for i in range(4):
        s.add_pod(
            make_pod(f"p{i}")
            .req({"cpu": "1"})
            .label("app", "web")
            .pod_anti_affinity_in("app", ["web"], ZONE)
            .obj()
        )
    out = {o.pod.name: o.node_name for o in s.schedule_all_pending()}
    placed = [v for v in out.values() if v]
    assert len(placed) == 3  # one pod per zone, fourth unschedulable
    assert len(set(placed)) == 3


def test_preferred_affinity_attracts():
    s = TPUScheduler(profile=ipa_profile(True), batch_size=8)
    zones(s, per_zone=1)
    s.add_pod(make_pod("buddy").req({"cpu": "1"}).label("app", "db").node("n-b0").obj())
    s.add_pod(
        make_pod("p")
        .req({"cpu": "1"})
        .preferred_pod_affinity_in("app", ["db"], ZONE, weight=50)
        .obj()
    )
    out = s.schedule_all_pending()
    assert out[0].node_name == "n-b0"


def test_preferred_anti_affinity_repels():
    s = TPUScheduler(profile=ipa_profile(True), batch_size=8)
    zones(s, per_zone=1, names=("a", "b"))
    s.add_pod(make_pod("noisy").req({"cpu": "1"}).label("app", "db").node("n-b0").obj())
    s.add_pod(
        make_pod("p")
        .req({"cpu": "1"})
        .preferred_pod_affinity_in("app", ["db"], ZONE, weight=50, anti=True)
        .obj()
    )
    out = s.schedule_all_pending()
    assert out[0].node_name == "n-a0"


def test_existing_pods_preferred_terms_score_incoming():
    """Existing pods' preferred affinity pulls matching incoming pods."""
    s = TPUScheduler(profile=ipa_profile(True), batch_size=8)
    zones(s, per_zone=1, names=("a", "b"))
    magnet = (
        make_pod("magnet")
        .req({"cpu": "1"})
        .preferred_pod_affinity_in("app", ["web"], ZONE, weight=80)
        .node("n-a0")
        .obj()
    )
    s.add_pod(magnet)
    s.add_pod(make_pod("p").req({"cpu": "1"}).label("app", "web").obj())
    out = s.schedule_all_pending()
    assert out[0].node_name == "n-a0"


def test_matches_reference_randomized():
    rng = np.random.default_rng(23)
    apps = ["web", "db", "cache"]
    nodes = []
    s = TPUScheduler(profile=ipa_profile(True), batch_size=64)
    for i in range(12):
        n = (
            make_node(f"n{i}")
            .capacity({"cpu": "640", "pods": 200})
            .zone(f"z{i % 3}")
            .obj()
        )
        s.add_node(n)
        nodes.append(n)

    pods = []
    for i in range(40):
        app = apps[int(rng.integers(0, 3))]
        w = make_pod(f"p{i}").req({"cpu": "100m"}).label("app", app)
        r = int(rng.integers(0, 5))
        target = apps[int(rng.integers(0, 3))]
        topo = ZONE if rng.integers(0, 2) else "kubernetes.io/hostname"
        if r == 0:
            w = w.pod_affinity_in("app", [target], topo)
        elif r == 1:
            w = w.pod_anti_affinity_in("app", [target], topo)
        elif r == 2:
            w = w.preferred_pod_affinity_in("app", [target], topo, weight=int(rng.integers(1, 100)))
        elif r == 3:
            w = w.preferred_pod_affinity_in("app", [target], topo, weight=int(rng.integers(1, 100)), anti=True)
        pods.append(w.obj())

    for p in pods:
        s.add_pod(p)
    out = {o.pod.name: o for o in s.schedule_all_pending()}

    pods_on: dict[str, list] = {n.name: [] for n in nodes}
    for p in pods:
        o = out[p.name]
        feas = ipa_filter(p, nodes, pods_on)
        n_feas = sum(feas.values())
        assert o.feasible_nodes == n_feas, (p.name, o.feasible_nodes, n_feas)
        if o.node_name is None:
            assert n_feas == 0, (p.name, feas)
            continue
        assert feas[o.node_name], (p.name, o.node_name)
        scores = ipa_score(p, nodes, pods_on, feas)
        best = max(sc for name, sc in scores.items() if feas[name])
        assert scores[o.node_name] == best, (p.name, o.node_name, scores)
        pods_on[o.node_name].append(p)


def test_mirror_consistency_with_affinity():
    s = TPUScheduler(profile=ipa_profile(True), batch_size=16)
    zones(s, per_zone=1)
    for i in range(9):
        w = make_pod(f"p{i}").req({"cpu": "100m"}).label("app", "web")
        if i % 3 == 0:
            w = w.pod_anti_affinity_in("app", ["web"], ZONE)
        s.add_pod(w.obj())
    s.schedule_all_pending()
    assert s.builder.host_mirror_equal()
