"""Test env: force CPU with 8 virtual devices so multi-chip sharding tests run
without TPU hardware (the driver validates the real multi-chip path via
__graft_entry__.dryrun_multichip). Must run before jax is imported."""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

# The image pre-loads an 'axon' TPU platform plugin that overrides
# JAX_PLATFORMS from the environment; pin the config explicitly so tests run
# on the 8 virtual CPU devices, not through the TPU tunnel.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Flight-recorder auto-dumps (engine faults, quarantines, breaker trips,
# recoveries — paths the fault/journal suites exercise on purpose) land
# in a per-session scratch dir instead of shedding files into /tmp.
import atexit  # noqa: E402
import tempfile  # noqa: E402

if "TPU_FLIGHT_DIR" not in os.environ:
    _flight_dir = tempfile.TemporaryDirectory(prefix="tpu-flight-tests-")
    os.environ["TPU_FLIGHT_DIR"] = _flight_dir.name
    atexit.register(_flight_dir.cleanup)


def pytest_configure(config):
    # Markers used by the tier-1 selection (`-m 'not slow'`) and the
    # fault-injection matrix (scripts/run_fault_matrix.py runs the full
    # grid; the fast subset in tests/test_faults.py stays in tier-1).
    config.addinivalue_line(
        "markers", "slow: long-running; excluded from tier-1 (-m 'not slow')"
    )
    config.addinivalue_line(
        "markers", "faults: fault-injection matrix tests"
    )
