"""Test env: force CPU with 8 virtual devices so multi-chip sharding tests run
without TPU hardware (the driver validates the real multi-chip path via
__graft_entry__.dryrun_multichip). Must run before jax is imported."""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

# The image pre-loads an 'axon' TPU platform plugin that overrides
# JAX_PLATFORMS from the environment; pin the config explicitly so tests run
# on the 8 virtual CPU devices, not through the TPU tunnel.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    # Markers used by the tier-1 selection (`-m 'not slow'`) and the
    # fault-injection matrix (scripts/run_fault_matrix.py runs the full
    # grid; the fast subset in tests/test_faults.py stays in tier-1).
    config.addinivalue_line(
        "markers", "slow: long-running; excluded from tier-1 (-m 'not slow')"
    )
    config.addinivalue_line(
        "markers", "faults: fault-injection matrix tests"
    )
