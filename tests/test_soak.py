"""Tier-1 soak smoke (loadgen/): a seconds-scale seeded soak runs end
to end in-process, populates the SLO-percentile and miss-rate-knee
fields, and is deterministic — the same seed reproduces the arrival
schedule exactly and lands bit-identical final bindings.  The
committed SOAK_rNN.json artifacts come from scripts/run_soak.py's
minutes-scale two-process run; this is the always-on guard that the
harness itself stays correct and replayable."""

import json

import pytest

from kubernetes_tpu.loadgen.arrivals import (
    coalesce,
    diurnal_offsets,
    poisson_offsets,
)
from kubernetes_tpu.loadgen.scenarios import build_events
from kubernetes_tpu.loadgen.soak import SoakConfig, run_soak, strip_private
from kubernetes_tpu.loadgen.workloads import WorkloadMix


def smoke_config(seed: int = 3) -> SoakConfig:
    return SoakConfig(
        seed=seed,
        nodes=16,
        zones=4,
        churn_nodes=2,
        rate_pods_per_s=100.0,
        duration_s=2.0,
        knee_points=(2.0, 20.0),
        knee_phase_s=1.0,
        invalidation_rate_per_s=0.5,
        node_flap_period_s=1.0,
        flap_down_s=0.3,
        cold_consumer_period_s=1.5,
        live_pod_cap=60,
        batch_size=32,
        chunk_size=8,
        warm_pods=32,
        two_process=False,
        pace="virtual",  # no sleeping: the smoke is seconds-scale
        snapshot_every=4,
        journal_fsync="never",  # container fsync is ~10ms; smoke stays fast
    )


# -- the generators alone ---------------------------------------------------


def test_poisson_schedule_is_seeded_and_sorted():
    a = poisson_offsets(50.0, 10.0, seed=7)
    b = poisson_offsets(50.0, 10.0, seed=7)
    c = poisson_offsets(50.0, 10.0, seed=8)
    assert a == b
    assert a != c
    assert a == sorted(a)
    assert all(0.0 <= t < 10.0 for t in a)
    # Rate sanity: ~500 expected, Poisson sd ~22.
    assert 350 < len(a) < 650


def test_diurnal_schedule_modulates_rate():
    offs = diurnal_offsets(
        base_rate=10.0, peak_rate=100.0, period_s=10.0, duration_s=10.0,
        seed=5,
    )
    assert offs == diurnal_offsets(10.0, 100.0, 10.0, 10.0, seed=5)
    # The crest (middle of the period) must carry several times the
    # trough's arrivals.
    trough = sum(1 for t in offs if t < 2.0 or t >= 8.0)
    crest = sum(1 for t in offs if 3.0 <= t < 7.0)
    assert crest > 2 * max(1, trough)


def test_coalesce_windows_preserve_indices():
    offs = [0.05, 0.1, 0.3, 0.31, 0.9]
    windows = coalesce(offs, 0.25)
    assert [idxs for _t, idxs in windows] == [[0, 1], [2, 3], [4]]
    assert [t for t, _ in windows] == [0.0, 0.25, 0.75]


def test_scenario_script_is_seeded():
    kw = dict(
        nodes=8, churn_nodes=2, invalidation_rate_per_s=5.0,
        node_flap_period_s=1.0, cold_consumer_period_s=2.0,
    )
    a = build_events(5.0, seed=11, **kw)
    assert a == build_events(5.0, seed=11, **kw)
    assert a != build_events(5.0, seed=12, **kw)
    kinds = {e.kind for e in a}
    assert "flap_down" in kinds and "flap_up" in kinds
    assert "cold_consumer" in kinds
    assert kinds & {"inv_capacity", "inv_label", "inv_ns"}
    assert [e.t for e in a] == sorted(e.t for e in a)


def test_autoscale_ticks_ride_the_scenario_clock():
    """ISSUE 11: the elastic control loop's cadence is scripted like
    every other scenario event — interval-regular, merged in time
    order, absent when disarmed."""
    kw = dict(nodes=8, churn_nodes=2, invalidation_rate_per_s=1.0)
    a = build_events(10.0, seed=11, autoscale_interval_s=2.5, **kw)
    ticks = [e for e in a if e.kind == "autoscale_tick"]
    assert [e.t for e in ticks] == [2.5, 5.0, 7.5]
    assert [e.data for e in ticks] == [0, 1, 2]
    assert [e.t for e in a] == sorted(e.t for e in a)
    off = build_events(10.0, seed=11, **kw)
    assert not [e for e in off if e.kind == "autoscale_tick"]


def test_workload_mix_is_seeded_and_renames():
    a = WorkloadMix("mixed", seed=4)
    b = WorkloadMix("mixed", seed=4)
    pods_a = [a.pod(i) for i in range(40)]
    pods_b = [b.pod(i) for i in range(40)]
    assert [p.uid for p in pods_a] == [p.uid for p in pods_b]
    assert all(p.metadata.name == f"lg-{i}" for i, p in enumerate(pods_a))
    assert a.counts == b.counts
    assert sum(a.counts.values()) == 40
    with pytest.raises(ValueError):
        WorkloadMix("no-such-mix", seed=0)


# -- the harness end to end -------------------------------------------------


@pytest.fixture(scope="module")
def soak_artifacts():
    """Run the smoke soak TWICE with one seed (the determinism
    contract is the expensive half of the assertion set — share the
    runs across tests)."""
    return run_soak(smoke_config()), run_soak(smoke_config())


def test_soak_runs_end_to_end_and_populates_fields(soak_artifacts):
    art, _ = soak_artifacts
    slo = art["slo"]
    assert slo["decisions"] > 100
    assert slo["p50_ms"] >= 0.0
    assert slo["p99_ms"] >= slo["p50_ms"]
    assert slo["p999_ms"] >= slo["p99_ms"]
    assert slo["budget_ms"] == 250.0
    assert art["sustained_pods_per_sec"] > 0
    # Knee fields: one point per configured intensity, each populated.
    knee = art["knee"]
    assert [p["intensity_per_s"] for p in knee["points"]] == [2.0, 20.0]
    for p in knee["points"]:
        assert p["decisions"] > 0
        assert 0.0 <= p["hit_rate"] <= 1.0
        assert p["p99_ms"] >= p["p50_ms"] >= 0.0
    assert knee["miss_cost_ms"] > 0
    # Speculation served from the cache at least once, missed at least
    # once (the knee needs both sides).
    spec = art["speculation"]
    assert spec["hits"] > 0 and spec["misses"] > 0
    assert 0.0 < spec["miss_rate"] < 1.0
    # The sidecar's own stats rode the dump.
    assert spec["sidecar"]["speculated"] > 0
    # Journal growth was observed and stayed bounded (the snapshot
    # cadence truncated at least twice over the stream).
    j = art["journal"]
    assert j["dir_sampled"]
    assert j["compactions_observed"] >= 2
    assert j["stats"]["truncations"] >= 2
    assert j["bounded"]
    # Retirement kept the live set capped.
    assert art["retired_total"] > 0
    assert art["bound_final"] <= smoke_config().live_pod_cap
    # Scenario machinery actually fired.
    assert art["cold_consumers"] >= 1
    flaps = sum(
        p["events"].get("flap_down", 0) for p in art["phases"]
    )
    assert flaps >= 1


def test_soak_same_seed_same_schedule_and_bindings(soak_artifacts):
    a, b = soak_artifacts
    # Identical arrival schedule, offset for offset…
    assert a["_arrival_offsets"] == b["_arrival_offsets"]
    assert (
        a["determinism"]["arrival_sha256"]
        == b["determinism"]["arrival_sha256"]
    )
    # …and bit-identical final bindings.
    assert (
        a["determinism"]["bindings_sha256"]
        == b["determinism"]["bindings_sha256"]
    )
    assert a["bound_final"] == b["bound_final"]
    assert a["determinism"]["arrivals_total"] > 0


def test_soak_artifact_is_json_clean(soak_artifacts):
    art, _ = soak_artifacts
    doc = strip_private(art)
    assert "_arrival_offsets" not in doc
    # The committed-artifact view must round-trip as plain JSON.
    assert json.loads(json.dumps(doc)) == doc


def test_different_seed_changes_schedule(soak_artifacts):
    a, _ = soak_artifacts
    c = run_soak(smoke_config(seed=4))
    assert (
        c["determinism"]["arrival_sha256"]
        != a["determinism"]["arrival_sha256"]
    )


# -- the resumable driver (ISSUE 18): kill/resume bit-identity --------------
#
# The checkpointer's whole claim is that a SIGKILLed soak driver,
# resumed from its last atomic checkpoint, finishes bit-identical to an
# uninterrupted same-seed run — at a checkpoint BOUNDARY kill (the
# checkpoint is the last executed op) and a MID-INTERVAL kill (ops past
# the checkpoint are re-derived by the deterministic prefix replay).
# Subprocesses, real SIGKILL: the in-process path cannot fake dying.

import os
import signal
import subprocess
import sys

RESUME_BASE = dict(
    seed=7,
    nodes=40,
    zones=4,
    churn_nodes=4,
    rate_pods_per_s=30.0,
    duration_s=6.0,
    knee_points=(),
    invalidation_rate_per_s=0.2,
    node_flap_period_s=0.0,
    pace="virtual",
    batch_size=64,
    chunk_size=16,
    warm_pods=32,
    live_pod_cap=400,
    journal_fsync="never",
    scripted_events=((3.0, "owner_kill", 1),),
    checkpoint_every_ops=40,
)

RESUME_CHILD = """
import dataclasses, json, os, sys
os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, sys.argv[2])
from kubernetes_tpu.loadgen.soak import SoakConfig, run_fleet_soak
art = run_fleet_soak(SoakConfig(**json.loads(sys.argv[1])), 2)
print("RESULT:" + json.dumps(
    {"determinism": art["determinism"], "resume": art["resume"]}
))
"""


def _run_resume_child(cfg_dict):
    repo = os.path.join(os.path.dirname(__file__), "..")
    return subprocess.run(
        [sys.executable, "-c", RESUME_CHILD, json.dumps(cfg_dict), repo],
        capture_output=True,
        text=True,
        timeout=600,
    )


def _child_result(proc):
    line = [
        ln for ln in proc.stdout.splitlines() if ln.startswith("RESULT:")
    ][-1]
    return json.loads(line[len("RESULT:"):])


DET_KEYS = (
    "arrival_sha256",
    "bindings_sha256",
    "timeline_sha256",
    "driver_state_sha256",
    "arrivals_total",
)


@pytest.fixture(scope="module")
def resume_twin(tmp_path_factory):
    """The uninterrupted same-seed twin every kill/resume leg is
    compared against (one subprocess, shared across the legs)."""
    tmp = tmp_path_factory.mktemp("resume-twin")
    cfg = dict(
        RESUME_BASE,
        out_dir=str(tmp / "out"),
        journal_dir=str(tmp / "journal"),
        checkpoint_path=str(tmp / "soak.ckpt"),
    )
    proc = _run_resume_child(cfg)
    assert proc.returncode == 0, proc.stderr[-3000:]
    return _child_result(proc)


@pytest.mark.parametrize(
    "kill_after_op",
    [
        pytest.param(40, id="checkpoint-boundary"),
        pytest.param(57, id="mid-interval"),
    ],
)
def test_soak_driver_killed_and_resumed_is_bit_identical(
    resume_twin, tmp_path, kill_after_op
):
    cfg = dict(
        RESUME_BASE,
        out_dir=str(tmp_path / "out"),
        journal_dir=str(tmp_path / "journal"),
        checkpoint_path=str(tmp_path / "soak.ckpt"),
    )
    killed = _run_resume_child(dict(cfg, kill_after_op=kill_after_op))
    # The driver really died mid-run, and an atomic checkpoint survived.
    assert killed.returncode == -signal.SIGKILL, (
        killed.returncode,
        killed.stderr[-2000:],
    )
    assert os.path.exists(cfg["checkpoint_path"])
    resumed = _run_resume_child(dict(cfg, resume=True))
    assert resumed.returncode == 0, resumed.stderr[-4000:]
    doc = _child_result(resumed)
    rs = doc["resume"]
    assert rs["resumed"] and rs["digest_verified"], rs
    # Resumed strictly from the checkpoint, not from scratch — and for
    # the mid-interval kill, from BEFORE the kill point (ops 41..57 are
    # re-derived by the deterministic prefix replay).
    assert rs["resume_op_index"] == 40
    for key in DET_KEYS:
        assert doc["determinism"][key] == resume_twin["determinism"][key], key
