"""Canonical JSON (de)serialization for the API object model.

The sidecar wire protocol ships cluster objects as JSON — the same choice
the reference's extender protocol makes for v1.Pod (extender/v1/types.go
ExtenderArgs) — so any host scheduler (Go, C++, Python) can produce them
without sharing our dataclasses.  Encoding is a direct field mapping:
dataclass → object, tuple → array, INT_SENTINEL-free primitives as-is."""

from __future__ import annotations

import dataclasses
import json
import threading
import typing
from typing import Any, get_args, get_origin, get_type_hints

from . import types as t

_HINTS_CACHE: dict[type, dict[str, Any]] = {}


def _codegen():
    # Deferred: codegen imports back into this module's _build as the
    # missing-key fallback.  Double-checked init — server threads and the
    # in-process client race the first call.
    global _GEN
    g = _GEN
    if g is None:
        with _GEN_LOCK:
            if _GEN is None:
                from . import codegen

                _GEN = codegen._Gen(_build)
            g = _GEN
    return g


_GEN = None
_GEN_LOCK = threading.Lock()


def to_dict(obj: Any) -> Any:
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return {
            f.name: to_dict(getattr(obj, f.name))
            for f in dataclasses.fields(obj)
        }
    if isinstance(obj, (list, tuple)):
        return [to_dict(x) for x in obj]
    if isinstance(obj, dict):
        return {k: to_dict(v) for k, v in obj.items()}
    return obj


def to_json(obj: Any) -> bytes:
    """Canonical JSON bytes.  Dataclasses go through the generated
    per-type dumper (codegen.py — byte-identical to the reflective
    to_dict path, ~8× faster); anything else through to_dict."""
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        data = _codegen().dumper(type(obj))(obj)
    else:
        data = to_dict(obj)
    return json.dumps(data, sort_keys=True).encode()


def build(tp: type, data: Any):
    """Fast reconstruction via the generated per-type builder."""
    return _codegen().builder(tp)(data)


def _build(tp: Any, data: Any) -> Any:
    """Reconstruct a value of type ``tp`` from plain JSON data."""
    if data is None:
        return None
    origin = get_origin(tp)
    if origin is typing.Union:  # Optional[X] and unions
        for arg in get_args(tp):
            if arg is type(None):
                continue
            return _build(arg, data)
        return None
    if origin in (tuple,):
        args = get_args(tp)
        if len(args) == 2 and args[1] is Ellipsis:
            return tuple(_build(args[0], x) for x in data)
        return tuple(_build(a, x) for a, x in zip(args, data))
    if origin in (list,):
        (elem,) = get_args(tp) or (Any,)
        return [_build(elem, x) for x in data]
    if origin in (dict,):
        kt, vt = get_args(tp) or (Any, Any)
        return {k: _build(vt, v) for k, v in data.items()}
    if isinstance(tp, type) and dataclasses.is_dataclass(tp):
        hints = _HINTS_CACHE.get(tp)
        if hints is None:
            hints = get_type_hints(tp)
            _HINTS_CACHE[tp] = hints
        kwargs = {
            f.name: _build(hints[f.name], data[f.name])
            for f in dataclasses.fields(tp)
            if f.name in data
        }
        return tp(**kwargs)
    return data


def pod_from_json(raw: bytes | str) -> t.Pod:
    return pod_from_data(json.loads(raw))


def featsig_from_data(namespace, labels, spec_data) -> tuple:
    """THE featurization-cache key constructor — the single source for
    both entry paths (wire pods here via pod_from_data; in-process pods
    via engine/features.pod_sig), so identical templates always share
    cache entries: the key is (namespace, sort-keys labels JSON or "",
    sort-keys spec JSON) over the canonical data model, and the two
    paths produce string-identical dumps because the canonical dumper
    emits exactly the parsed wire shape."""
    return (
        namespace or "default",
        json.dumps(labels, sort_keys=True) if labels else "",
        json.dumps(spec_data, sort_keys=True),
    )


def pod_from_data(data: dict) -> t.Pod:
    """Pod from parsed JSON data, pre-stamping the featurization
    signature (engine/features.py `_featsig`) for unassigned, un-pinned
    pods: identical template-stamped pods share identical canonical spec
    JSON, so the sort-keys dump of the parsed subtrees IS the cache key —
    computed here at C speed."""
    pod = build(t.Pod, data)
    spec = data.get("spec")
    if spec is not None and not spec.get("node_name"):
        from ..engine.features import pin_name

        if pin_name(pod) is None:
            meta = data.get("metadata") or {}
            pod._featsig = featsig_from_data(
                meta.get("namespace"), meta.get("labels"), spec
            )
    return pod


def node_from_json(raw: bytes | str) -> t.Node:
    return build(t.Node, json.loads(raw))


# Kind name → (type, scheduler add-method name) for the sidecar's AddObject.
KINDS: dict[str, tuple[type, str]] = {
    # update_node diffs against the cached record for precise requeue
    # events and falls back to add for unknown nodes — upserts over the
    # wire must not fire NODE_ADD per heartbeat.
    "Node": (t.Node, "update_node"),
    # update_pod diffs against the cached/queued record (no-op for
    # status-only re-deliveries) and falls back to add for unknown pods —
    # re-running add_pod per watch upsert would double-apply a bound pod's
    # resource delta and gang quorum credit (ADVICE r2).
    "Pod": (t.Pod, "update_pod"),
    "PersistentVolume": (t.PersistentVolume, "add_pv"),
    "PersistentVolumeClaim": (t.PersistentVolumeClaim, "add_pvc"),
    "StorageClass": (t.StorageClass, "add_storage_class"),
    "CSINode": (t.CSINode, "add_csinode"),
    "PodGroup": (t.PodGroup, "add_pod_group"),
    "PodDisruptionBudget": (t.PodDisruptionBudget, "add_pdb"),
    "ResourceClaim": (t.ResourceClaim, "add_resource_claim"),
    "ResourceSlice": (t.ResourceSlice, "add_resource_slice"),
    # Node-heartbeat lease (coordination.k8s.io): renewals feed the
    # node-lifecycle controller's staleness clock (controllers.py).
    "Lease": (t.Lease, "renew_node_lease"),
}

# Kind name → scheduler remove-method for the kinds that support watch
# DELETED events (the Reflector's full object surface and the sidecar's
# remove frame).  Pod/Node keep their historical direct routes
# (delete_pod / remove_node); the method takes the object's uid/name.
REMOVERS: dict[str, str] = {
    "Node": "remove_node",
    "Pod": "delete_pod",
    "PersistentVolume": "remove_pv",
    "PersistentVolumeClaim": "remove_pvc",
    "StorageClass": "remove_storage_class",
    "CSINode": "remove_csinode",
    "PodDisruptionBudget": "remove_pdb",
    "ResourceClaim": "remove_resource_claim",
    "ResourceSlice": "remove_resource_slice",
}


def from_json(kind: str, raw: bytes | str):
    if kind == "Pod":
        return pod_from_data(json.loads(raw))
    tp, _ = KINDS[kind]
    return build(tp, json.loads(raw))
