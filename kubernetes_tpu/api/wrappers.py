"""Fluent test/workload builders, in the spirit of the reference's
pkg/scheduler/testing/wrappers.go (MakeNode / MakePod chains)."""

from __future__ import annotations

from typing import Optional

from . import types as t


class PodWrapper:
    def __init__(self, name: str = "pod", namespace: str = "default"):
        self._pod = t.Pod(metadata=t.ObjectMeta(name=name, namespace=namespace))
        self._pod.spec.containers.append(t.Container(name="c0"))

    # -- metadata ----------------------------------------------------------
    def uid(self, uid: str) -> "PodWrapper":
        self._pod.metadata.uid = uid
        return self

    def label(self, k: str, v: str) -> "PodWrapper":
        self._pod.metadata.labels[k] = v
        return self

    def labels(self, d: dict[str, str]) -> "PodWrapper":
        self._pod.metadata.labels.update(d)
        return self

    # -- resources ---------------------------------------------------------
    def req(self, resources: dict[str, str | int]) -> "PodWrapper":
        """Add requests to the first container (canonicalizes quantities)."""
        self._pod.spec.containers[0].requests.update(
            {k: t.parse_quantity(v, k) for k, v in resources.items()}
        )
        return self

    def init_req(
        self, resources: dict[str, str | int], restart_policy: Optional[str] = None
    ) -> "PodWrapper":
        self._pod.spec.init_containers.append(
            t.Container(
                name=f"init{len(self._pod.spec.init_containers)}",
                requests={k: t.parse_quantity(v, k) for k, v in resources.items()},
                restart_policy=restart_policy,
            )
        )
        return self

    def overhead(self, resources: dict[str, str | int]) -> "PodWrapper":
        self._pod.spec.overhead.update(
            {k: t.parse_quantity(v, k) for k, v in resources.items()}
        )
        return self

    # -- placement ---------------------------------------------------------
    def node(self, name: str) -> "PodWrapper":
        self._pod.spec.node_name = name
        return self

    def priority(self, p: int) -> "PodWrapper":
        self._pod.spec.priority = p
        return self

    def start_time(self, ts: float) -> "PodWrapper":
        self._pod.status.start_time = ts
        return self

    def preemption_policy(self, policy: str) -> "PodWrapper":
        self._pod.spec.preemption_policy = policy
        return self

    def node_selector(self, d: dict[str, str]) -> "PodWrapper":
        self._pod.spec.node_selector.update(d)
        return self

    def toleration(
        self, key: str = "", op: str = t.TOLERATION_OP_EQUAL, value: str = "",
        effect: str = "", seconds: float | None = None,
    ) -> "PodWrapper":
        self._pod.spec.tolerations += (
            t.Toleration(key, op, value, effect, toleration_seconds=seconds),
        )
        return self

    def host_port(self, port: int, protocol: str = "TCP", host_ip: str = "") -> "PodWrapper":
        c = self._pod.spec.containers[0]
        c.ports += (t.ContainerPort(host_port=port, protocol=protocol, host_ip=host_ip),)
        return self

    def container_image(self, *names: str) -> "PodWrapper":
        self._pod.spec.containers[0].images += names
        return self

    def pvc_volume(self, pvc_name: str) -> "PodWrapper":
        self._pod.spec.volumes += (t.Volume(name=f"v{len(self._pod.spec.volumes)}", pvc=pvc_name),)
        return self

    def device_volume(self, device_id: str, read_only: bool = False) -> "PodWrapper":
        self._pod.spec.volumes += (
            t.Volume(name=f"v{len(self._pod.spec.volumes)}", device_id=device_id, read_only=read_only),
        )
        return self

    def pod_group(self, name: str) -> "PodWrapper":
        self._pod.spec.pod_group = name
        return self

    def scheduling_gate(self, name: str) -> "PodWrapper":
        self._pod.spec.scheduling_gates += (t.PodSchedulingGate(name),)
        return self

    def scheduler(self, name: str) -> "PodWrapper":
        """Profile selection (pod.spec.schedulerName)."""
        self._pod.spec.scheduler_name = name
        return self

    def resource_claim(self, name: str) -> "PodWrapper":
        """Reference a ResourceClaim (spec.resourceClaims, DRA)."""
        self._pod.spec.resource_claims += (name,)
        return self

    # -- affinity ----------------------------------------------------------
    def _affinity(self) -> t.Affinity:
        if self._pod.spec.affinity is None:
            self._pod.spec.affinity = t.Affinity()
        return self._pod.spec.affinity

    def node_affinity_in(self, key: str, values: list[str]) -> "PodWrapper":
        term = t.NodeSelectorTerm(
            match_expressions=(t.NodeSelectorRequirement(key, t.OP_IN, tuple(values)),)
        )
        a = self._affinity()
        na = a.node_affinity or t.NodeAffinity()
        req = na.required or t.NodeSelector()
        na = t.NodeAffinity(
            required=t.NodeSelector(req.terms + (term,)), preferred=na.preferred
        )
        self._pod.spec.affinity = t.Affinity(na, a.pod_affinity, a.pod_anti_affinity)
        return self

    def preferred_node_affinity_in(
        self, key: str, values: list[str], weight: int = 1
    ) -> "PodWrapper":
        term = t.NodeSelectorTerm(
            match_expressions=(t.NodeSelectorRequirement(key, t.OP_IN, tuple(values)),)
        )
        a = self._affinity()
        na = a.node_affinity or t.NodeAffinity()
        na = t.NodeAffinity(
            required=na.required,
            preferred=na.preferred + (t.PreferredSchedulingTerm(weight, term),),
        )
        self._pod.spec.affinity = t.Affinity(na, a.pod_affinity, a.pod_anti_affinity)
        return self

    def _pod_term(self, label_key: str, label_values: list[str], topo: str) -> t.PodAffinityTerm:
        return t.PodAffinityTerm(
            label_selector=t.LabelSelector(
                match_expressions=(
                    t.LabelSelectorRequirement(label_key, t.OP_IN, tuple(label_values)),
                )
            ),
            topology_key=topo,
        )

    def _attach_pod_term(
        self, term: t.PodAffinityTerm, anti: bool, weight: int | None
    ) -> "PodWrapper":
        """Attach a (weighted) pod (anti-)affinity term — the single place
        that rebuilds the immutable Affinity tuple tree."""
        a = self._affinity()
        if anti:
            pa = a.pod_anti_affinity or t.PodAntiAffinity()
            if weight is None:
                pa = t.PodAntiAffinity(pa.required + (term,), pa.preferred)
            else:
                pa = t.PodAntiAffinity(
                    pa.required,
                    pa.preferred + (t.WeightedPodAffinityTerm(weight, term),),
                )
            self._pod.spec.affinity = t.Affinity(a.node_affinity, a.pod_affinity, pa)
        else:
            pa = a.pod_affinity or t.PodAffinity()
            if weight is None:
                pa = t.PodAffinity(pa.required + (term,), pa.preferred)
            else:
                pa = t.PodAffinity(
                    pa.required,
                    pa.preferred + (t.WeightedPodAffinityTerm(weight, term),),
                )
            self._pod.spec.affinity = t.Affinity(a.node_affinity, pa, a.pod_anti_affinity)
        return self

    def pod_affinity_in(self, key: str, values: list[str], topo: str) -> "PodWrapper":
        return self._attach_pod_term(self._pod_term(key, values, topo), False, None)

    def pod_anti_affinity_in(self, key: str, values: list[str], topo: str) -> "PodWrapper":
        return self._attach_pod_term(self._pod_term(key, values, topo), True, None)

    def preferred_pod_affinity_in(
        self, key: str, values: list[str], topo: str, weight: int = 1, anti: bool = False
    ) -> "PodWrapper":
        return self._attach_pod_term(self._pod_term(key, values, topo), anti, weight)

    def ns_selector_pod_affinity_in(
        self,
        key: str,
        values: list[str],
        topo: str,
        ns_key: str,
        ns_values: list[str],
        anti: bool = False,
        preferred_weight: int | None = None,
    ) -> "PodWrapper":
        """(Anti-)affinity term selecting pods across namespaces via a
        namespaceSelector (the NSSelector scheduler_perf cases)."""
        term = t.PodAffinityTerm(
            label_selector=t.LabelSelector(
                match_expressions=(
                    t.LabelSelectorRequirement(key, t.OP_IN, tuple(values)),
                )
            ),
            topology_key=topo,
            namespace_selector=t.LabelSelector(
                match_expressions=(
                    t.LabelSelectorRequirement(ns_key, t.OP_IN, tuple(ns_values)),
                )
            ),
        )
        return self._attach_pod_term(term, anti, preferred_weight)

    def node_name_affinity(self, node_name: str) -> "PodWrapper":
        """DaemonSet-style pinning: required node affinity on the
        metadata.name matchField (what the DaemonSet controller emits)."""
        term = t.NodeSelectorTerm(
            match_fields=(
                t.NodeSelectorRequirement(
                    "metadata.name", t.OP_IN, (node_name,)
                ),
            )
        )
        a = self._affinity()
        na = a.node_affinity or t.NodeAffinity()
        req = na.required or t.NodeSelector()
        na = t.NodeAffinity(
            required=t.NodeSelector(req.terms + (term,)), preferred=na.preferred
        )
        self._pod.spec.affinity = t.Affinity(na, a.pod_affinity, a.pod_anti_affinity)
        return self

    def spread_constraint(
        self,
        max_skew: int,
        topo: str,
        when_unsatisfiable: str,
        label_key: str,
        label_values: list[str],
        min_domains: Optional[int] = None,
        node_affinity_policy: str = t.POLICY_HONOR,
        node_taints_policy: str = t.POLICY_IGNORE,
        match_label_keys: tuple[str, ...] = (),
    ) -> "PodWrapper":
        self._pod.spec.topology_spread_constraints += (
            t.TopologySpreadConstraint(
                max_skew=max_skew,
                topology_key=topo,
                when_unsatisfiable=when_unsatisfiable,
                label_selector=t.LabelSelector(
                    match_expressions=(
                        t.LabelSelectorRequirement(label_key, t.OP_IN, tuple(label_values)),
                    )
                ),
                min_domains=min_domains,
                node_affinity_policy=node_affinity_policy,
                node_taints_policy=node_taints_policy,
                match_label_keys=tuple(match_label_keys),
            ),
        )
        return self

    def obj(self) -> t.Pod:
        return self._pod


class NodeWrapper:
    def __init__(self, name: str = "node"):
        self._node = t.Node(metadata=t.ObjectMeta(name=name, namespace=""))
        self._node.metadata.labels["kubernetes.io/hostname"] = name

    def label(self, k: str, v: str) -> "NodeWrapper":
        self._node.metadata.labels[k] = v
        return self

    def capacity(self, resources: dict[str, str | int]) -> "NodeWrapper":
        """Set capacity AND allocatable (like MakeNode().Capacity())."""
        parsed = {k: t.parse_quantity(v, k) for k, v in resources.items()}
        self._node.status.capacity.update(parsed)
        self._node.status.allocatable.update(parsed)
        return self

    def allocatable(self, resources: dict[str, str | int]) -> "NodeWrapper":
        self._node.status.allocatable.update(
            {k: t.parse_quantity(v, k) for k, v in resources.items()}
        )
        return self

    def taint(self, key: str, value: str = "", effect: str = t.EFFECT_NO_SCHEDULE) -> "NodeWrapper":
        self._node.spec.taints += (t.Taint(key, value, effect),)
        return self

    def unschedulable(self, v: bool = True) -> "NodeWrapper":
        self._node.spec.unschedulable = v
        return self

    def image(self, name: str, size_bytes: int) -> "NodeWrapper":
        self._node.status.images += (t.ContainerImage(names=(name,), size_bytes=size_bytes),)
        return self

    def zone(self, z: str) -> "NodeWrapper":
        self._node.metadata.labels["topology.kubernetes.io/zone"] = z
        return self

    def region(self, r: str) -> "NodeWrapper":
        self._node.metadata.labels["topology.kubernetes.io/region"] = r
        return self

    def obj(self) -> t.Node:
        return self._node


def make_pod(name: str = "pod", namespace: str = "default") -> PodWrapper:
    return PodWrapper(name, namespace)


def make_node(name: str = "node") -> NodeWrapper:
    return NodeWrapper(name)


def make_pv(
    name: str,
    capacity: str | int = "10Gi",
    storage_class: str = "",
    zone: str | None = None,
    node_affinity_zone: list[str] | None = None,
    access_modes: tuple[str, ...] = (t.RWO,),
    csi_driver: str = "",
) -> t.PersistentVolume:
    labels = {}
    if zone is not None:
        labels["topology.kubernetes.io/zone"] = zone
    na = None
    if node_affinity_zone is not None:
        na = t.NodeSelector(
            terms=(
                t.NodeSelectorTerm(
                    match_expressions=(
                        t.NodeSelectorRequirement(
                            "topology.kubernetes.io/zone", t.OP_IN, tuple(node_affinity_zone)
                        ),
                    )
                ),
            )
        )
    return t.PersistentVolume(
        name=name,
        capacity=t.parse_quantity(capacity),
        storage_class=storage_class,
        labels=labels,
        node_affinity=na,
        access_modes=access_modes,
        csi_driver=csi_driver,
    )


def make_pvc(
    name: str,
    namespace: str = "default",
    storage_class: str = "",
    request: str | int = "1Gi",
    volume_name: str = "",
    access_modes: tuple[str, ...] = (t.RWO,),
) -> t.PersistentVolumeClaim:
    return t.PersistentVolumeClaim(
        name=name,
        namespace=namespace,
        storage_class=storage_class,
        request=t.parse_quantity(request),
        volume_name=volume_name,
        access_modes=access_modes,
    )
