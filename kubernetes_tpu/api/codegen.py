"""Generated per-dataclass JSON (de)serializers.

`serialize._build` / `serialize.to_dict` walk type hints reflectively on
every call — ~114µs to rebuild a Pod, ~80µs to serialize one.  At the
sidecar's wire rates (10k+ pods per measured window, one JSON object per
informer event) that reflection is the single largest host-side cost of
the integrated path.  This module generates a specialized builder/dumper
function per dataclass once (the same trade the reference makes by
generating ugorji/json codecs for its API types instead of reflecting:
k8s.io/apimachinery generated.pb.go + deepcopy-gen), then runs at plain
attribute/dict speed (~8µs/pod).

Semantics are identical to the reflective versions and pinned by
tests/test_types.py round-trips plus the golden object fixtures:
  - builders: missing keys fall back to dataclass defaults (any KeyError
    routes the whole object through the generic `fallback` builder);
    None stays None for Optional fields.
  - dumpers: every field is emitted (the canonical form — no omitempty),
    tuples/dicts of primitives pass through uncopied (json.dumps treats
    tuples as arrays; nothing mutates the result before encoding).
"""

from __future__ import annotations

import dataclasses
import threading
import typing
from typing import Any, get_args, get_origin, get_type_hints

_PRIMITIVES = (str, int, float, bool)


def _is_passthrough(tp: Any) -> bool:
    """Types whose JSON form needs no per-element work in either
    direction (primitives and Any)."""
    return tp in _PRIMITIVES or tp is Any or tp is object


class _Gen:
    """One code generator; `builder(cls)` / `dumper(cls)` memoize
    per-dataclass functions compiled into a shared namespace."""

    def __init__(self, fallback):
        # fallback(cls, data) — the reflective builder, used when a fast
        # builder sees a missing key (hand-written JSON omitting fields).
        self.ns: dict[str, Any] = {"_fallback": fallback, "_tuple": tuple}
        self.builders: dict[type, Any] = {}
        self.dumpers: dict[type, Any] = {}
        # Generation is guarded: the sidecar server threads share this
        # generator with the client side of in-process tests, and the
        # None cycle-guard placeholder must never leak to a second
        # thread as "the compiled function".
        self._lock = threading.Lock()

    # -- building (JSON data -> dataclass) --------------------------------

    def _bexpr(self, tp: Any, src: str, depth: int) -> str:
        origin = get_origin(tp)
        if origin is typing.Union:
            args = [a for a in get_args(tp) if a is not type(None)]
            # Mirrors serialize._build: the first non-None arm wins.
            inner = self._bexpr(args[0], src, depth)
            if inner == src:
                return src
            return f"(None if {src} is None else {inner})"
        if origin is tuple:
            args = get_args(tp)
            if len(args) == 2 and args[1] is Ellipsis:
                var = f"x{depth}"
                inner = self._bexpr(args[0], var, depth + 1)
                if inner == var:
                    return f"_tuple({src})"
                return f"_tuple({inner} for {var} in {src})"
            # Fixed-arity tuples in the object model are primitive pairs
            # (LabelSelector.match_labels) — elementwise work never needed.
            if all(_is_passthrough(a) for a in args):
                return f"_tuple({src})"
            raise NotImplementedError(f"fixed tuple of non-primitives: {tp}")
        if origin is list:
            (elem,) = get_args(tp) or (Any,)
            var = f"x{depth}"
            inner = self._bexpr(elem, var, depth + 1)
            if inner == var:
                return f"list({src})"
            return f"[{inner} for {var} in {src}]"
        if origin is dict:
            args = get_args(tp)
            if not args:
                return f"dict({src})"
            _, vt = args
            var = f"v{depth}"
            inner = self._bexpr(vt, var, depth + 1)
            if inner == var:
                return f"dict({src})"
            return f"{{k{depth}: {inner} for k{depth}, {var} in {src}.items()}}"
        if isinstance(tp, type) and dataclasses.is_dataclass(tp):
            return f"{self._builder_name(tp)}({src})"
        return src  # primitive / Any / opaque

    def _builder_name(self, cls: type) -> str:
        name = f"_b_{cls.__name__}"
        if cls not in self.builders:
            self.builders[cls] = None  # cycle guard; body fills it below
            self._gen_builder(cls, name)
        return name

    def _gen_builder(self, cls: type, name: str) -> None:
        hints = get_type_hints(cls)
        cls_ref = f"_c_{cls.__name__}"
        self.ns[cls_ref] = cls
        lines = [f"def {name}(d):", "    try:", f"        return {cls_ref}("]
        for f in dataclasses.fields(cls):
            expr = self._bexpr(hints[f.name], f"d[{f.name!r}]", 0)
            lines.append(f"            {f.name}={expr},")
        lines += [
            "        )",
            "    except KeyError:",
            # A producer omitted a field (hand-written JSON): take the
            # reflective path, which applies dataclass defaults per key.
            f"        return _fallback({cls_ref}, d)",
        ]
        exec("\n".join(lines), self.ns)  # noqa: S102 — our own generated code
        self.builders[cls] = self.ns[name]

    def builder(self, cls: type):
        fn = self.builders.get(cls)
        if fn is None:
            with self._lock:
                if self.builders.get(cls) is None:
                    self.builders.pop(cls, None)
                    self._builder_name(cls)
                fn = self.builders[cls]
        return fn

    # -- dumping (dataclass -> JSON-able data) -----------------------------

    def _dexpr(self, tp: Any, src: str, depth: int) -> str:
        origin = get_origin(tp)
        if origin is typing.Union:
            args = [a for a in get_args(tp) if a is not type(None)]
            inner = self._dexpr(args[0], src, depth)
            if inner == src:
                return src
            return f"(None if {src} is None else {inner})"
        if origin is tuple:
            args = get_args(tp)
            if len(args) == 2 and args[1] is Ellipsis:
                var = f"x{depth}"
                inner = self._dexpr(args[0], var, depth + 1)
                if inner == var:
                    return src  # tuple of primitives: dumps emits arrays
                return f"[{inner} for {var} in {src}]"
            if all(_is_passthrough(a) for a in args):
                return src
            raise NotImplementedError(f"fixed tuple of non-primitives: {tp}")
        if origin is list:
            (elem,) = get_args(tp) or (Any,)
            var = f"x{depth}"
            inner = self._dexpr(elem, var, depth + 1)
            if inner == var:
                return src
            return f"[{inner} for {var} in {src}]"
        if origin is dict:
            args = get_args(tp)
            if not args:
                return src
            _, vt = args
            var = f"v{depth}"
            inner = self._dexpr(vt, var, depth + 1)
            if inner == var:
                return src
            return f"{{k{depth}: {inner} for k{depth}, {var} in {src}.items()}}"
        if isinstance(tp, type) and dataclasses.is_dataclass(tp):
            return f"{self._dumper_name(tp)}({src})"
        return src

    def _dumper_name(self, cls: type) -> str:
        name = f"_d_{cls.__name__}"
        if cls not in self.dumpers:
            self.dumpers[cls] = None
            self._gen_dumper(cls, name)
        return name

    def _gen_dumper(self, cls: type, name: str) -> None:
        hints = get_type_hints(cls)
        lines = [f"def {name}(o):", "    return {"]
        for f in dataclasses.fields(cls):
            expr = self._dexpr(hints[f.name], f"o.{f.name}", 0)
            lines.append(f"        {f.name!r}: {expr},")
        lines += ["    }"]
        exec("\n".join(lines), self.ns)  # noqa: S102
        self.dumpers[cls] = self.ns[name]

    def dumper(self, cls: type):
        fn = self.dumpers.get(cls)
        if fn is None:
            with self._lock:
                if self.dumpers.get(cls) is None:
                    self.dumpers.pop(cls, None)
                    self._dumper_name(cls)
                fn = self.dumpers[cls]
        return fn
