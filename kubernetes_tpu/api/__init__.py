from . import types, wrappers  # noqa: F401
from .types import Node, Pod  # noqa: F401
from .wrappers import make_node, make_pod  # noqa: F401
