"""Object model: the subset of the Kubernetes API the scheduler consumes.

These are plain Python dataclasses, not a port of the generated Go types
(reference: staging/src/k8s.io/api/core/v1/types.go).  Quantities are
canonicalized at parse time — CPU to integer millicores, everything else to
integer base units (bytes / counts) — matching how the reference's scheduler
consumes them after `resource.Quantity.MilliValue()` / `.Value()`
(pkg/scheduler/framework/types.go:1055 calculateResource).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Optional

# ---------------------------------------------------------------------------
# Resource quantities
# ---------------------------------------------------------------------------

# Canonical resource names (mirrors v1.ResourceCPU etc.).
CPU = "cpu"
MEMORY = "memory"
EPHEMERAL_STORAGE = "ephemeral-storage"
PODS = "pods"

_BINARY_SUFFIX = {
    "Ki": 1024,
    "Mi": 1024**2,
    "Gi": 1024**3,
    "Ti": 1024**4,
    "Pi": 1024**5,
    "Ei": 1024**6,
}
_DECIMAL_SUFFIX = {
    "n": 1e-9,
    "u": 1e-6,
    "m": 1e-3,
    "": 1.0,
    "k": 1e3,
    "M": 1e6,
    "G": 1e9,
    "T": 1e12,
    "P": 1e15,
    "E": 1e18,
}

_QTY_RE = re.compile(r"^([+-]?[0-9.]+(?:[eE][+-]?[0-9]+)?)([A-Za-z]*)$")


def parse_quantity(value: str | int | float, resource: str = "") -> int:
    """Parse a Kubernetes quantity string to canonical integer units.

    CPU → millicores (``100m`` → 100, ``2`` → 2000); all other resources →
    base units, rounding up fractional values the way resource.Quantity does
    for scheduling purposes (``1.5Gi`` → 1610612736 bytes).
    """
    is_cpu = resource == CPU
    # Exact integer paths first: int64 quantities must not round-trip through
    # float (2^53+1 would silently lose precision).
    if isinstance(value, int):
        return value * 1000 if is_cpu else value
    if isinstance(value, float):
        num, suffix = value, ""
    else:
        m = _QTY_RE.match(value.strip())
        if not m:
            raise ValueError(f"cannot parse quantity {value!r}")
        mantissa, suffix = m.group(1), m.group(2)
        try:
            imant = int(mantissa)
        except ValueError:
            imant = None
        if imant is not None:
            # Integer mantissa: keep the arithmetic in exact ints wherever the
            # multiplier is integral.
            if suffix in _BINARY_SUFFIX:
                base_i = imant * _BINARY_SUFFIX[suffix]
                return base_i * 1000 if is_cpu else base_i
            mult = _DECIMAL_SUFFIX.get(suffix)
            if mult is None:
                raise ValueError(f"unknown quantity suffix {suffix!r} in {value!r}")
            if mult >= 1.0:
                base_i = imant * int(mult)
                return base_i * 1000 if is_cpu else base_i
            if is_cpu and suffix == "m":
                return imant  # millicores exactly
            num = float(imant)
        else:
            num = float(mantissa)
    if suffix in _BINARY_SUFFIX:
        base = num * _BINARY_SUFFIX[suffix]
    elif suffix in _DECIMAL_SUFFIX:
        base = num * _DECIMAL_SUFFIX[suffix]
    else:
        raise ValueError(f"unknown quantity suffix {suffix!r} in {value!r}")
    if is_cpu:
        base *= 1000.0
    # Round up: a request of 0.5 byte must still reserve 1.
    scaled = int(base)
    if base > scaled:
        scaled += 1
    return scaled


def parse_resource_list(d: dict[str, str | int | float] | None) -> dict[str, int]:
    """Parse {"cpu": "2", "memory": "4Gi", ...} to canonical integer units."""
    if not d:
        return {}
    return {k: parse_quantity(v, k) for k, v in d.items()}


# Defaults used for NonZeroRequested (reference:
# pkg/scheduler/util/pod_resources.go — DefaultMilliCPURequest / DefaultMemoryRequest).
DEFAULT_MILLI_CPU_REQUEST = 100
DEFAULT_MEMORY_REQUEST = 200 * 1024 * 1024


# ---------------------------------------------------------------------------
# Selectors / affinity
# ---------------------------------------------------------------------------

# NodeSelectorOperator values (staging/src/k8s.io/api/core/v1/types.go).
OP_IN = "In"
OP_NOT_IN = "NotIn"
OP_EXISTS = "Exists"
OP_DOES_NOT_EXIST = "DoesNotExist"
OP_GT = "Gt"
OP_LT = "Lt"


@dataclass(frozen=True)
class NodeSelectorRequirement:
    key: str
    operator: str
    values: tuple[str, ...] = ()


@dataclass(frozen=True)
class NodeSelectorTerm:
    # Requirements are ANDed; terms are ORed.
    match_expressions: tuple[NodeSelectorRequirement, ...] = ()
    match_fields: tuple[NodeSelectorRequirement, ...] = ()


@dataclass(frozen=True)
class NodeSelector:
    terms: tuple[NodeSelectorTerm, ...] = ()


@dataclass(frozen=True)
class PreferredSchedulingTerm:
    weight: int
    preference: NodeSelectorTerm


@dataclass(frozen=True)
class NodeAffinity:
    required: Optional[NodeSelector] = None
    preferred: tuple[PreferredSchedulingTerm, ...] = ()


@dataclass(frozen=True)
class LabelSelectorRequirement:
    key: str
    operator: str  # In | NotIn | Exists | DoesNotExist
    values: tuple[str, ...] = ()


@dataclass(frozen=True)
class LabelSelector:
    """A label selector; None means "match nothing", empty means "match all"."""

    match_labels: tuple[tuple[str, str], ...] = ()
    match_expressions: tuple[LabelSelectorRequirement, ...] = ()


@dataclass(frozen=True)
class PodAffinityTerm:
    label_selector: Optional[LabelSelector]
    topology_key: str
    namespaces: tuple[str, ...] = ()
    namespace_selector: Optional[LabelSelector] = None


@dataclass(frozen=True)
class WeightedPodAffinityTerm:
    weight: int
    term: PodAffinityTerm


@dataclass(frozen=True)
class PodAffinity:
    required: tuple[PodAffinityTerm, ...] = ()
    preferred: tuple[WeightedPodAffinityTerm, ...] = ()


@dataclass(frozen=True)
class PodAntiAffinity:
    required: tuple[PodAffinityTerm, ...] = ()
    preferred: tuple[WeightedPodAffinityTerm, ...] = ()


@dataclass(frozen=True)
class Affinity:
    node_affinity: Optional[NodeAffinity] = None
    pod_affinity: Optional[PodAffinity] = None
    pod_anti_affinity: Optional[PodAntiAffinity] = None


# TopologySpreadConstraint.whenUnsatisfiable
DO_NOT_SCHEDULE = "DoNotSchedule"
SCHEDULE_ANYWAY = "ScheduleAnyway"

# Node inclusion policies.
POLICY_HONOR = "Honor"
POLICY_IGNORE = "Ignore"


@dataclass(frozen=True)
class TopologySpreadConstraint:
    max_skew: int
    topology_key: str
    when_unsatisfiable: str  # DoNotSchedule | ScheduleAnyway
    label_selector: Optional[LabelSelector] = None
    min_domains: Optional[int] = None
    node_affinity_policy: str = POLICY_HONOR
    node_taints_policy: str = POLICY_IGNORE
    # matchLabelKeys (gated by MatchLabelKeysInPodTopologySpread): the
    # pod's values for these keys merge into the effective selector, so
    # spreading counts only pods of the same rollout generation
    # (podtopologyspread/filtering.go mergeLabelSetWithSelector).
    match_label_keys: tuple[str, ...] = ()


def spread_effective_selector(
    c: "TopologySpreadConstraint", pod_labels
) -> Optional[LabelSelector]:
    """The constraint's selector with matchLabelKeys merged in: each listed
    key present on the pod adds an exact-match requirement with the pod's
    value; absent keys are skipped (filtering.go — requirements are built
    from the pod's own label set).  Shared by the engine featurizer and
    the scalar test oracle so both sides compute one semantics."""
    if not c.match_label_keys:
        return c.label_selector
    extra = tuple(
        (k, pod_labels[k]) for k in c.match_label_keys if k in pod_labels
    )
    if not extra:
        return c.label_selector
    base = c.label_selector or LabelSelector()
    return LabelSelector(
        match_labels=base.match_labels + extra,
        match_expressions=base.match_expressions,
    )


# ---------------------------------------------------------------------------
# Taints & tolerations
# ---------------------------------------------------------------------------

EFFECT_NO_SCHEDULE = "NoSchedule"
EFFECT_PREFER_NO_SCHEDULE = "PreferNoSchedule"
EFFECT_NO_EXECUTE = "NoExecute"

TOLERATION_OP_EXISTS = "Exists"
TOLERATION_OP_EQUAL = "Equal"


@dataclass(frozen=True)
class Taint:
    key: str
    value: str = ""
    effect: str = EFFECT_NO_SCHEDULE


@dataclass(frozen=True)
class Toleration:
    key: str = ""
    operator: str = TOLERATION_OP_EQUAL
    value: str = ""
    effect: str = ""  # empty matches all effects
    # NoExecute grace (v1.Toleration.TolerationSeconds): None = tolerate
    # forever; N = the taint-eviction controller evicts after N seconds.
    toleration_seconds: Optional[float] = None

    def tolerates(self, taint: Taint) -> bool:
        """Mirror of v1helper.TolerationsTolerateTaint single-taint check
        (staging/src/k8s.io/api/core/v1/toleration.go ToleratesTaint)."""
        if self.effect and self.effect != taint.effect:
            return False
        if self.key and self.key != taint.key:
            return False
        if self.operator == TOLERATION_OP_EXISTS:
            return True
        return self.value == taint.value


# ---------------------------------------------------------------------------
# Pods
# ---------------------------------------------------------------------------

RESTART_POLICY_ALWAYS = "Always"


@dataclass(frozen=True)
class ContainerPort:
    host_port: int = 0
    container_port: int = 0
    protocol: str = "TCP"
    host_ip: str = ""


@dataclass
class Container:
    name: str = ""
    requests: dict[str, int] = field(default_factory=dict)  # canonical units
    limits: dict[str, int] = field(default_factory=dict)
    ports: tuple[ContainerPort, ...] = ()
    restart_policy: Optional[str] = None  # init containers: "Always" = sidecar
    images: tuple[str, ...] = ()


@dataclass(frozen=True)
class PodSchedulingGate:
    name: str


@dataclass
class ObjectMeta:
    name: str = ""
    namespace: str = "default"
    uid: str = ""
    labels: dict[str, str] = field(default_factory=dict)
    annotations: dict[str, str] = field(default_factory=dict)


PREEMPT_LOWER_PRIORITY = "PreemptLowerPriority"
PREEMPT_NEVER = "Never"


@dataclass
class PodSpec:
    containers: list[Container] = field(default_factory=list)
    init_containers: list[Container] = field(default_factory=list)
    overhead: dict[str, int] = field(default_factory=dict)
    node_selector: dict[str, str] = field(default_factory=dict)
    affinity: Optional[Affinity] = None
    tolerations: tuple[Toleration, ...] = ()
    topology_spread_constraints: tuple[TopologySpreadConstraint, ...] = ()
    priority: int = 0
    preemption_policy: str = PREEMPT_LOWER_PRIORITY
    node_name: str = ""
    scheduler_name: str = "default-scheduler"
    scheduling_gates: tuple[PodSchedulingGate, ...] = ()
    # NB: no quotes around Volume — the module's lazy annotations resolve
    # the whole string at get_type_hints time, but a QUOTED name inside a
    # PEP-585 generic stays a plain str forever (3.10 never converts it
    # to a ForwardRef), which made the generated dumper emit raw Volume
    # objects and broke every JSON path that serialized a volume pod.
    volumes: tuple[Volume, ...] = ()
    # Gang scheduling (coscheduling-style): name of the pod's PodGroup.
    pod_group: str = ""
    # ResourceClaim names in the pod's namespace (spec.resourceClaims).
    resource_claims: tuple[str, ...] = ()


@dataclass
class PodStatus:
    nominated_node_name: str = ""
    phase: str = "Pending"
    start_time: float = 0.0  # pod start timestamp (preemption tie-break)


@dataclass
class Pod:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodSpec = field(default_factory=PodSpec)
    status: PodStatus = field(default_factory=PodStatus)

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace

    @property
    def uid(self) -> str:
        # Memoized: the uid is read on every queue/cache/commit touch
        # (~17 reads per scheduled pod) and informer deliveries always
        # arrive as NEW objects, so the identity can never change under a
        # live instance.
        u = self.__dict__.get("_uid")
        if u is None:
            u = (
                self.metadata.uid
                or f"{self.metadata.namespace}/{self.metadata.name}"
            )
            self.__dict__["_uid"] = u
        return u

    def resource_request(self) -> dict[str, int]:
        """Effective scheduling request.

        Mirrors resourcehelper.PodRequests as the scheduler uses it
        (pkg/scheduler/framework/types.go:1055 calculateResource):
        max(sum of app containers + sidecars, peak init container) + overhead.
        """
        total: dict[str, int] = {}

        def add(into: dict[str, int], frm: dict[str, int]) -> None:
            for k, v in frm.items():
                into[k] = into.get(k, 0) + v

        def maxof(into: dict[str, int], frm: dict[str, int]) -> None:
            for k, v in frm.items():
                if v > into.get(k, 0):
                    into[k] = v

        for c in self.spec.containers:
            add(total, c.requests)
        sidecar_sum: dict[str, int] = {}
        init_peak: dict[str, int] = {}
        for c in self.spec.init_containers:
            if c.restart_policy == RESTART_POLICY_ALWAYS:
                add(sidecar_sum, c.requests)
                # A sidecar's own request plus all earlier sidecars is a peak too.
                maxof(init_peak, dict(sidecar_sum))
            else:
                peak = dict(sidecar_sum)
                add(peak, c.requests)
                maxof(init_peak, peak)
        add(total, sidecar_sum)
        maxof(total, init_peak)
        if self.spec.overhead:
            add(total, self.spec.overhead)
        return total

    def non_zero_request(self) -> tuple[int, int]:
        """(milliCPU, memory) with per-container scheduler defaults for missing
        requests (reference: NonMissingContainerRequests in
        noderesources/resource_allocation.go:123 and
        pkg/scheduler/util/pod_resources.go GetNonzeroRequests)."""

        def defaulted(c: Container, res: str, dflt: int) -> int:
            v = c.requests.get(res)
            return dflt if v is None else v

        cpu = sum(defaulted(c, CPU, DEFAULT_MILLI_CPU_REQUEST) for c in self.spec.containers)
        mem = sum(defaulted(c, MEMORY, DEFAULT_MEMORY_REQUEST) for c in self.spec.containers)
        # Init-container peak with the same defaulting.
        init_cpu = max(
            (defaulted(c, CPU, DEFAULT_MILLI_CPU_REQUEST) for c in self.spec.init_containers),
            default=0,
        )
        init_mem = max(
            (defaulted(c, MEMORY, DEFAULT_MEMORY_REQUEST) for c in self.spec.init_containers),
            default=0,
        )
        cpu, mem = max(cpu, init_cpu), max(mem, init_mem)
        cpu += self.spec.overhead.get(CPU, 0)
        mem += self.spec.overhead.get(MEMORY, 0)
        return cpu, mem

    def host_ports(self) -> list[tuple[str, str, int]]:
        """(protocol, hostIP, hostPort) triples with hostPort != 0."""
        out = []
        for c in self.spec.containers:
            for p in c.ports:
                if p.host_port:
                    out.append((p.protocol or "TCP", p.host_ip or "0.0.0.0", p.host_port))
        return out


# ---------------------------------------------------------------------------
# Nodes
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class ContainerImage:
    names: tuple[str, ...]
    size_bytes: int = 0


@dataclass
class NodeSpec:
    taints: tuple[Taint, ...] = ()
    unschedulable: bool = False


@dataclass
class NodeStatus:
    capacity: dict[str, int] = field(default_factory=dict)
    allocatable: dict[str, int] = field(default_factory=dict)
    images: tuple[ContainerImage, ...] = ()


@dataclass
class Node:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: NodeSpec = field(default_factory=NodeSpec)
    status: NodeStatus = field(default_factory=NodeStatus)

    @property
    def name(self) -> str:
        return self.metadata.name


@dataclass
class Lease:
    """coordination.k8s.io/v1 Lease, reduced to the kubelet node-heartbeat
    use (pkg/kubelet/nodelease): ``renew_time`` is the holder's last
    renewal in the FEED's clock domain (seconds).  The node-lifecycle
    controller (controllers.py) judges node liveness from Lease renewals —
    nodes that never renew a lease are exempt, so embedders that only feed
    Node objects keep the pre-lease behavior."""

    node_name: str
    renew_time: float = 0.0

    @property
    def name(self) -> str:  # the wire store keys non-Pod kinds by .name
        return self.node_name


# ---------------------------------------------------------------------------
# Scalar (host-side) selector evaluation — the reference semantics that the
# vectorized ops must reproduce; also used directly for rare host-side paths.
# ---------------------------------------------------------------------------


def label_selector_matches(sel: Optional[LabelSelector], labels: dict[str, str]) -> bool:
    """Mirror of metav1.LabelSelectorAsSelector + Matches
    (staging/src/k8s.io/apimachinery/pkg/apis/meta/v1/helpers.go).
    None selects nothing; empty selects everything."""
    if sel is None:
        return False
    for k, v in sel.match_labels:
        if labels.get(k) != v:
            return False
    for req in sel.match_expressions:
        has = req.key in labels
        val = labels.get(req.key)
        if req.operator == OP_IN:
            if not has or val not in req.values:
                return False
        elif req.operator == OP_NOT_IN:
            if has and val in req.values:
                return False
        elif req.operator == OP_EXISTS:
            if not has:
                return False
        elif req.operator == OP_DOES_NOT_EXIST:
            if has:
                return False
        else:
            raise ValueError(f"bad label selector operator {req.operator}")
    return True


def _as_int(s: Optional[str]) -> Optional[int]:
    try:
        return int(s)  # type: ignore[arg-type]
    except (TypeError, ValueError):
        return None


def node_selector_requirement_matches(
    req: NodeSelectorRequirement, labels: dict[str, str]
) -> bool:
    """Mirror of nodeaffinity.nodeSelectorRequirementsAsSelector semantics
    (staging/src/k8s.io/component-helpers/scheduling/corev1/nodeaffinity/nodeaffinity.go)."""
    has = req.key in labels
    val = labels.get(req.key)
    if req.operator == OP_IN:
        return has and val in req.values
    if req.operator == OP_NOT_IN:
        return not has or val not in req.values
    if req.operator == OP_EXISTS:
        return has
    if req.operator == OP_DOES_NOT_EXIST:
        return not has
    if req.operator in (OP_GT, OP_LT):
        if not has or len(req.values) != 1:
            return False
        lhs, rhs = _as_int(val), _as_int(req.values[0])
        if lhs is None or rhs is None:
            return False
        return lhs > rhs if req.operator == OP_GT else lhs < rhs
    raise ValueError(f"bad node selector operator {req.operator}")


def node_selector_term_matches(
    term: NodeSelectorTerm, labels: dict[str, str], node_name: str = ""
) -> bool:
    if not term.match_expressions and not term.match_fields:
        return False  # empty term matches nothing (nodeaffinity.go:nodeSelectorTermsMatch)
    for req in term.match_expressions:
        if not node_selector_requirement_matches(req, labels):
            return False
    for req in term.match_fields:
        # Only supported field is metadata.name.
        if req.key != "metadata.name":
            return False
        if not node_selector_requirement_matches(
            NodeSelectorRequirement("metadata.name", req.operator, req.values),
            {"metadata.name": node_name},
        ):
            return False
    return True


def node_selector_matches(
    sel: Optional[NodeSelector], labels: dict[str, str], node_name: str = ""
) -> bool:
    if sel is None:
        return True
    if not sel.terms:
        return False
    return any(node_selector_term_matches(t, labels, node_name) for t in sel.terms)


# ---------------------------------------------------------------------------
# Volumes (PV / PVC / StorageClass / CSINode) — the subset the scheduler's
# volume plugins consume (reference: pkg/scheduler/framework/plugins/
# volumebinding, volumezone, volumerestrictions, nodevolumelimits).
# ---------------------------------------------------------------------------

BINDING_IMMEDIATE = "Immediate"
BINDING_WAIT_FOR_FIRST_CONSUMER = "WaitForFirstConsumer"

RWO = "ReadWriteOnce"
ROX = "ReadOnlyMany"
RWX = "ReadWriteMany"
RWOP = "ReadWriteOncePod"


@dataclass
class StorageClass:
    name: str
    provisioner: str = "kubernetes.io/no-provisioner"
    binding_mode: str = BINDING_IMMEDIATE
    # Topology restriction for dynamically provisioned volumes
    # (StorageClass.allowedTopologies): OR of terms like a NodeSelector.
    allowed_topologies: Optional[NodeSelector] = None


@dataclass
class PersistentVolume:
    name: str
    capacity: int = 0  # bytes
    access_modes: tuple[str, ...] = (RWO,)
    storage_class: str = ""
    # PV.spec.nodeAffinity.required — where this volume is reachable.
    node_affinity: Optional[NodeSelector] = None
    labels: dict[str, str] = field(default_factory=dict)  # incl. zone/region
    claim_ref: Optional[str] = None  # "ns/name" of the bound PVC
    csi_driver: str = ""  # CSI driver name (for attach limits)


@dataclass
class PersistentVolumeClaim:
    name: str
    namespace: str = "default"
    storage_class: str = ""
    access_modes: tuple[str, ...] = (RWO,)
    request: int = 0  # bytes
    volume_name: str = ""  # bound PV, "" = unbound

    @property
    def uid(self) -> str:
        return f"{self.namespace}/{self.name}"


@dataclass(frozen=True)
class Volume:
    """One pod volume: a PVC reference or an in-tree device volume
    (GCE PD / AWS EBS / AzureDisk / ISCSI modeled uniformly as a device id
    with the reference's both-read-only exemption)."""

    name: str = ""
    pvc: str = ""  # PVC name (pod's namespace)
    device_id: str = ""  # in-tree volume unique device id
    read_only: bool = False


@dataclass
class CSINode:
    """CSINode.spec.drivers[*].allocatable.count per driver."""

    name: str  # node name
    driver_limits: dict[str, int] = field(default_factory=dict)


@dataclass
class PodGroup:
    """Gang-scheduling group (the out-of-tree coscheduling plugin's
    PodGroup CRD): at least ``min_member`` pods schedule together or none
    do."""

    name: str
    min_member: int = 1


@dataclass
class Device:
    """resource.k8s.io BasicDevice (api/resource/v1alpha3/types.go:205):
    one named device instance with typed attributes (bool/int/string) and
    capacity quantities (canonical integer units, like every quantity in
    the object model — CEL ``device.capacity`` terms compare against
    these, dra_cel.py)."""

    name: str
    attributes: dict = field(default_factory=dict)
    capacity: dict = field(default_factory=dict)


@dataclass
class DeviceRequest:
    """ResourceClaim.spec.devices.requests[i]: ``count`` devices of a
    class, narrowed by CEL selectors (DeviceRequest.Selectors;
    dra_cel.py compiles the vectorizable subset)."""

    name: str
    device_class: str
    count: int = 1
    selectors: tuple[str, ...] = ()  # CEL expressions, ANDed


@dataclass
class ResourceClaim:
    """resource.k8s.io ResourceClaim with structured parameters
    (plugins/dynamicresources/, staging dynamic-resource-allocation/
    structured/): device requests with CEL selectors; allocation pins the
    claim to one node and names the chosen devices.  The single-request
    counted shorthand (device_class + count, the round-2 form) remains the
    default when ``requests`` is empty."""

    name: str
    device_class: str = ""
    count: int = 1
    namespace: str = "default"
    allocated_node: str = ""  # "" = unallocated (delayed allocation)
    reserved_for: tuple[str, ...] = ()  # pod uids (status.reservedFor)
    requests: tuple[DeviceRequest, ...] = ()
    # Allocation result (status.allocation.devices.results): the chosen
    # (request name, device name) pairs.
    allocated_devices: tuple[tuple[str, str], ...] = ()

    def device_requests(self) -> tuple[DeviceRequest, ...]:
        """The claim's requests; the counted shorthand synthesizes one
        selector-less request."""
        if self.requests:
            return self.requests
        return (DeviceRequest("r0", self.device_class, self.count),)

    @property
    def uid(self) -> str:
        return f"{self.namespace}/{self.name}"


@dataclass
class ResourceSlice:
    """resource.k8s.io ResourceSlice: the devices a node publishes for one
    device class.  ``devices`` carries named instances with attributes
    (ResourceSlice.spec.devices, types.go:144); the counted form
    (``count`` with no devices) publishes anonymous attribute-less
    instances."""

    node_name: str
    device_class: str
    count: int = 1
    devices: tuple[Device, ...] = ()


@dataclass
class PodDisruptionBudget:
    """policy/v1 PodDisruptionBudget, reduced to what preemption needs:
    the selector and the live status.disruptionsAllowed count
    (framework/preemption/preemption.go filterPodsWithPDBViolation reads
    pdb.Status.DisruptionsAllowed)."""

    name: str
    selector: Optional[LabelSelector] = None
    disruptions_allowed: int = 0
    namespace: str = "default"
    # Spec fields (policy/v1 PDBSpec): when either is set, the in-process
    # DisruptionController (controllers.py) recomputes disruptions_allowed
    # from live pod state; when both are None the field above is the
    # informer-fed status and stays untouched.  int or "N%" strings
    # (intstr.IntOrString).
    min_available: Optional[int | str] = None
    max_unavailable: Optional[int | str] = None
