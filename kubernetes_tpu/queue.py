"""Three-stage scheduling queue: activeQ / backoffQ / unschedulable pool.

Mirrors the reference's PriorityQueue (pkg/scheduler/backend/queue/
scheduling_queue.go:152): activeQ is a heap ordered by the QueueSort plugin
(priority desc, then enqueue time — queuesort/priority_sort.go), backoffQ
holds pods whose backoff hasn't expired (1s initial, ×2 per attempt, 10s cap —
scheduling_queue.go:73–81), and the unschedulable pool holds pods waiting for
a cluster event that might make them schedulable again
(flushUnschedulablePodsLeftover re-activates them after 5min, :807).

Requeue-on-event hints are simplified to event bitmasks per rejection source
(the analog of isPodWorthRequeuing's per-plugin QueueingHintFn, :406)."""

from __future__ import annotations

import heapq
import itertools
import time
from dataclasses import dataclass, field
from enum import IntFlag, auto

from .api import types as t


class Event(IntFlag):
    """Cluster event kinds driving requeue (framework/events.go:40)."""

    NODE_ADD = auto()
    NODE_UPDATE = auto()
    NODE_TAINT = auto()
    NODE_LABEL = auto()
    POD_ADD = auto()
    POD_UPDATE = auto()
    POD_DELETE = auto()
    PV_ADD = auto()
    PVC_ADD = auto()
    ANY = (
        NODE_ADD | NODE_UPDATE | NODE_TAINT | NODE_LABEL | POD_ADD | POD_UPDATE
        | POD_DELETE | PV_ADD | PVC_ADD
    )


# Which events can unblock a pod rejected by a given plugin — the static core
# of the reference's per-plugin EventsToRegister (e.g. fit.go:253 queueing hints).
PLUGIN_REQUEUE_EVENTS: dict[str, Event] = {
    "NodeResourcesFit": Event.NODE_ADD | Event.NODE_UPDATE | Event.POD_DELETE | Event.POD_UPDATE,
    "NodeAffinity": Event.NODE_ADD | Event.NODE_LABEL,
    "NodeName": Event.NODE_ADD,
    "NodeUnschedulable": Event.NODE_ADD | Event.NODE_UPDATE,
    "TaintToleration": Event.NODE_ADD | Event.NODE_TAINT,
    "NodePorts": Event.NODE_ADD | Event.POD_DELETE,
    "PodTopologySpread": Event.NODE_ADD | Event.NODE_LABEL | Event.POD_ADD | Event.POD_DELETE | Event.POD_UPDATE,
    "InterPodAffinity": Event.NODE_ADD | Event.NODE_LABEL | Event.POD_ADD | Event.POD_DELETE | Event.POD_UPDATE,
    "VolumeBinding": Event.NODE_ADD | Event.PV_ADD | Event.PVC_ADD | Event.POD_DELETE,
    "VolumeZone": Event.NODE_ADD | Event.NODE_LABEL | Event.PV_ADD | Event.PVC_ADD,
    "VolumeRestrictions": Event.POD_DELETE | Event.PV_ADD | Event.PVC_ADD | Event.NODE_ADD,
    "NodeVolumeLimits": Event.NODE_ADD | Event.NODE_UPDATE | Event.POD_DELETE | Event.PVC_ADD,
    # Gang members wait for more members (pod adds) or capacity.
    "GangScheduling": Event.POD_ADD | Event.POD_DELETE | Event.NODE_ADD,
}

DEFAULT_POD_INITIAL_BACKOFF_S = 1.0
DEFAULT_POD_MAX_BACKOFF_S = 10.0
DEFAULT_MAX_UNSCHEDULABLE_DURATION_S = 300.0


@dataclass(order=False)
class QueuedPodInfo:
    """Mirror of framework.QueuedPodInfo (types.go:362)."""

    pod: t.Pod
    timestamp: float = 0.0  # time added to activeQ this round
    initial_attempt_timestamp: float = 0.0
    attempts: int = 0
    unschedulable_plugins: set[str] = field(default_factory=set)
    gated: bool = False


class SchedulingQueue:
    def __init__(
        self,
        initial_backoff_s: float = DEFAULT_POD_INITIAL_BACKOFF_S,
        max_backoff_s: float = DEFAULT_POD_MAX_BACKOFF_S,
        max_unschedulable_s: float = DEFAULT_MAX_UNSCHEDULABLE_DURATION_S,
        clock=time.monotonic,
    ):
        self._clock = clock
        self._seq = itertools.count()
        self._active: list = []  # heap of (-priority, timestamp, seq, uid)
        self._backoff: list = []  # heap of (expiry, seq, uid)
        self._unschedulable: dict[str, QueuedPodInfo] = {}
        self._info: dict[str, QueuedPodInfo] = {}
        self._in_active: set[str] = set()
        self.initial_backoff_s = initial_backoff_s
        self.max_backoff_s = max_backoff_s
        self.max_unschedulable_s = max_unschedulable_s
        self._gated: dict[str, QueuedPodInfo] = {}

    def __len__(self) -> int:
        return len(self._in_active)

    def pending_count(self) -> int:
        return len(self._in_active) + len(self._backoff) + len(self._unschedulable) + len(self._gated)

    # -- add / pop -----------------------------------------------------------

    def add(self, pod: t.Pod) -> None:
        now = self._clock()
        qp = self._info.get(pod.uid)
        if qp is None:
            qp = QueuedPodInfo(pod=pod, timestamp=now, initial_attempt_timestamp=now)
            self._info[pod.uid] = qp
        qp.pod = pod
        # PreEnqueue: SchedulingGates holds gated pods out of every queue
        # (plugins/schedulinggates/scheduling_gates.go).
        if pod.spec.scheduling_gates:
            qp.gated = True
            self._gated[pod.uid] = qp
            return
        qp.gated = False
        self._push_active(qp)

    def _push_active(self, qp: QueuedPodInfo) -> None:
        if qp.pod.uid in self._in_active:
            return
        qp.timestamp = self._clock()
        heapq.heappush(
            self._active,
            (-qp.pod.spec.priority, qp.timestamp, next(self._seq), qp.pod.uid),
        )
        self._in_active.add(qp.pod.uid)
        self._unschedulable.pop(qp.pod.uid, None)

    def pop_batch(self, k: int) -> list[QueuedPodInfo]:
        """Pop up to k pods in QueueSort order — the batch analog of
        activeQueue.pop (active_queue.go:186)."""
        self.flush_backoff()
        out: list[QueuedPodInfo] = []
        while self._active and len(out) < k:
            _, _, _, uid = heapq.heappop(self._active)
            if uid not in self._in_active:
                continue
            self._in_active.discard(uid)
            qp = self._info[uid]
            qp.attempts += 1
            out.append(qp)
        return out

    # -- failure / backoff -----------------------------------------------------

    def backoff_duration(self, attempts: int) -> float:
        d = self.initial_backoff_s
        for _ in range(1, attempts):
            d *= 2
            if d >= self.max_backoff_s:
                return self.max_backoff_s
        return d

    def add_unschedulable(self, qp: QueuedPodInfo, plugins: set[str]) -> None:
        """AddUnschedulableIfNotPresent (scheduling_queue.go:728): pods that
        failed go to the unschedulable pool keyed by what rejected them."""
        qp.unschedulable_plugins = plugins
        self._unschedulable[qp.pod.uid] = qp

    def add_backoff(self, qp: QueuedPodInfo) -> None:
        expiry = self._clock() + self.backoff_duration(qp.attempts)
        heapq.heappush(self._backoff, (expiry, next(self._seq), qp.pod.uid))

    def next_backoff_expiry(self) -> float | None:
        """Earliest backoff expiry, or None when the backoffQ is empty."""
        return self._backoff[0][0] if self._backoff else None

    def sleep_until_backoff(self) -> bool:
        """Sleep until the earliest backoff expires.  Returns False when
        there is nothing to wait for — including under an injected test
        clock, which wall-clock sleeping can never advance."""
        expiry = self.next_backoff_expiry()
        if expiry is None or self._clock is not time.monotonic:
            return False
        time.sleep(max(0.0, expiry - self._clock()) + 1e-3)
        return True

    def flush_backoff(self) -> int:
        """Move expired backoff pods to activeQ (flushBackoffQCompleted :777)."""
        now = self._clock()
        n = 0
        while self._backoff and self._backoff[0][0] <= now:
            _, _, uid = heapq.heappop(self._backoff)
            qp = self._info.get(uid)
            if qp is not None:
                self._push_active(qp)
                n += 1
        return n

    def flush_unschedulable_leftover(self) -> int:
        """Re-activate pods stuck unschedulable > max duration (:807)."""
        now = self._clock()
        stale = [
            uid
            for uid, qp in self._unschedulable.items()
            if now - qp.timestamp > self.max_unschedulable_s
        ]
        for uid in stale:
            self._push_active(self._unschedulable.pop(uid))
        return len(stale)

    # -- events ----------------------------------------------------------------

    def on_event(self, event: Event) -> int:
        """MoveAllToActiveOrBackoffQueue (scheduling_queue.go:1029): wake
        unschedulable pods whose rejecting plugins care about this event."""
        woken = []
        for uid, qp in self._unschedulable.items():
            interested = Event(0)
            for pl in qp.unschedulable_plugins or {"NodeResourcesFit"}:
                interested |= PLUGIN_REQUEUE_EVENTS.get(pl, Event.ANY)
            if interested & event:
                woken.append(uid)
        for uid in woken:
            qp = self._unschedulable.pop(uid)
            self.add_backoff(qp)
        return len(woken)

    def remove_gate(self, uid: str) -> None:
        """A pod's scheduling gates were cleared; admit it."""
        qp = self._gated.pop(uid, None)
        if qp is not None:
            qp.gated = False
            self._push_active(qp)

    def delete(self, uid: str) -> None:
        self._in_active.discard(uid)
        self._unschedulable.pop(uid, None)
        self._gated.pop(uid, None)
        self._info.pop(uid, None)

    def done(self, uid: str) -> None:
        """Pod scheduled successfully; drop bookkeeping."""
        self._info.pop(uid, None)
