"""Three-stage scheduling queue: activeQ / backoffQ / unschedulable pool.

Mirrors the reference's PriorityQueue (pkg/scheduler/backend/queue/
scheduling_queue.go:152): activeQ is a heap ordered by the QueueSort plugin
(priority desc, then enqueue time — queuesort/priority_sort.go), backoffQ
holds pods whose backoff hasn't expired (1s initial, ×2 per attempt, 10s cap —
scheduling_queue.go:73–81), and the unschedulable pool holds pods waiting for
a cluster event that might make them schedulable again
(flushUnschedulablePodsLeftover re-activates them after 5min, :807).

Requeue-on-event hints are simplified to event bitmasks per rejection source
(the analog of isPodWorthRequeuing's per-plugin QueueingHintFn, :406)."""

from __future__ import annotations

import heapq
import itertools
import time
from collections import deque
from dataclasses import dataclass, field
from enum import IntFlag, auto

import numpy as np

from .api import types as t
from .framework import fairness


class Event(IntFlag):
    """Cluster event kinds driving requeue (framework/events.go:40)."""

    NODE_ADD = auto()
    NODE_UPDATE = auto()
    NODE_TAINT = auto()
    NODE_LABEL = auto()
    POD_ADD = auto()
    POD_UPDATE = auto()
    POD_DELETE = auto()
    PV_ADD = auto()
    PVC_ADD = auto()
    CLAIM_ADD = auto()  # ResourceClaim/ResourceSlice events (DRA)
    ANY = (
        NODE_ADD | NODE_UPDATE | NODE_TAINT | NODE_LABEL | POD_ADD | POD_UPDATE
        | POD_DELETE | PV_ADD | PVC_ADD | CLAIM_ADD
    )


# Which events can unblock a pod rejected by a given plugin — the static core
# of the reference's per-plugin EventsToRegister (e.g. fit.go:253 queueing hints).
PLUGIN_REQUEUE_EVENTS: dict[str, Event] = {
    "NodeResourcesFit": Event.NODE_ADD | Event.NODE_UPDATE | Event.POD_DELETE | Event.POD_UPDATE,
    "NodeAffinity": Event.NODE_ADD | Event.NODE_LABEL,
    "NodeName": Event.NODE_ADD,
    "NodeUnschedulable": Event.NODE_ADD | Event.NODE_UPDATE,
    "TaintToleration": Event.NODE_ADD | Event.NODE_TAINT,
    "NodePorts": Event.NODE_ADD | Event.POD_DELETE,
    "PodTopologySpread": Event.NODE_ADD | Event.NODE_LABEL | Event.POD_ADD | Event.POD_DELETE | Event.POD_UPDATE,
    "InterPodAffinity": Event.NODE_ADD | Event.NODE_LABEL | Event.POD_ADD | Event.POD_DELETE | Event.POD_UPDATE,
    "VolumeBinding": Event.NODE_ADD | Event.PV_ADD | Event.PVC_ADD | Event.POD_DELETE,
    "VolumeZone": Event.NODE_ADD | Event.NODE_LABEL | Event.PV_ADD | Event.PVC_ADD,
    "VolumeRestrictions": Event.POD_DELETE | Event.PV_ADD | Event.PVC_ADD | Event.NODE_ADD,
    "NodeVolumeLimits": Event.NODE_ADD | Event.NODE_UPDATE | Event.POD_DELETE | Event.PVC_ADD,
    # Gang members wait for more members (pod adds) or capacity.
    "GangScheduling": Event.POD_ADD | Event.POD_DELETE | Event.NODE_ADD,
    "DynamicResources": Event.CLAIM_ADD | Event.POD_DELETE | Event.NODE_ADD
    | Event.NODE_UPDATE,
}

DEFAULT_POD_INITIAL_BACKOFF_S = 1.0
DEFAULT_POD_MAX_BACKOFF_S = 10.0
DEFAULT_MAX_UNSCHEDULABLE_DURATION_S = 300.0
# Quarantine-release history window (SchedulingQueue.release_history):
# bounded so an unbounded release stream cannot grow the queue's durable
# state — compaction trims snapshots to this trailing window.
RELEASE_HISTORY_MAX = 256


@dataclass
class EventCtx:
    """Event-object payload for object-aware queueing hints — the batch
    analog of the oldObj/newObj arguments the reference passes to each
    plugin's QueueingHintFn (scheduling_queue.go:406 isPodWorthRequeuing;
    e.g. fit.go:253 isSchedulableAfterPodChange checks whether the deleted
    pod actually frees enough for the waiting pod).

    ``max_free``/``max_slots`` summarize capacity freed or added by the
    event, elementwise-maxed over every affected node (nominated pods'
    claims already subtracted).  The max is an upper bound on any single
    node's free vector, so hints stay conservative: a pod that fits some
    affected node always fits the max and is woken; a pod that cannot fit
    the max cannot fit anywhere and is skipped."""

    max_free: np.ndarray | None = None  # (R,) free allocatable upper bound
    max_slots: int = 0  # free pod slots upper bound


def _pack_reqs(reqs: list[np.ndarray]) -> np.ndarray:
    """Stack request vectors into one (K, maxR) int64 matrix (zero-padded;
    a missing column means the pod does not request that resource)."""
    mx = max((q.shape[0] for q in reqs), default=0)
    reqm = np.zeros((len(reqs), max(mx, 1)), np.int64)
    for i, q in enumerate(reqs):
        reqm[i, : q.shape[0]] = q
    return reqm


def _fits_packed(reqm: np.ndarray, ctx: EventCtx) -> np.ndarray:
    """(K,) bool over a prepacked request matrix: which pods the event's
    freed capacity could seat — THE fit predicate, shared by the scalar
    hint and the queue's batched wake path so the two cannot drift.  A pod
    needing a resource column the affected nodes don't expose never
    wakes."""
    k = reqm.shape[0]
    if ctx.max_slots < 1:
        return np.zeros(k, np.bool_)
    r = ctx.max_free.shape[0]
    head = reqm[:, :r]
    free = ctx.max_free[: head.shape[1]]
    # The fit filter's per-resource escape: a resource the pod does not
    # request never blocks it (negative free in an unrequested column —
    # nominated-claim subtraction — must not pin the pod asleep).
    fits = ((head == 0) | (head <= free[None, :])).all(axis=1)
    if reqm.shape[1] > r:
        fits &= ~(reqm[:, r:] != 0).any(axis=1)
    return fits


def _fits_free(reqs: list[np.ndarray], ctx: EventCtx) -> np.ndarray:
    return _fits_packed(_pack_reqs(reqs), ctx)


def _fit_hint(qp: "QueuedPodInfo", event: "Event", ctx: EventCtx) -> bool:
    """NodeResourcesFit QueueingHint (fit.go:253 isSchedulableAfterPodChange
    / :300 isSchedulableAfterNodeChange): requeue only when the event's
    freed/added capacity could actually seat this pod."""
    if ctx.max_free is None or qp.delta is None:
        return True  # no object info — conservative requeue
    return bool(_fits_free([qp.delta["req"]], ctx)[0])


# Object-aware per-plugin hints; plugins absent here fall back to the static
# event-mask behavior (PLUGIN_REQUEUE_EVENTS alone).
PLUGIN_HINTS = {
    "NodeResourcesFit": _fit_hint,
}

@dataclass(order=False)
class QueuedPodInfo:
    """Mirror of framework.QueuedPodInfo (types.go:362)."""

    pod: t.Pod
    timestamp: float = 0.0  # time added to activeQ this round
    initial_attempt_timestamp: float = 0.0
    attempts: int = 0
    unschedulable_plugins: set[str] = field(default_factory=set)
    gated: bool = False
    # The pod's featurized commit delta from its last attempt (request
    # vector etc.) — the object-aware hints read it; None before the first
    # attempt or after a spec update invalidated it.
    delta: dict | None = None
    # A nominated-pin evaluation failed for this pod: its next attempt
    # takes the full pass (the scheduler's _pin_rows skips it).  Reset when
    # a fresh nomination is recorded.
    nom_pin_failed: bool = False
    # Requeue-verdict class this pod was filed under when it entered the
    # unschedulable pool (set by _unsched_insert, read by _unsched_remove).
    unsched_class: tuple | None = None


class SchedulingQueue:
    def __init__(
        self,
        initial_backoff_s: float = DEFAULT_POD_INITIAL_BACKOFF_S,
        max_backoff_s: float = DEFAULT_POD_MAX_BACKOFF_S,
        max_unschedulable_s: float = DEFAULT_MAX_UNSCHEDULABLE_DURATION_S,
        clock=time.monotonic,
        admission_policy=None,
    ):
        self._clock = clock
        # Weighted-fair admission (framework/fairness.FairAdmission), OFF
        # by default: unarmed, pop_batch is the byte-identical pre-fairness
        # QueueSort path.  Armed, active pods pool into per-tenant heaps
        # and the policy's WFQ/credit state picks which tenant's head pops
        # next.  Arm at construction or via arm_admission().
        self.admission = admission_policy
        self._tenant_active: dict[str, list] = {}
        # True after a pop_batch that returned short NOT because the
        # active pool drained but because every queued tenant is credit-
        # blocked — drain loops must stop polling on this instead of
        # spinning on len(queue) (aging re-arms eligibility later).
        self.last_pop_throttled = False
        self._seq = itertools.count()
        self._active: list = []  # heap of (-priority, timestamp, seq, uid)
        self._backoff: list = []  # heap of (expiry, seq, uid)
        self._unschedulable: dict[str, QueuedPodInfo] = {}
        # Verdict-class index over the unschedulable pool: pods whose
        # requeue verdict is identical for every event share a class
        # ((rejecting plugins, delta presence) — valid while every
        # registered hint is the batched fit hint), so on_event computes
        # ONE verdict per class and one vectorized fit check over a cached
        # request matrix instead of a Python walk of a 15k-pod pool.
        self._unsched_classes: dict[tuple, dict[str, QueuedPodInfo]] = {}
        self._unsched_req_cache: dict[tuple, tuple[list, np.ndarray]] = {}
        self._info: dict[str, QueuedPodInfo] = {}
        self._in_active: set[str] = set()
        # Quarantine pool: pods whose presence in a batch made the ENGINE
        # raise (poison pods, isolated by the scheduler's batch bisect).
        # Unlike the unschedulable pool, no cluster event re-admits them —
        # the failure is a property of the pod, not of capacity — so they
        # sit here until an operator (or a spec update, which invalidates
        # the poison featurization) releases them back through the backoff
        # machinery.  Surfaced as scheduler_pending_pods{queue="quarantine"}.
        self._quarantine: dict[str, QueuedPodInfo] = {}
        # Release history: the trailing window of quarantine releases
        # (operator actions worth triaging after the fact).  BOUNDED —
        # over an unbounded soak the release stream never ends, so the
        # ring trims itself and snapshots carry only this window; the
        # journal's release_quarantine records beyond it are reclaimed
        # by the next snapshot+truncate compaction cycle.
        self.release_history: deque = deque(maxlen=RELEASE_HISTORY_MAX)
        self.initial_backoff_s = initial_backoff_s
        self.max_backoff_s = max_backoff_s
        self.max_unschedulable_s = max_unschedulable_s
        self._gated: dict[str, QueuedPodInfo] = {}
        # Gang admission (the coscheduling plugin's PreEnqueue/Permit pair):
        # members of a registered PodGroup park here until the gang can meet
        # quorum — parked + already-bound credit ≥ minMember — then release
        # TOGETHER so they land in one batch (all-or-nothing co-scheduling;
        # without this, members scatter across pools and quorum never forms).
        self._gang_pool: dict[str, dict[str, QueuedPodInfo]] = {}
        self.gang_min: dict[str, int] = {}
        # Credit per gang beyond the parked members (bound members + members
        # waiting on Permit); the scheduler injects this so PreEnqueue
        # admission and the Permit gate agree.
        self.gang_credit = lambda g: 0
        # Members currently queued anywhere (active/backoff/unschedulable/
        # gated/pool), per gang — the Permit gate asks "are enough members
        # still coming?" before deciding wait-vs-rollback (WaitOnPermit).
        self._gang_members: dict[str, set[str]] = {}
        # SchedulerQueueingHints feature gate: when False, requeue decisions
        # use the static per-plugin event masks alone (the reference's
        # pre-hint behavior); object-aware PLUGIN_HINTS are skipped.
        self.use_queueing_hints = True
        # PodSchedulingReadiness gate: off ⇒ the SchedulingGates plugin is
        # not registered (plugins/registry.go), so .spec.schedulingGates is
        # ignored and gated pods enter the queue like any other.
        self.respect_scheduling_gates = True
        # Per-profile PreEnqueue (profile.pre_enqueue): the scheduler
        # installs a pod → bool predicate saying whether the pod's profile
        # runs SchedulingGates; None = every profile does.
        self.gates_apply_to = None
        # Write-ahead binding journal (journal.Journal), attached by
        # TPUScheduler.attach_journal.  The queue journals the one durable
        # decision IT owns — releasing a quarantined pod — before applying
        # it; everything else is journaled at the scheduler's commit sites.
        self.journal = None
        # Tenant attribution hook (framework/metrics.py TenantMetrics
        # .note_pod), installed by the scheduler/router when tenant
        # attribution is armed: called with ("admitted", pod) on a pod's
        # FIRST queue entry and ("deferred", pod) on every backoff /
        # unschedulable parking — the queue-admission leg of the
        # per-tenant fairness counters.  None = attribution off.
        self.tenant_note = None

    def __len__(self) -> int:
        return len(self._in_active)

    def pending_count(self) -> int:
        return (
            len(self._in_active)
            + len(self._backoff)
            + len(self._unschedulable)
            + len(self._gated)
            + len(self._quarantine)
            + sum(len(p) for p in self._gang_pool.values())
        )

    # -- quarantine ------------------------------------------------------------

    def quarantine(self, qp: QueuedPodInfo) -> None:
        """Isolate a poison pod (its batch made the engine raise).  Re-owns
        the info entry pop_batch dropped; the pod leaves every other pool
        and stays out of scheduling until released."""
        uid = qp.pod.uid
        self._info[uid] = qp
        self._in_active.discard(uid)
        self._unsched_remove(uid)
        qp.unschedulable_plugins = {"EngineFault"}
        qp.timestamp = self._clock()
        qp.delta = None  # featurization is suspect — never trust it again
        self._quarantine[uid] = qp

    def quarantined(self) -> list[str]:
        return list(self._quarantine)

    def release_quarantine(self, uid: str | None = None) -> int:
        """Hand quarantined pod(s) back through the backoff machinery (an
        operator action after a fix, or the update path after a spec
        change).  Backoff grows with the pod's accumulated attempts, so a
        still-poisonous pod re-quarantines at a bounded retry rate instead
        of wedging batches back-to-back."""
        uids = [uid] if uid is not None else list(self._quarantine)
        n = 0
        for u in uids:
            if u in self._quarantine and self.journal is not None:
                # Write-ahead: the release is a durable decision — a
                # restart must not resurrect the pod into quarantine.
                self.journal.append("release_quarantine", {"uid": u})
            qp = self._quarantine.pop(u, None)
            if qp is not None:
                self.add_backoff(qp)
                # Triage trail: what was released and after how many
                # attempts.  The deque bounds itself (RELEASE_HISTORY_MAX)
                # — the clock is the queue's own (monotonic by default,
                # rebased across restarts like every other queue clock).
                self.release_history.append(
                    {
                        "uid": u,
                        "attempts": qp.attempts,
                        "ts": round(self._clock(), 3),
                    }
                )
                n += 1
        return n

    def restore_quarantine(self, pod: t.Pod, attempts: int = 1) -> None:
        """Recovery path (journal.recover): re-isolate a pod a journal
        record says was quarantined, preserving its accumulated attempt
        count so the post-release backoff damping survives the restart.
        The pod may also exist as a snapshot-restored PENDING entry (the
        quarantine decision postdates the snapshot) — quarantine() pulls
        it out of whatever pool it sits in."""
        qp = self._info.get(pod.uid)
        if qp is None:
            now = self._clock()
            qp = QueuedPodInfo(
                pod=pod, timestamp=now, initial_attempt_timestamp=now
            )
        qp.attempts = max(qp.attempts, attempts)
        # Replay applies a decision the journal already holds; appends are
        # muted during recovery, so re-journaling here is wrong by design.
        self.quarantine(qp)  # tpulint: disable=wal-unjournaled-apply

    # -- gang admission --------------------------------------------------------

    def register_gang(self, name: str, min_member: int) -> None:
        self.gang_min[name] = min_member
        self._try_admit_gang(name)

    def gang_pending(self, g: str) -> int:
        """Members of gang g currently queued anywhere (not in-flight)."""
        return len(self._gang_members.get(g, ()))

    def _track_gang_member(self, qp: QueuedPodInfo) -> None:
        self._gang_members.setdefault(qp.pod.spec.pod_group, set()).add(qp.pod.uid)

    def _untrack_gang_member(self, pod: t.Pod) -> None:
        g = pod.spec.pod_group
        if g:
            members = self._gang_members.get(g)
            if members is not None:
                members.discard(pod.uid)
                if not members:
                    self._gang_members.pop(g, None)

    def _park_gang_member(self, qp: QueuedPodInfo) -> None:
        self._gang_pool.setdefault(qp.pod.spec.pod_group, {})[qp.pod.uid] = qp
        self._track_gang_member(qp)

    def _gang_admissible(self, g: str) -> bool:
        pool = self._gang_pool.get(g)
        return pool is not None and len(pool) + self.gang_credit(g) >= self.gang_min.get(g, 1)

    def _try_admit_gang(self, g: str, via_backoff: bool = False) -> bool:
        """Release every parked member of gang ``g`` if quorum is reachable.
        ``via_backoff`` damps event-driven re-admission after a rollback (the
        gang failed with these exact members, so retry behind backoff)."""
        if not self._gang_admissible(g):
            return False
        for qp in self._gang_pool.pop(g).values():
            if via_backoff:
                self.add_backoff(qp)
            else:
                self._push_active(qp)
        return True

    # -- add / pop -----------------------------------------------------------

    def add(self, pod: t.Pod) -> None:
        now = self._clock()
        if pod.uid in self._quarantine:
            # Informer re-deliveries must not resurrect a poison pod into
            # the active queue; spec CHANGES go through update(), which
            # does release it (new featurization, new chance).
            self._quarantine[pod.uid].pod = pod
            return
        qp = self._info.get(pod.uid)
        if qp is None:
            qp = QueuedPodInfo(pod=pod, timestamp=now, initial_attempt_timestamp=now)
            self._info[pod.uid] = qp
            if self.tenant_note is not None:
                self.tenant_note("admitted", pod)
        qp.pod = pod
        # PreEnqueue: SchedulingGates holds gated pods out of every queue
        # (plugins/schedulinggates/scheduling_gates.go).
        if (
            self.respect_scheduling_gates
            and pod.spec.scheduling_gates
            and (self.gates_apply_to is None or self.gates_apply_to(pod))
        ):
            qp.gated = True
            self._gated[pod.uid] = qp
            return
        qp.gated = False
        g = pod.spec.pod_group
        if g:
            self._track_gang_member(qp)
            if g in self.gang_min:
                # New member arrival: park, then admit the whole gang at
                # once if quorum is now reachable.
                self._park_gang_member(qp)
                self._try_admit_gang(g)
                return
        self._push_active(qp)

    def reactivate(self, qp: QueuedPodInfo) -> None:
        """Return an in-flight pod to the ACTIVE queue for a next-batch
        retry (prefetch dissolution, schema-grown-batch fallbacks).
        Restores the bookkeeping pop_batch dropped — the info entry and
        gang membership; registered-gang members re-park so the
        all-or-nothing release is preserved, with an instant re-admission
        attempt (this retry is not a quorum failure)."""
        self._info[qp.pod.uid] = qp
        g = qp.pod.spec.pod_group
        if g:
            self._track_gang_member(qp)
            if g in self.gang_min:
                self._park_gang_member(qp)
                self._try_admit_gang(g)
                return
        self._push_active(qp)

    def requeue_gang_member(self, qp: QueuedPodInfo) -> None:
        """Park a rolled-back gang member WITHOUT instant re-admission — the
        gang just failed with exactly these members, so re-admission waits
        for a cluster event (damped through backoff in on_event) or an
        explicit readmit_gang from the scheduler.  Takes the original
        QueuedPodInfo so attempts/first-enqueue survive the rollback
        (backoff damping and e2e latency stay honest)."""
        self._info[qp.pod.uid] = qp
        self._park_gang_member(qp)

    def readmit_gang(self, g: str) -> bool:
        """Retry a parked gang behind backoff (transient failures — e.g. a
        same-batch PV race — must not strand a quorum-complete gang in a
        quiet cluster where no event would ever re-admit it)."""
        return self._try_admit_gang(g, via_backoff=True)

    def _push_active(self, qp: QueuedPodInfo) -> None:
        if qp.pod.uid in self._in_active:
            return
        qp.timestamp = self._clock()
        item = (
            -qp.pod.spec.priority,
            qp.timestamp,
            next(self._seq),
            qp.pod.uid,
        )
        if self.admission is not None:
            # Armed: active pods pool per tenant (QueueSort order WITHIN
            # a tenant; the policy orders ACROSS tenants) and the policy
            # stamps first-enqueue for aging/starvation accounting.
            tenant = fairness.tenant_of(qp.pod)
            heapq.heappush(self._tenant_active.setdefault(tenant, []), item)
            self.admission.note_enqueue(tenant, qp.pod.uid)
        else:
            heapq.heappush(self._active, item)
        self._in_active.add(qp.pod.uid)
        self._unsched_remove(qp.pod.uid)

    def arm_admission(self, policy) -> None:
        """Arm weighted-fair admission on a live queue: migrate the
        active heap into per-tenant heaps (heap tuples carry over — the
        within-tenant QueueSort order is preserved) and stamp every
        migrated pod's enqueue with the policy so aging starts now."""
        self.admission = policy
        self._tenant_active = {}
        drained, self._active = self._active, []
        for item in drained:
            uid = item[3]
            if uid not in self._in_active:
                continue  # stale heap entry — drop, like pop_batch would
            tenant = fairness.tenant_of(self._info[uid].pod)
            heapq.heappush(self._tenant_active.setdefault(tenant, []), item)
            policy.note_enqueue(tenant, uid)

    def pop_batch(self, k: int) -> list[QueuedPodInfo]:
        """Pop up to k pods in QueueSort order — the batch analog of
        activeQueue.pop (active_queue.go:186).  With admission armed the
        order is the fairness policy's WFQ admission order instead."""
        if self.admission is not None:
            return self._pop_batch_admission(k)
        self.flush_backoff()
        out: list[QueuedPodInfo] = []
        while self._active and len(out) < k:
            _, _, _, uid = heapq.heappop(self._active)
            if uid not in self._in_active:
                continue
            self._in_active.discard(uid)
            qp = self._info[uid]
            qp.attempts += 1
            self._untrack_gang_member(qp.pod)  # in-flight, no longer pending
            out.append(qp)
        return out

    def _pop_batch_admission(self, k: int) -> list[QueuedPodInfo]:
        """The armed pop path: each slot asks the policy which queued
        tenant admits next (WFQ tags + credits + aging escape), then pops
        that tenant's QueueSort head.  Deterministic: candidates are the
        sorted tenant names with a live head, the clock is the policy's
        logical clock, and every debit lands in the policy's intent set
        for the commit drain to journal."""
        self.flush_backoff()
        self.last_pop_throttled = False
        out: list[QueuedPodInfo] = []
        while len(out) < k:
            # Recovery carry-over first: a pod whose admission record
            # survived the crash but whose bind did not is ALREADY
            # admitted (durable debit + admitted_log entry) — it re-enters
            # the batch in durable admission order, ahead of and without
            # re-debiting new WFQ selections.  Its heap entry goes stale
            # and is pruned lazily below, like a delete's.
            pre = self.admission.take_preadmitted(self._in_active)
            if pre is not None:
                self._in_active.discard(pre)
                qp = self._info[pre]
                qp.attempts += 1
                self._untrack_gang_member(qp.pod)
                out.append(qp)
                continue
            tenants = []
            for t in sorted(self._tenant_active):
                heap = self._tenant_active[t]
                while heap and heap[0][3] not in self._in_active:
                    heapq.heappop(heap)  # stale entry (deleted/updated)
                if heap:
                    tenants.append(t)
                else:
                    del self._tenant_active[t]
            if not tenants:
                break
            now = self.admission.now()
            picked = self.admission.select(tenants, now)
            if picked is None:
                # Pods are queued but every tenant is credit-blocked:
                # throttled, not starved — aging re-arms eligibility.
                self.last_pop_throttled = True
                break
            tenant, escape = picked
            _, _, _, uid = heapq.heappop(self._tenant_active[tenant])
            self._in_active.discard(uid)
            qp = self._info[uid]
            qp.attempts += 1
            self._untrack_gang_member(qp.pod)  # in-flight, no longer pending
            self.admission.admit(tenant, uid, now, escape)
            out.append(qp)
        return out

    # -- failure / backoff -----------------------------------------------------

    def backoff_duration(self, attempts: int) -> float:
        d = self.initial_backoff_s
        for _ in range(1, attempts):
            d *= 2
            if d >= self.max_backoff_s:
                return self.max_backoff_s
        return d

    def _unsched_insert(self, qp: QueuedPodInfo) -> None:
        # Idempotent under re-classification: a uid already pooled under a
        # different rejecting-plugin set must leave its old class index.
        if qp.pod.uid in self._unschedulable:
            self._unsched_remove(qp.pod.uid)
        self._unschedulable[qp.pod.uid] = qp
        ck = (
            frozenset(qp.unschedulable_plugins)
            if qp.unschedulable_plugins
            else None,
            qp.delta is None,
        )
        qp.unsched_class = ck
        self._unsched_classes.setdefault(ck, {})[qp.pod.uid] = qp
        self._unsched_req_cache.pop(ck, None)

    def _unsched_remove(self, uid: str) -> QueuedPodInfo | None:
        qp = self._unschedulable.pop(uid, None)
        if qp is None:
            return None
        pool = self._unsched_classes.get(qp.unsched_class)
        if pool is not None:
            pool.pop(uid, None)
            if not pool:
                self._unsched_classes.pop(qp.unsched_class, None)
        self._unsched_req_cache.pop(qp.unsched_class, None)
        return qp

    def add_unschedulable(self, qp: QueuedPodInfo, plugins: set[str]) -> None:
        """AddUnschedulableIfNotPresent (scheduling_queue.go:728): pods that
        failed go to the unschedulable pool keyed by what rejected them.
        Members of a registered gang park in the gang pool instead."""
        qp.unschedulable_plugins = plugins
        if self.tenant_note is not None:
            self.tenant_note("deferred", qp.pod)
        g = qp.pod.spec.pod_group
        if g:
            self._track_gang_member(qp)
            if g in self.gang_min:
                self._park_gang_member(qp)
                return
        self._unsched_insert(qp)

    def add_backoff(self, qp: QueuedPodInfo) -> None:
        if self.tenant_note is not None:
            self.tenant_note("deferred", qp.pod)
        expiry = self._clock() + self.backoff_duration(qp.attempts)
        heapq.heappush(self._backoff, (expiry, next(self._seq), qp.pod.uid))

    def restore_backoff(self, qp: QueuedPodInfo) -> None:
        """Re-own a pod released with done() (e.g. from an off-queue wait
        room) and park it behind backoff — restores the info entry
        done() dropped, like reactivate does for the active queue."""
        self._info[qp.pod.uid] = qp
        self.add_backoff(qp)

    def next_backoff_expiry(self) -> float | None:
        """Earliest backoff expiry, or None when the backoffQ is empty."""
        return self._backoff[0][0] if self._backoff else None

    def sleep_until_backoff(self) -> bool:
        """Sleep until the earliest backoff expires.  Returns False when
        there is nothing to wait for — including under an injected test
        clock, which wall-clock sleeping can never advance."""
        expiry = self.next_backoff_expiry()
        if expiry is None or self._clock is not time.monotonic:
            return False
        time.sleep(max(0.0, expiry - self._clock()) + 1e-3)
        return True

    def flush_backoff(self) -> int:
        """Move expired backoff pods to activeQ (flushBackoffQCompleted :777)."""
        now = self._clock()
        n = 0
        while self._backoff and self._backoff[0][0] <= now:
            _, _, uid = heapq.heappop(self._backoff)
            qp = self._info.get(uid)
            # A stale heap entry must not spring a quarantined pod (a
            # restored snapshot can hold a pod in backoff that a later
            # journal record moved to quarantine).
            if qp is not None and uid not in self._quarantine:
                self._push_active(qp)
                n += 1
        return n

    def flush_unschedulable_leftover(self) -> int:
        """Re-activate pods stuck unschedulable > max duration (:807).
        Stale parked gangs get a re-admission attempt too."""
        now = self._clock()
        stale = [
            uid
            for uid, qp in self._unschedulable.items()
            if now - qp.timestamp > self.max_unschedulable_s
        ]
        for uid in stale:
            self._push_active(self._unsched_remove(uid))
        n = len(stale)
        for g in list(self._gang_pool):
            if any(
                now - qp.timestamp > self.max_unschedulable_s
                for qp in self._gang_pool[g].values()
            ) and self._try_admit_gang(g):
                n += 1
        return n

    # -- events ----------------------------------------------------------------

    def _requeue_verdict(self, qp: QueuedPodInfo, event: Event, ctx: EventCtx | None):
        """isPodWorthRequeuing (scheduling_queue.go:406), three-valued: the
        pod requeues when ANY plugin that rejected it (a) registered for
        this event kind and (b) — when an object-aware hint and event
        payload exist — says the event object could actually unblock it.
        Returns True/False, or 'fit' when the only deciding hint is the
        fit hint with a usable payload — the caller batches those into one
        vectorized check (a preemption burst scans a 15k-pod pool per
        POD_DELETE; per-pod Python was ~20% of the measured window)."""
        defer_fit = False
        for pl in qp.unschedulable_plugins or {"NodeResourcesFit"}:
            if not (PLUGIN_REQUEUE_EVENTS.get(pl, Event.ANY) & event):
                continue
            hint = PLUGIN_HINTS.get(pl) if self.use_queueing_hints else None
            if hint is None or ctx is None:
                return True
            if hint is _fit_hint and qp.delta is not None and ctx.max_free is not None:
                defer_fit = True
                continue
            if hint(qp, event, ctx):
                return True
        return "fit" if defer_fit else False

    def _worth_requeuing(self, qp: QueuedPodInfo, event: Event, ctx: EventCtx | None) -> bool:
        v = self._requeue_verdict(qp, event, ctx)
        if v == "fit":
            return _fit_hint(qp, event, ctx)
        return v

    def _class_reqs(self, ck: tuple) -> tuple[list, np.ndarray]:
        """(uids, packed request matrix) for one verdict class, cached
        until the class's membership changes (insert/remove invalidate)."""
        cached = self._unsched_req_cache.get(ck)
        if cached is None:
            pool = self._unsched_classes.get(ck, {})
            uids = list(pool)
            cached = (uids, _pack_reqs([pool[u].delta["req"] for u in uids]))
            self._unsched_req_cache[ck] = cached
        return cached

    def on_event(self, event: Event, ctx: EventCtx | None = None) -> int:
        """MoveAllToActiveOrBackoffQueue (scheduling_queue.go:1029): wake
        unschedulable pods whose rejecting plugins care about this event
        (filtered through the object-aware hints when ``ctx`` is given)."""
        woken: list[str] = []
        # The verdict depends only on (rejecting plugins, delta presence)
        # as long as every registered hint is the BATCHED fit hint — one
        # verdict per CLASS over the maintained index instead of a Python
        # walk of the pool (a preemption burst scans a 15k-pod pool per
        # POD_DELETE; the per-pod verdict walk was ~15% of the
        # preemption-async measured window), and the fit classes check one
        # cached request matrix per class in a single vectorized compare.
        if all(h is _fit_hint for h in PLUGIN_HINTS.values()):
            for ck in list(self._unsched_classes):
                pool = self._unsched_classes.get(ck)
                if not pool:
                    continue
                rep = next(iter(pool.values()))
                verdict = self._requeue_verdict(rep, event, ctx)
                if verdict is True:
                    woken.extend(pool)
                elif verdict == "fit":
                    uids, reqm = self._class_reqs(ck)
                    fits = _fits_packed(reqm, ctx)
                    woken.extend(u for u, ok in zip(uids, fits) if ok)
        else:
            # Custom hints registered: per-pod verdicts, but the fit checks
            # still batch into one vectorized compare (the per-pod numpy
            # path costs ~0.5s per event over a 15k-pod pool).
            fit_uids: list[str] = []
            fit_reqs: list[np.ndarray] = []
            for uid, qp in self._unschedulable.items():
                verdict = self._requeue_verdict(qp, event, ctx)
                if verdict is True:
                    woken.append(uid)
                elif verdict == "fit":
                    fit_uids.append(uid)
                    fit_reqs.append(qp.delta["req"])
            if fit_uids:
                fits = _fits_free(fit_reqs, ctx)
                woken.extend(u for u, ok in zip(fit_uids, fits) if ok)
        for uid in woken:
            qp = self._unsched_remove(uid)
            if qp is not None:
                self.add_backoff(qp)
        # Parked gangs re-try when an event the gang cares about fires —
        # membership changes (the GangScheduling mask) OR anything the
        # members' own rejecting plugins wait on (a gang blocked by taints
        # wakes on the taint removal, like a solo pod would).  Re-admission
        # goes through backoff (the gang already failed once as-is).
        for g in list(self._gang_pool):
            interested = PLUGIN_REQUEUE_EVENTS["GangScheduling"]
            for qp in self._gang_pool[g].values():
                for pl in qp.unschedulable_plugins:
                    interested |= PLUGIN_REQUEUE_EVENTS.get(pl, Event.ANY)
            if interested & event and self._try_admit_gang(g, via_backoff=True):
                woken.append(g)
        return len(woken)

    def update(self, pod: t.Pod) -> None:
        """updatePodInSchedulingQueue (eventhandlers.go:136): refresh the
        queued object; a scheduling-relevant change (labels, spec) to an
        unschedulable pod may have made it schedulable — move it straight to
        activeQ (the reference's isPodUpdated → queue.Update path).  Pods in
        activeQ/backoffQ just get the fresher object."""
        qp = self._info.get(pod.uid)
        if qp is None:
            self.add(pod)
            return
        changed = (
            qp.pod.metadata.labels != pod.metadata.labels
            or qp.pod.spec != pod.spec
        )
        qp.pod = pod
        if pod.uid in self._quarantine:
            # A spec/label change invalidates the poison featurization:
            # give the pod another chance, behind backoff (its attempt
            # count keeps the retry rate bounded if it is still poison).
            if changed:
                self.release_quarantine(pod.uid)
            return
        if qp.gated and not pod.spec.scheduling_gates:
            self.remove_gate(pod.uid)
            return
        if changed:
            qp.delta = None  # featurization delta is stale
            if pod.uid in self._unschedulable:
                self._push_active(qp)

    def remove_gate(self, uid: str) -> None:
        """A pod's scheduling gates were cleared; admit it."""
        qp = self._gated.pop(uid, None)
        if qp is not None:
            qp.gated = False
            self._push_active(qp)

    def delete(self, uid: str) -> None:
        self._in_active.discard(uid)
        if self.admission is not None:
            # A deleted pod's enqueue stamp must not keep holding the
            # tenant's aging escape open (its heap entry goes stale and
            # drops lazily at the next pop).
            self.admission.forget(uid)
        self._unsched_remove(uid)
        self._gated.pop(uid, None)
        self._quarantine.pop(uid, None)
        qp = self._info.pop(uid, None)
        if qp is not None and qp.pod.spec.pod_group:
            self._untrack_gang_member(qp.pod)
            pool = self._gang_pool.get(qp.pod.spec.pod_group)
            if pool is not None:
                pool.pop(uid, None)
                if not pool:
                    self._gang_pool.pop(qp.pod.spec.pod_group, None)

    def done(self, uid: str) -> None:
        """Pod scheduled successfully; drop bookkeeping."""
        self._info.pop(uid, None)

    # -- durability (journal.py snapshot surface) ------------------------------

    def durable_state(self) -> dict:
        """Serialize every queued pod for a journal snapshot.  Clocks are
        RELATIVE (backoff remaining, age since first enqueue): monotonic
        timestamps don't survive a process, so restore_state rebases them
        on the restoring process's clock — a pod 3s into a 10s backoff
        resumes with ~7s left, not a reset."""
        from .api import serialize

        now = self._clock()
        backoff_left: dict[str, float] = {}
        for exp, _seq, uid in self._backoff:
            left = max(0.0, exp - now)
            # Duplicate heap entries: keep the earliest expiry (the one
            # flush_backoff would honor first).
            if uid not in backoff_left or left < backoff_left[uid]:
                backoff_left[uid] = left
        entries: list[dict] = []
        seen: set[str] = set()

        def ent(qp: QueuedPodInfo, pool: str, **extra) -> None:
            if qp.pod.uid in seen:
                return
            seen.add(qp.pod.uid)
            entries.append(
                {
                    "pod": serialize.to_dict(qp.pod),
                    "pool": pool,
                    "attempts": qp.attempts,
                    "age": max(0.0, now - qp.initial_attempt_timestamp),
                    "plugins": sorted(qp.unschedulable_plugins),
                    **extra,
                }
            )

        for uid, qp in self._quarantine.items():
            ent(qp, "quarantine")
        for uid, qp in self._gated.items():
            ent(qp, "gated")
        for uid, qp in self._unschedulable.items():
            ent(qp, "unschedulable")
        for pool in self._gang_pool.values():
            for qp in pool.values():
                ent(qp, "gang")
        if self.admission is not None:
            # In-flight pops whose debits are not yet group-committed:
            # presumed-aborted on recovery, so they re-enter ACTIVE at
            # the FRONT in pop order — the restored WFQ ledger predates
            # their debits and re-selects them exactly as the
            # interrupted run did.  If the crash DID leave their group
            # durable, replay supersedes this entry: a bind record's
            # bound upsert deletes the queue entry (scheduler.add_pod),
            # and a surviving admission record consumes it through the
            # preadmitted drain ahead of any fresh selection.
            for uid in self.admission.pending_intents():
                qp = self._info.get(uid)
                if qp is not None and uid not in self._in_active:
                    ent(qp, "active")
        # Active pods emit in QueueSort heap order, NOT set order: the
        # restorer re-pushes entries in document order with fresh seqs and
        # one shared timestamp, so the stored order IS the recovered pop
        # order — iterating the _in_active set here would bake one
        # process's hash order into the snapshot and scramble the armed
        # per-tenant heads (the tenant kill cells catch this).
        live = (
            [it for h in self._tenant_active.values() for it in h]
            if self.admission is not None
            else list(self._active)
        )
        for item in sorted(live):
            if item[3] in self._in_active:
                ent(self._info[item[3]], "active")
        for uid in sorted(self._in_active):  # heap-orphan backstop
            ent(self._info[uid], "active")
        for uid, left in backoff_left.items():
            qp = self._info.get(uid)
            if qp is not None:
                ent(qp, "backoff", backoff_remaining_s=round(left, 6))
        out = {
            "entries": entries,
            # Already trimmed to the trailing window (bounded deque):
            # the snapshot can never grow with the release stream.
            # Clocks rebase as ages (like backoff remaining-seconds) —
            # raw monotonic stamps are meaningless in the next process.
            "release_history": [
                {
                    "uid": e["uid"],
                    "attempts": e["attempts"],
                    "age_s": round(max(0.0, now - e["ts"]), 3),
                }
                for e in self.release_history
            ],
        }
        if self.admission is not None:
            # The DURABLE fairness ledger (WFQ tags, credit balances,
            # per-tenant attempts, rebased enqueue stamps): snapshot +
            # journaled "admission" records replay the exact selection
            # state, so recovery admits in the identical order.
            out["admission"] = self.admission.durable_state()
        return out

    def restore_state(self, state: dict) -> int:
        """Rebuild the pools from a durable_state() document (recovery).
        Pods already present — bound pods the snapshot's store section
        restored first, say — are skipped; gang members re-park through
        the normal admission machinery so quorum logic stays live."""
        from .api import serialize

        now = self._clock()
        # Admission restores FIRST: the pod entries below re-enter through
        # _push_active → note_enqueue, which keeps an already-present
        # (rebased) stamp — accumulated starvation wait survives the crash.
        adm = state.get("admission")
        if adm is not None and self.admission is not None:
            self.admission.restore_state(adm)
        n = 0
        for e in state.get("entries", ()):
            pod = serialize.pod_from_data(e["pod"])
            uid = pod.uid
            if uid in self._info or uid in self._quarantine:
                continue
            qp = QueuedPodInfo(
                pod=pod,
                timestamp=now,
                initial_attempt_timestamp=now - float(e.get("age", 0.0)),
                attempts=int(e.get("attempts", 0)),
                unschedulable_plugins=set(e.get("plugins", ())),
            )
            self._info[uid] = qp
            pool = e.get("pool", "active")
            if pool == "quarantine":
                qp.unschedulable_plugins = qp.unschedulable_plugins or {
                    "EngineFault"
                }
                self._quarantine[uid] = qp
            elif pool == "gated":
                qp.gated = True
                self._gated[uid] = qp
            elif pool == "unschedulable":
                if pod.spec.pod_group:
                    self._track_gang_member(qp)
                self._unsched_insert(qp)
            elif pool == "gang":
                self._park_gang_member(qp)
            elif pool == "backoff":
                if pod.spec.pod_group:
                    self._track_gang_member(qp)
                heapq.heappush(
                    self._backoff,
                    (
                        now + float(e.get("backoff_remaining_s", 0.0)),
                        next(self._seq),
                        uid,
                    ),
                )
            else:
                if pod.spec.pod_group:
                    self._track_gang_member(qp)
                self._push_active(qp)
            n += 1
        # The release-history window survives restarts (its ring bound
        # applies on restore too — an over-long stored list trims).
        # Stored ages rebase onto this process's clock; a raw "ts" from
        # an in-process ring copy passes through unchanged.
        for rec in state.get("release_history", ()):
            e = dict(rec)
            if "age_s" in e:
                e["ts"] = round(now - e.pop("age_s"), 3)
            self.release_history.append(e)
        # Parked gangs whose quorum is already reachable release now (a
        # restart must not strand a quorum-complete gang).
        for g in list(self._gang_pool):
            self._try_admit_gang(g)
        return n

    def depths(self) -> dict[str, int]:
        """Per-class queue depths — the scheduler_pending_pods{queue=…}
        gauge payload (metrics.go:121 PendingPods) and the dump's counts.
        Label values match the reference's queue names where one exists."""
        return {
            "active": len(self._in_active),
            "backoff": len(self._backoff),
            "unschedulable": len(self._unschedulable),
            "gated": len(self._gated),
            "gang-parked": sum(len(p) for p in self._gang_pool.values()),
            "quarantine": len(self._quarantine),
        }

    def dump(self) -> dict:
        """Queue state for the debugger dump (keeps the privates here)."""
        d = self.depths()
        return {
            "active": d["active"],
            "backoff": d["backoff"],
            "pending": self.pending_count(),
            "unschedulable": d["unschedulable"],
            "gated": d["gated"],
            "gang_pool": {g: sorted(p) for g, p in self._gang_pool.items()},
            "quarantine": sorted(self._quarantine),
        }
