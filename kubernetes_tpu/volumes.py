"""Volume catalog: host-side PV/PVC/StorageClass/CSINode state + binding.

The host half of the volume plugins (reference:
plugins/volumebinding/binder.go FindPodVolumes/AssumePodVolumes,
volumezone, nodevolumelimits).  String/object matching stays on the host;
the device ops consume compiled requirement programs and per-node count
tensors produced from this catalog.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from .api import types as t

# Zone/region label keys a PV may carry (volumezone/volume_zone.go
# topologyLabels; both GA and legacy beta names).
ZONE_KEYS = (
    "topology.kubernetes.io/zone",
    "failure-domain.beta.kubernetes.io/zone",
)
REGION_KEYS = (
    "topology.kubernetes.io/region",
    "failure-domain.beta.kubernetes.io/region",
)

NO_PROVISIONER = "kubernetes.io/no-provisioner"


@dataclass
class VolumeCatalog:
    pvs: dict[str, t.PersistentVolume] = field(default_factory=dict)
    pvcs: dict[str, t.PersistentVolumeClaim] = field(default_factory=dict)
    classes: dict[str, t.StorageClass] = field(default_factory=dict)
    csinodes: dict[str, t.CSINode] = field(default_factory=dict)
    # PVC uid → number of pods using it (for ReadWriteOncePod conflicts,
    # volumerestrictions/volume_restrictions.go).
    pvc_users: dict[str, int] = field(default_factory=dict)
    # Bumped on every catalog mutation; featurization caches key on it so a
    # PV/PVC/class change invalidates cached pod features.
    epoch: int = 0
    # storage class → {pv name: pv} of UNBOUND static PVs: candidates_for
    # was an O(all PVs) scan per call (~2s of a 5k-pod CSI workload);
    # maintained at exactly the claim_ref mutation sites.  Also the
    # chunk-conflict gate (class_has_static_candidates): only a finite PV
    # pool makes same-batch PreBinds race.
    unbound: dict[str, dict[str, "t.PersistentVolume"]] = field(
        default_factory=dict
    )
    # WFFC dynamic provisioning mode.  "sync" models an instantaneous
    # provisioner (the PreBind creates the PV in-process — the round-3
    # behavior, right for self-contained benchmarks).  "wait" mirrors the
    # reference (volume_binding.go:521 BindPodVolumes): PreBind writes a
    # provisioning INTENT (the volume.kubernetes.io/selected-node
    # annotation trigger) and the bind completes only when the external
    # provisioner's PV arrives via add_pv, or times out and unreserves.
    wffc_provisioning: str = "sync"
    # pvc uid → selected node name, while a provisioning intent is open.
    provisioning: dict[str, str] = field(default_factory=dict)

    # -- object events -------------------------------------------------------

    def add_pv(self, pv: t.PersistentVolume) -> list[str]:
        """Upsert a PV (informer).  Returns the uids of PVCs whose open
        provisioning intent this PV fulfils (the provisioner created the
        volume pre-bound via claimRef) — the scheduler completes their
        waiting PreBinds."""
        old = self.pvs.get(pv.name)
        if old is not None and not old.claim_ref:
            self.unbound.get(old.storage_class, {}).pop(old.name, None)
        self.pvs[pv.name] = pv
        if not pv.claim_ref:
            self.unbound.setdefault(pv.storage_class, {})[pv.name] = pv
        self.epoch += 1
        fulfilled: list[str] = []
        if pv.claim_ref and pv.claim_ref in self.provisioning:
            pvc = self.pvcs.get(pv.claim_ref)
            if pvc is not None and not pvc.volume_name:
                pvc.volume_name = pv.name
                del self.provisioning[pv.claim_ref]
                fulfilled.append(pvc.uid)
        return fulfilled

    def class_has_static_candidates(self, storage_class: str) -> bool:
        """Any unclaimed static PV in this class?  (Chunk-conflict gate:
        only a finite PV pool makes same-batch PreBinds race.)"""
        return bool(self.unbound.get(storage_class))

    def add_pvc(self, pvc: t.PersistentVolumeClaim) -> None:
        self.pvcs[pvc.uid] = pvc
        self.epoch += 1

    def add_class(self, sc: t.StorageClass) -> None:
        self.classes[sc.name] = sc
        self.epoch += 1

    def add_csinode(self, csinode: t.CSINode) -> None:
        self.csinodes[csinode.name] = csinode
        self.epoch += 1

    def adjust_pvc_users(self, pvc_uids: list[str], delta: int) -> None:
        for uid in pvc_uids:
            self.pvc_users[uid] = self.pvc_users.get(uid, 0) + delta
        if pvc_uids:
            self.epoch += 1

    # -- pod classification --------------------------------------------------

    def pod_pvcs(self, pod: t.Pod) -> list[t.PersistentVolumeClaim | None]:
        """The pod's claims (None for dangling references)."""
        out = []
        for vol in pod.spec.volumes:
            if vol.pvc:
                out.append(self.pvcs.get(f"{pod.namespace}/{vol.pvc}"))
        return out

    def classify(self, pvc: t.PersistentVolumeClaim):
        """→ ("bound", pv) | ("delayed", candidates, sc) |
        ("unbound_immediate", None) | ("lost", None).

        Mirrors volume_binding.go: bound claims resolve their PV; unbound
        claims with a WaitForFirstConsumer class bind at schedule time
        (candidates = matching unbound PVs, dynamic provisioning as
        fallback); unbound Immediate claims are UnschedulableAndUnresolvable
        until the PV controller binds them."""
        if pvc.volume_name:
            pv = self.pvs.get(pvc.volume_name)
            return ("bound", pv) if pv is not None else ("lost", None)
        sc = self.classes.get(pvc.storage_class)
        if sc is not None and sc.binding_mode == t.BINDING_WAIT_FOR_FIRST_CONSUMER:
            return ("delayed", self.candidates_for(pvc), sc)
        return ("unbound_immediate", None)

    def candidates_for(self, pvc: t.PersistentVolumeClaim) -> list[t.PersistentVolume]:
        """Static PVs this claim could bind (class, access modes, size —
        volumebinding's PV matching, persistentvolume/util.go FindMatchingVolume)."""
        out = []
        for pv in self.unbound.get(pvc.storage_class, {}).values():
            if not set(pvc.access_modes) <= set(pv.access_modes):
                continue
            if pv.capacity < pvc.request:
                continue
            out.append(pv)
        return out

    def pvc_driver(self, pvc: t.PersistentVolumeClaim) -> str:
        """CSI driver for attach-limit counting (nodevolumelimits/csi.go):
        bound → PV's driver; unbound → the class's provisioner."""
        if pvc.volume_name:
            pv = self.pvs.get(pvc.volume_name)
            if pv is not None and pv.csi_driver:
                return pv.csi_driver
            return ""
        sc = self.classes.get(pvc.storage_class)
        if sc is not None and sc.provisioner != NO_PROVISIONER:
            return sc.provisioner
        return ""

    # -- zone requirements (VolumeZone) -------------------------------------

    @staticmethod
    def zone_requirements(pv: t.PersistentVolume) -> list[t.NodeSelectorRequirement]:
        """A bound PV's zone/region labels as node requirements.  Label
        values may be ``__``-separated sets (volumehelpers.LabelZonesToSet)."""
        reqs = []
        for key in ZONE_KEYS + REGION_KEYS:
            v = pv.labels.get(key)
            if v is not None:
                reqs.append(
                    t.NodeSelectorRequirement(key, t.OP_IN, tuple(v.split("__")))
                )
        return reqs

    # -- bind (the PreBind step) --------------------------------------------

    def bind_pod_volumes(self, pod: t.Pod, node: t.Node) -> list | None:
        """Bind the pod's delayed claims on the chosen node (the in-process
        analog of volumebinding PreBind, volume_binding.go:521).  Returns
        None when a claim can no longer be satisfied there (a same-batch
        race lost) — the caller forgets the pod (assume/forget protocol) —
        else a list of undo records for ``unbind_pod_volumes`` (a gang whose
        Permit admission later collapses must revert its members' binds)."""
        chosen: list[tuple[t.PersistentVolumeClaim, t.PersistentVolume | None]] = []
        own_refs: dict[str, int] = {}
        for vol in pod.spec.volumes:
            if vol.pvc:
                uid = f"{pod.namespace}/{vol.pvc}"
                own_refs[uid] = own_refs.get(uid, 0) + 1
        for pvc in self.pod_pvcs(pod):
            if pvc is None:
                return None
            # Re-check ReadWriteOncePod here: a same-batch peer may have
            # assumed the claim after this pod was featurized (the pod's own
            # assume already counted its references).
            if t.RWOP in pvc.access_modes:
                others = self.pvc_users.get(pvc.uid, 0) - own_refs.get(pvc.uid, 0)
                if others > 0:
                    return None
            kind, *_rest = self.classify(pvc)
            if kind in ("bound",):
                continue
            if kind in ("lost", "unbound_immediate"):
                return None
            sc = self.classes.get(pvc.storage_class)
            cands = [
                pv
                for pv in self.candidates_for(pvc)
                if t.node_selector_matches(
                    pv.node_affinity, node.metadata.labels, node.name
                )
            ]
            if cands:
                # Smallest satisfying PV (FindMatchingVolume picks the
                # smallest that fits).
                pv = min(cands, key=lambda p: p.capacity)
                chosen.append((pvc, pv))
            elif sc is not None and sc.provisioner != NO_PROVISIONER:
                ok = sc.allowed_topologies is None or t.node_selector_matches(
                    sc.allowed_topologies, node.metadata.labels, node.name
                )
                if not ok:
                    return None
                chosen.append((pvc, None))  # dynamically provisioned
            else:
                return None
        undo: list[tuple[str, t.PersistentVolumeClaim, str]] = []
        for pvc, pv in chosen:
            if pv is None:
                if self.wffc_provisioning == "wait":
                    # The provisioning trigger (AssumePodVolumes + the
                    # selected-node annotation): the claim stays unbound
                    # until the provisioner's PV lands (add_pv) or the
                    # PreBind wait times out.
                    self.provisioning[pvc.uid] = node.name
                    self.epoch += 1
                    undo.append(("intent", pvc, node.name))
                    continue
                name = f"provisioned-{pvc.namespace}-{pvc.name}"
                self.add_pv(
                    t.PersistentVolume(
                        name=name,
                        capacity=pvc.request,
                        access_modes=pvc.access_modes,
                        storage_class=pvc.storage_class,
                        claim_ref=pvc.uid,
                        csi_driver=self.pvc_driver(pvc),
                    )
                )
                pvc.volume_name = name
                undo.append(("provisioned", pvc, name))
            else:
                pv.claim_ref = pvc.uid
                pvc.volume_name = pv.name
                self.unbound.get(pv.storage_class, {}).pop(pv.name, None)
                self.epoch += 1
                undo.append(("static", pvc, pv.name))
        return undo

    def unbind_pod_volumes(self, undo: list) -> None:
        """Revert a bind_pod_volumes (gang Permit collapse after PreBind):
        release static PVs, delete phantom provisioned PVs."""
        for kind, pvc, pv_name in undo:
            if kind == "intent":
                # Withdraw the provisioning trigger; a PV the provisioner
                # already delivered stays in the catalog (the claim keeps
                # its binding — rebinding elsewhere later is a no-op race
                # the classify() bound path resolves).
                if not pvc.volume_name:
                    self.provisioning.pop(pvc.uid, None)
                continue
            pvc.volume_name = ""
            if kind == "provisioned":
                self.pvs.pop(pv_name, None)
            else:
                pv = self.pvs.get(pv_name)
                if pv is not None:
                    pv.claim_ref = None
                    self.unbound.setdefault(pv.storage_class, {})[pv.name] = pv
        if undo:
            self.epoch += 1
