"""Batched preemption: the PostFilter dry-run as one device pass.

Reference (framework/preemption/preemption.go + plugins/defaultpreemption/):
the evaluator clones the snapshot per candidate node, removes lower-priority
pods most-important-last (SelectVictimsOnNode sorts by MoreImportantPod and
reprieves most-important-first, :541 DryRunPreemption), and picks the winner
by five lexicographic criteria (:424 pickOneNodeForPreemption — fewest PDB
violations → lowest max victim priority → smallest victim priority sum →
fewest victims → latest earliest victim start time).

TPU design: both parallel axes of the reference map onto one dispatch — the
candidate-node axis is the device vector axis, and the queue of failed pods
becomes a `lax.scan` whose carry commits each preemption's resource release
before the next preemptor looks (mirroring the scheduling pass).  The host
packs every node's pods sorted least-important-first (priority asc,
start-time desc) into (N, V) tensors once per batch; each scan step masks the
entries below its own preemptor's priority, prefix-sums their releases, finds
the minimal fitting prefix k*(n) per node, excludes nodes any unresolvable
filter rejects (the UnschedulableAndUnresolvable analog, :216), and reduces
the pick criteria as masked argmins.  Chosen victims are marked consumed in
the carried tensors so later preemptors in the batch cannot double-claim
them.  Unlike the reference, which dry-runs only a rotating percentage of
candidates, the full node axis is evaluated.

Divergence (documented): victim selection takes the minimal fitting PREFIX
of the least-important-first list, whereas the reference's
SelectVictimsOnNode greedily reprieves most-important-first and can keep a
non-contiguous subset — for multi-resource fits the prefix rule may evict a
different (never smaller-priority-first) set.  Also, the in-scan fit check
releases resources and pod slots only; port/anti-affinity release is not
re-simulated.  Two effects:
a nomination may still fail the next full filter pass (the retry then runs
with the victims actually gone, matching the reference's post-deletion
behavior), and — the false-negative direction — a node whose only failure
is a resolvable non-resource conflict (a victim's host port or anti-affinity
pair) is never nominated, because zero victims are needed resource-wise.
Full-filter dry-run over victim prefixes closes that gap in a later round.
PDB violation counting arrives with the disruption controller (criterion 1
is currently a constant 0).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .api import types as t
from .framework.config import Profile
from .ops import common as opcommon
from .snapshot import Schema, _bucket

I32_MAX = np.int32(2**31 - 1)


@dataclass
class PreemptionResult:
    node_name: str
    victims: list[t.Pod]


class PreemptStep(NamedTuple):
    picks: jax.Array  # (K,) i32 node row, -1 = no candidate
    k_star: jax.Array  # (K,) i32 prefix length at the picked node
    n_victims: jax.Array  # (K,) i32 victims inside that prefix


def build_preempt_pass(
    profile: Profile,
    schema: Schema,
    builder_res_col,
    active: frozenset[str] | None = None,
):
    """Compile the scan-over-preemptors dry-run for one (profile, schema,
    active-op-set) — the active set must match the scheduling batch whose
    feature rows feed this pass."""
    filter_ops = [
        opcommon.get(n)
        for n in profile.filters
        if active is None or n in active
    ]
    static: dict = {}
    for op in {o.name: o for o in filter_ops}.values():
        if op.static is not None:
            static.update(op.static(profile, schema, builder_res_col))
    ctx = opcommon.PassContext(profile=profile, schema=schema, static=static)

    def step(carry, pf, dctx):
        state, vic_prio, vic_req, vic_nonzero, vic_start = carry
        # Candidate nodes: valid and not unresolvably rejected.
        candidate = state.valid
        for op in filter_ops:
            if op.hard_filter is not None:
                candidate &= ~op.hard_filter(state, pf, dctx)

        n, v = vic_prio.shape
        prio = pf["priority"].astype(jnp.int32)
        lower = vic_prio < prio  # (N, V) — consumed victims carry I32_MAX
        rel = jnp.cumsum(jnp.where(lower[:, :, None], vic_req, 0), axis=1)
        rel = jnp.concatenate(
            [jnp.zeros((n, 1, rel.shape[2]), rel.dtype), rel], axis=1
        )  # (N, V+1, R)
        rel_nz = jnp.cumsum(jnp.where(lower[:, :, None], vic_nonzero, 0), axis=1)
        rel_nz = jnp.concatenate(
            [jnp.zeros((n, 1, 2), rel_nz.dtype), rel_nz], axis=1
        )
        n_lower = jnp.cumsum(lower.astype(jnp.int32), axis=1)
        n_lower = jnp.concatenate([jnp.zeros((n, 1), jnp.int32), n_lower], axis=1)

        demand = pf["req"]  # (R,)
        free = state.alloc[:, None, :] - (state.req[:, None, :] - rel)
        fits_res = ((demand[None, None, :] == 0) | (demand[None, None, :] <= free)).all(-1)
        ks = jnp.arange(v + 1)[None, :]
        fits_cnt = state.num_pods[:, None] - n_lower + 1 <= state.allowed_pods[:, None]
        fits = fits_res & fits_cnt & (ks <= v)

        k_star = jnp.argmax(fits, axis=1)
        any_fit = fits.any(axis=1)
        n_vic = jnp.take_along_axis(n_lower, k_star[:, None], axis=1)[:, 0]
        # At least one victim, else deletion can't be what fixes this node.
        possible = candidate & any_fit & (n_vic >= 1) & pf["valid"]

        idx = jnp.maximum(k_star - 1, 0)

        # Running (max victim priority, earliest start AMONG those
        # max-priority victims) — criterion 5 compares the highest-priority
        # victims' start times only (GetEarliestPodStartTime,
        # preemption.go pickOneNodeForPreemption).
        def _combine(a, b):
            ap, as_ = a
            bp, bs = b
            p = jnp.maximum(ap, bp)
            s = jnp.where(
                ap == bp,
                jnp.minimum(as_, bs),
                jnp.where(ap > bp, as_, bs),
            )
            return p, s

        run_max_prio, run_start = lax.associative_scan(
            _combine,
            (
                jnp.where(lower, vic_prio, -1),
                jnp.where(lower, vic_start, jnp.inf),
            ),
            axis=1,
        )
        max_prio = jnp.take_along_axis(run_max_prio, idx[:, None], axis=1)[:, 0]
        prio_sum = jnp.take_along_axis(
            jnp.cumsum(jnp.where(lower, vic_prio, 0).astype(jnp.int64), axis=1),
            idx[:, None], axis=1,
        )[:, 0]
        run_min_start = jnp.take_along_axis(run_start, idx[:, None], axis=1)[:, 0]

        big = jnp.int64(2**62)

        def narrow(mask, key):
            best = jnp.min(jnp.where(mask, key, big))
            return mask & (key == best)

        mask = possible
        mask = narrow(mask, max_prio.astype(jnp.int64))
        mask = narrow(mask, prio_sum)
        mask = narrow(mask, n_vic.astype(jnp.int64))
        # Latest earliest-start wins: minimize the negated key, in
        # microseconds so sub-second differences survive the int cast.
        start_key = jnp.where(
            jnp.isfinite(run_min_start), -run_min_start * 1e6, -jnp.float64(2**61)
        ).astype(jnp.int64)
        mask = narrow(mask, start_key)
        pick = jnp.argmax(mask).astype(jnp.int32)
        do = possible.any()
        pick = jnp.where(do, pick, -1)
        row = jnp.maximum(pick, 0)
        kp = jnp.where(do, k_star[row], 0)

        # Commit: release the chosen prefix's resources and consume victims.
        chosen = (jnp.arange(v)[None, :] < kp) & lower[row][None, :] & do
        rel_vec = jnp.where(do, rel[row, kp], 0)
        rel_nz_vec = jnp.where(do, rel_nz[row, kp], 0)
        nvic = jnp.where(do, n_vic[row], 0)
        state = dataclasses.replace(
            state,
            req=state.req.at[row].add(-rel_vec),
            nonzero_req=state.nonzero_req.at[row].add(-rel_nz_vec),
            num_pods=state.num_pods.at[row].add(-nvic),
        )
        vic_prio = vic_prio.at[row].set(
            jnp.where(chosen[0], I32_MAX, vic_prio[row])
        )
        out = PreemptStep(
            picks=pick, k_star=kp.astype(jnp.int32), n_victims=nvic.astype(jnp.int32)
        )
        return (state, vic_prio, vic_req, vic_nonzero, vic_start), out

    @jax.jit
    def run(state, batch, inv, vic_prio, vic_req, vic_nonzero, vic_start):
        # Domain tables for the hard filters (e.g. InterPodAffinity's
        # required-affinity check).  The dry-run carry releases resources
        # only — group/term counts never change — so one build at entry
        # serves every scan step (engine/pass_.py build_dom).
        from .engine.pass_ import build_dom

        dom = build_dom(state, inv["et_slot"], inv["et_host"], schema.DV)
        dctx = dataclasses.replace(ctx, dom=dom)
        carry = (state, vic_prio, vic_req, vic_nonzero, vic_start)
        carry, out = lax.scan(lambda c, pf: step(c, pf, dctx), carry, batch)
        return out

    return run


class PreemptionEvaluator:
    """Host driver: packs victim tensors once per failed batch, runs the
    scan, applies the chosen victims (prepareCandidate, preemption.go:342)."""

    def __init__(self, scheduler) -> None:
        self.sched = scheduler
        self._cache: dict = {}

    def _pass(self, active: frozenset[str] | None):
        b = self.sched.builder
        key = (self.sched.profile, b.schema, tuple(sorted(b.res_col.items())), active)
        fn = self._cache.get(key)
        if fn is None:
            fn = build_preempt_pass(self.sched.profile, b.schema, b.res_col, active)
            self._cache[key] = fn
        return fn

    def preempt_batch(
        self,
        pods: list[t.Pod],
        batch_rows: dict,
        active: frozenset[str] | None = None,
        inv: dict | None = None,
    ) -> list[PreemptionResult | None]:
        """Run preemption for the failed pods of one scheduling batch.
        ``batch_rows`` are each pod's already-built feature dict rows."""
        sched = self.sched
        cache, builder = sched.cache, sched.builder
        schema = builder.schema

        # Cheap host-side prune: a pod whose demand exceeds every node's
        # allocatable can never be helped by deletion (prevents repacking
        # victim tensors for perma-stuck pods every batch).
        max_alloc = builder.host["alloc"].max(axis=0)
        max_allowed = int(builder.host["allowed_pods"].max(initial=0))

        def can_ever_fit(p: t.Pod) -> bool:
            pr = cache.pods.get(p.uid)
            delta = pr.delta if pr else builder.pod_delta_vectors(p)
            req = delta["req"]
            return bool((req <= max_alloc[: req.shape[0]]).all()) and max_allowed >= 1

        eligible = [
            p.spec.preemption_policy != t.PREEMPT_NEVER and can_ever_fit(p)
            for p in pods
        ]
        if not any(eligible):
            return [None] * len(pods)

        # Pack every node's pods, least important first.
        per_node: dict[int, list] = {}
        vmax = 1
        for rec in cache.nodes.values():
            vics = sorted(
                rec.pods.values(),
                key=lambda p: (p.spec.priority, -p.status.start_time),
            )
            per_node[rec.row] = vics
            vmax = max(vmax, len(vics))
        v = _bucket(vmax, 1)
        n = schema.N
        vic_prio = np.full((n, v), I32_MAX, np.int32)
        vic_req = np.zeros((n, v, schema.R), np.int64)
        vic_nonzero = np.zeros((n, v, 2), np.int64)
        vic_start = np.full((n, v), np.inf, np.float64)
        for row, vics in per_node.items():
            for j, p in enumerate(vics):
                pr = cache.pods[p.uid]
                req = pr.delta["req"]
                vic_prio[row, j] = p.spec.priority
                vic_req[row, j, : req.shape[0]] = req
                vic_nonzero[row, j] = pr.delta["nonzero"]
                vic_start[row, j] = p.status.start_time

        # Stack the failed pods' feature rows into a (K, …) batch; mark
        # ineligible rows invalid so their step is a no-op.
        k = _bucket(len(pods), 1)
        batch: dict = {}
        for key_, rows in batch_rows.items():
            stacked = np.stack(rows)
            pad = [(0, k - len(pods))] + [(0, 0)] * (stacked.ndim - 1)
            batch[key_] = np.pad(stacked, pad)
        batch["valid"] = np.zeros(k, np.bool_)
        batch["valid"][: len(pods)] = eligible

        if inv is None:
            inv = builder.batch_invariants()
        state = builder.state()
        out = self._pass(active)(
            state, batch, inv, jnp.asarray(vic_prio), jnp.asarray(vic_req),
            jnp.asarray(vic_nonzero), jnp.asarray(vic_start),
        )
        picks, kstars = np.asarray(out.picks), np.asarray(out.k_star)

        results: list[PreemptionResult | None] = []
        consumed: set[str] = set()
        for i, pod in enumerate(pods):
            pick, kp = int(picks[i]), int(kstars[i])
            if pick < 0:
                results.append(None)
                continue
            node_name = cache.node_name_at_row(pick)
            victims = [
                p
                for p in per_node[pick][:kp]
                if p.spec.priority < pod.spec.priority and p.uid not in consumed
            ]
            # prepareCandidate: delete victims, nominate the node.  The host
            # deltas mark rows dirty; the next state() flush re-syncs the
            # device (the in-scan release was resources-only).
            for vic in victims:
                consumed.add(vic.uid)
                cache.remove_pod(vic.uid)
            pod.status.nominated_node_name = node_name
            results.append(PreemptionResult(node_name=node_name, victims=victims))
        return results
