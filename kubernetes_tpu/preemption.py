"""Batched preemption: the PostFilter dry-run as one device pass.

Reference (framework/preemption/preemption.go + plugins/defaultpreemption/):
the evaluator clones the snapshot per candidate node, removes lower-priority
pods most-important-last (SelectVictimsOnNode sorts by MoreImportantPod and
reprieves most-important-first, :541 DryRunPreemption), and picks the winner
by five lexicographic criteria (:424 pickOneNodeForPreemption — fewest PDB
violations → lowest max victim priority → smallest victim priority sum →
fewest victims → latest earliest victim start time).

TPU design: both parallel axes of the reference map onto one dispatch — the
candidate-node axis is the device vector axis, and the queue of failed pods
becomes a `lax.scan` whose carry commits each preemption's resource release
before the next preemptor looks (mirroring the scheduling pass).  The host
packs every node's pods into (N, V) tensors once per batch (non-violating
first, least-important-first within each class — violating classified with
simulated per-PDB budget consumption most-important-first, exactly
filterPodsWithPDBViolation); each scan step masks the entries below its own
preemptor's priority and excludes nodes any unresolvable filter rejects
(the UnschedulableAndUnresolvable analog, :216).  Chosen victims are marked
consumed in the carried tensors so later preemptors in the batch cannot
double-claim them.  Unlike the reference, which dry-runs only a rotating
percentage of candidates, the full node axis is evaluated.

Candidacy and feasibility run the preemptor's FULL active filter set
against per-node what-if states (resources, pod counts, group/term/port
tensors released via scatter) — a node whose only failure is a victim's
host port or anti-affinity pair is still found (the r1 false negative).

Victim selection is the reference's GREEDY REPRIEVE (SelectVictimsOnNode):
start from every lower-priority pod removed, then walk victims in reverse
slot order — violating most-important-first, then non-violating
most-important-first, the reference's exact reprieve order — re-admitting
each one whose return keeps the preemptor feasible, yielding possibly
NON-CONTIGUOUS victim sets.  Criterion 1's violation count is thereby
minimized per candidate, as in pickOneNodeForPreemption.  On device the
reprieve is a lax.scan over victim slots whose carry is the per-node
removal mask — each step one batched what-if filter evaluation (O(V) evals
of O(N·V·R) masked sums; V buckets at 8 for realistic pods-per-node, so
the quadratic term stays small — an incremental-carry formulation is the
known optimization if dense nodes ever dominate).

Volume/DRA state IS released in the what-if (r5): victims' device-volume
uses, CSI attachments (distinct-volume crossings), and DRA claim/pool
charges join the released tensors, so reprieve runs for those classes and
a node feasible only via a volume/DRA victim is found with the reference's
minimal victim set.

Divergences (documented): later preemptors in one batch see consumed
victims' group/term/port/volume/DRA counts un-released (conservative; the
retry runs against truth).  A ReadWriteOncePod conflict (host featurize
scalar) keeps the evict-all-no-reprieve route.  Two victims on one node
SHARING an attached CSI volume or a DRA claim both register its crossing
when both are masked (the shared release double-counts — over-optimistic;
the Reserve re-check validates against truth before any commit).
PDB-violation classification simulates
budget consumption over ALL of a node's pods (preemptor-independent
packing); with mixed preemptor priorities in one batch the reference
classifies per preemptor over only its potential victims, which can
order the reprieve differently.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .api import types as t
from .framework.config import Profile
from .ops import common as opcommon
from .snapshot import Schema, _bucket
from .utils import device_fetch

I32_MAX = np.int32(2**31 - 1)

from functools import partial  # noqa: E402


# Pad fills for the what-if feature tensors' empty victim slots (id-like
# columns use the -1 sentinel; counts/flags use 0) — must match the
# np.full/np.zeros defaults pack_victims stages with.
_VFEAT_PAD = {
    "group": -1, "terms": -1, "port_triples": -1, "port_keys": -1,
    "vol_dev_ids": -1, "csi_ids": -1, "dra_kid": -1,
}


@partial(jax.jit, static_argnums=1)
def _unpack_victims(buf, spec):
    """Slice the single-transfer victim mega-buffer (pack_victims) back
    into per-field device arrays — one compiled program, so the seven
    logical arrays cost ONE tunnel round trip instead of seven.  The
    buffer ships only the OCCUPIED victim slots (vu = pow2 ≥ vmax); the
    unpack pads each field up to the pass's floor-8 victim axis ``v`` with
    its empty-slot sentinel on device — a node usually holds 1-4 pods, so
    the floor-8 shape stability no longer costs 8× the upload bytes.
    ``spec`` = (R, n_pdbs, pdb_words, vf_cols, v) — static per layout."""
    r, n_pdbs, pdb_words, vf_cols, v = spec
    vu = buf.shape[1]

    def pad(x, fill):
        if vu == v:
            return x
        w = [(0, 0)] * x.ndim
        w[1] = (0, v - vu)
        return jnp.pad(x, w, constant_values=fill)

    prio = pad(buf[..., 0].astype(jnp.int32), I32_MAX)
    req = pad(buf[..., 1 : 1 + r], 0)
    nonzero = pad(buf[..., 1 + r : 3 + r], 0)
    start = pad(lax.bitcast_convert_type(buf[..., 3 + r], jnp.float64), jnp.inf)
    words = buf[..., 4 + r : 4 + r + pdb_words]
    idx = np.arange(n_pdbs)
    pdb = pad(
        ((words[..., idx // 64] >> jnp.asarray(idx % 64)) & 1).astype(bool),
        False,
    )
    allowed = buf[:n_pdbs, 0, -1]
    out = [prio, req, nonzero, start, pdb, allowed]
    off = 4 + r + pdb_words
    for name, width, shape in vf_cols:
        fill = _VFEAT_PAD.get(name, 0)
        if len(shape) == 2:
            out.append(pad(buf[..., off].astype(jnp.int32), fill))
        else:
            out.append(pad(buf[..., off : off + width].astype(jnp.int32), fill))
        off += width
    return tuple(out)


@jax.jit
def _scatter_buf_rows(d_buf, rows, sub):
    """Update dirty node rows of the device-resident victim mega-buffer in
    place of a full re-upload: the incremental repack ships only the
    changed rows' bytes (a preemption batch dirties a handful of nodes;
    the full buffer is ~0.65MB — ~100ms of tunnel time per batch)."""
    return d_buf.at[rows].set(sub)


@partial(jax.jit, static_argnums=0)
def _chain_speculative(fn, state, batch_d, picks, elig_sigs, inv_d, *pack_arrays):
    """Run a compiled dry-run with its valid mask derived from the main
    pass's DEVICE-resident picks (valid = eligible ∧ pick < 0 — scan
    failures AND chunk-deferrals speculate; results for pods the strict
    tail later places are simply never applied).  fn is static (the cached
    compiled pass), so this wrapper inlines into one dispatched program."""
    elig, sigs = elig_sigs
    b = dict(batch_d)
    b["valid"] = elig & (picks < 0)
    b["sig"] = sigs
    return fn(state, b, inv_d, *pack_arrays)


@dataclass
class PreemptionResult:
    node_name: str
    victims: list[t.Pod]


class PreemptStep(NamedTuple):
    picks: jax.Array  # (K,) i32 node row, -1 = no candidate
    vic_mask: jax.Array  # (K, V) bool — chosen victims at the picked node
    n_victims: jax.Array  # (K,) i32 victims in that mask


def build_preempt_pass(
    profile: Profile,
    schema: Schema,
    builder_res_col,
    active: frozenset[str] | None = None,
    n_pdbs: int = 1,
    chunk: int = 1,
):
    """Compile the scan-over-preemptors dry-run for one (profile, schema,
    active-op-set) — the active set must match the scheduling batch whose
    feature rows feed this pass.

    ``chunk`` preemptors evaluate per scan step (vmapped — on TPU the
    per-step dispatch overhead dominates these tensors, exactly like the
    scheduling pass).  Chunk-mates picking the SAME node would double-claim
    victims, so later same-node picks defer (pick = -2) to a strict
    chunk=1 re-run by the evaluator.  Within-chunk drift (documented):
    chunk-mates see chunk-start victim state, and PDB budgets are not
    shared across chunk-mates' nodes — placement stays sound because
    same-node conflicts defer."""
    filter_ops = [
        opcommon.get(n)
        for n in profile.filters
        if active is None or n in active
    ]
    static: dict = {}
    for op in {o.name: o for o in filter_ops}.values():
        if op.static is not None:
            static.update(op.static(profile, schema, builder_res_col))
    ctx = opcommon.PassContext(profile=profile, schema=schema, static=static)

    # Whether the active filter set reads the domain tables (rebuilt per
    # what-if inside full_ok when so).
    needs_dom = any(
        op.name in ("InterPodAffinity", "PodTopologySpread") for op in filter_ops
    )
    # Filters whose verdict can change when pods are removed from a node.
    # NodeResourcesFit evaluates in closed form against the masked release
    # sums; _SEARCHABLE ops get the per-mask what-if evaluation — their
    # release overlays are simulated, INCLUDING the volume device
    # conflicts, CSI attach counts (distinct-volume crossings), and DRA
    # claim/pool charges victims held (VERDICT r4 missing-6: the
    # reference's dry-run re-runs full filters with victims' RemovePod
    # extensions releasing that state, preemption.go:541,
    # interpodaffinity/filtering.go:155).  Their UNRESOLVABLE portions
    # (missing claims, allocation pins, zone conflicts) still constrain
    # candidacy via hard_filter.  Release-INdependent filters (taints,
    # node affinity, volume zones, …) run once on the live state.
    # Residual divergence: a ReadWriteOncePod conflict is a host-side
    # featurize scalar, not a released tensor — an RWOP-blocked preemptor
    # keeps the old evict-all-no-reprieve route (res_fail below).
    _RELEASE_DEPENDENT = {
        "NodeResourcesFit", "NodePorts", "InterPodAffinity",
        "PodTopologySpread", "VolumeRestrictions", "NodeVolumeLimits",
        "DynamicResources",
    }
    _SEARCHABLE = {
        "NodePorts", "InterPodAffinity", "PodTopologySpread",
        "VolumeRestrictions", "NodeVolumeLimits", "DynamicResources",
    }
    search_ops = [
        op
        for op in filter_ops
        if op.name in _SEARCHABLE and op.filter is not None
    ]
    # Unresolvable portions of searchable ops (DRA missing/pins) still
    # gate candidacy.
    search_hard_ops = [
        op
        for op in filter_ops
        if op.name in _SEARCHABLE and op.hard_filter is not None
    ]
    vr_active = any(op.name == "VolumeRestrictions" for op in filter_ops)
    invariant_ops = [
        op
        for op in filter_ops
        if op.name not in _RELEASE_DEPENDENT and op.filter is not None
    ]
    resolvable_ops = [
        op
        for op in filter_ops
        if op.name in _RELEASE_DEPENDENT - _SEARCHABLE - {"NodeResourcesFit"}
        and op.hard_filter is not None
    ]

    def eval_one(
        state, vic_prio, vic_req, vic_nonzero, vic_start, pf, dctx, vfeat,
        vic_pdb, pdb_allowed,
    ):
        """One preemptor's dry-run against the given victim state: returns
        the pick and its commit ingredients (no state mutation).

        Victim selection = the reference's SelectVictimsOnNode: remove ALL
        lower-priority pods, then reprieve most-important-first (reverse
        slot order; PDB-violating victims are packed last, so they get
        their reprieve attempt first)."""
        n, v = vic_prio.shape
        prio = pf["priority"].astype(jnp.int32)
        lower = vic_prio < prio  # (N, V) — consumed victims carry I32_MAX

        rows2 = jnp.broadcast_to(jnp.arange(n)[:, None], (n, v))

        def released(mask):
            """ClusterState with each node's masked victims removed — the
            per-node what-if the reference builds with NodeInfo.Snapshot()
            + RemovePod per candidate (DryRunPreemption, preemption.go:541).
            ``mask`` (N, V) bool."""
            rel_m = jnp.sum(jnp.where(mask[:, :, None], vic_req, 0), axis=1)
            relnz_m = jnp.sum(
                jnp.where(mask[:, :, None], vic_nonzero, 0), axis=1
            )
            new = dict(
                req=state.req - rel_m,
                nonzero_req=state.nonzero_req - relnz_m,
                num_pods=state.num_pods - mask.sum(axis=1).astype(jnp.int32),
            )
            if "group" in vfeat:
                g = vfeat["group"]  # (N, V)
                new["group_counts"] = state.group_counts.at[
                    jnp.maximum(g, 0), rows2
                ].add(-(mask & (g >= 0)).astype(jnp.int32))
            if "terms" in vfeat:
                tm = vfeat["terms"]  # (N, V, TS)
                new["et_counts"] = state.et_counts.at[
                    jnp.maximum(tm, 0), rows2[:, :, None]
                ].add(-(mask[:, :, None] & (tm >= 0)).astype(jnp.int32))
            if "port_triples" in vfeat:
                pt, pk = vfeat["port_triples"], vfeat["port_keys"]
                dec = (mask[:, :, None] & (pt >= 0)).astype(jnp.int32)
                new["port_counts"] = state.port_counts.at[
                    jnp.maximum(pt, 0), rows2[:, :, None]
                ].add(-dec)
                new["portkey_counts"] = state.portkey_counts.at[
                    jnp.maximum(pk, 0), rows2[:, :, None]
                ].add(-dec)
            rows3 = rows2[:, :, None]
            if "vol_dev_ids" in vfeat:
                # Victims' device-volume uses (VolumeRestrictions): exact
                # inverse of apply_pod_delta's devices application.
                di = vfeat["vol_dev_ids"]  # (N, V, Sd)
                dw = vfeat["vol_dev_rw"]
                dm = (mask[:, :, None] & (di >= 0)).astype(jnp.int32)
                new["dev_counts"] = state.dev_counts.at[
                    jnp.maximum(di, 0), rows3
                ].add(-dm)
                new["dev_rw_counts"] = state.dev_rw_counts.at[
                    jnp.maximum(di, 0), rows3
                ].add(-(dm * (dw > 0)))
            if "csi_ids" in vfeat:
                # CSI attach limits: csivol_counts decrement per reference;
                # csi_used releases only where the DISTINCT volume's count
                # crosses to zero (csi.go:219 semantics — two victims
                # sharing an attached volume free it only together).
                ci = vfeat["csi_ids"]  # (N, V, Sc)
                cd = vfeat["csi_drv"]
                cm = (mask[:, :, None] & (ci >= 0)).astype(jnp.int32)
                ci_s = jnp.maximum(ci, 0)
                new_cv = state.csivol_counts.at[ci_s, rows3].add(-cm)
                crossed = (cm > 0) & (new_cv[ci_s, rows3] == 0)
                new["csivol_counts"] = new_cv
                new["csi_used"] = state.csi_used.at[
                    jnp.maximum(cd, 0), rows3
                ].add(-crossed.astype(jnp.int32))
            if "dra_kid" in vfeat:
                # DRA claim references + pool charges: claim counts drop
                # per FIRST slot (the count-moving one, mirroring
                # apply_pod_delta); EVERY slot of a crossing claim
                # releases its own pool column's charge (the prev==1
                # branch applies per slot there too).
                kid = vfeat["dra_kid"]  # (N, V, Sk)
                cid = vfeat["dra_cid"]
                cnt = vfeat["dra_cnt"]
                first = vfeat["dra_first"] > 0
                act = mask[:, :, None] & (kid >= 0)
                km = (act & first).astype(jnp.int32)
                kid_s = jnp.maximum(kid, 0)
                new_kc = state.dra_claim_counts.at[kid_s, rows3].add(-km)
                crossed = act & (new_kc[kid_s, rows3] == 0)
                new["dra_claim_counts"] = new_kc
                new["dra_alloc"] = state.dra_alloc.at[
                    jnp.maximum(cid, 0), rows3
                ].add(-jnp.where(crossed, cnt, 0).astype(state.dra_alloc.dtype))
            return dataclasses.replace(state, **new)

        # Release-independent filters: one evaluation on the live state —
        # pod removal never fixes a taint/node-affinity/zone rejection, so
        # these also subsume UnschedulableAndUnresolvable candidacy.
        base_ok = state.valid
        for op in invariant_ops:
            base_ok &= op.filter(state, pf, dctx)
        # Unresolvable portions of the searchable set (DRA missing claims
        # and allocation pins): deleting pods moves no allocation.
        for op in search_hard_ops:
            base_ok &= ~op.hard_filter(state, pf, dctx)
        # Residual unsimulated-resolvable ops (none in the in-tree set —
        # volume/DRA releases are simulated since r5) keep the
        # evict-all-no-reprieve route, as does a ReadWriteOncePod-blocked
        # preemptor: the RWOP conflict is a host featurize scalar, not a
        # released tensor, so its per-node eviction is not simulated.
        res_fail = jnp.zeros(state.valid.shape, jnp.bool_)
        for op in resolvable_ops:
            base_ok &= ~op.hard_filter(state, pf, dctx)
            if op.filter is not None:
                res_fail |= ~op.filter(state, pf, dctx)
        if vr_active:
            res_fail |= jnp.broadcast_to(
                ~pf["vr_rwop_ok"], state.valid.shape
            )

        demand = pf["req"]  # (R,)

        def ok_closed(rel_m, cnt):
            """Closed-form fit of the preemptor given released resources
            ``rel_m`` (N, R) and removed-pod count ``cnt`` (N,)."""
            free = state.alloc - (state.req - rel_m)
            ok = ((demand[None, :] == 0) | (demand[None, :] <= free)).all(-1)
            ok &= state.num_pods - cnt + 1 <= state.allowed_pods
            return ok

        def ok_search(mask):
            """The release-dependent filter set against the released state
            (exact candidacy — a node whose sole failure is a victim's
            port, anti-affinity pair, device volume, CSI attachment, or
            DRA device is still found)."""
            st2 = released(mask)
            if needs_dom:
                from .engine.pass_ import build_dom

                dom0 = dctx.dom
                dom2 = build_dom(st2, dom0.et_slot, dom0.et_host, schema.DV)
                d2 = dataclasses.replace(dctx, dom=dom2)
            else:
                d2 = dctx
            pf2 = pf
            if vr_active:
                # The RWOP scalar is handled by the res_fail evict-all
                # route; inside the what-if it must not veto every node.
                pf2 = dict(pf)
                pf2["vr_rwop_ok"] = jnp.ones((), jnp.bool_)
            ok = jnp.ones(state.valid.shape, jnp.bool_)
            for op in search_ops:
                ok &= op.filter(st2, pf2, d2)
            return ok

        # Phase 1 — all lower-priority pods removed: the candidacy check
        # (SelectVictimsOnNode's initial RemovePod sweep).
        rel_lower = jnp.sum(jnp.where(lower[:, :, None], vic_req, 0), axis=1)
        cnt_lower = lower.sum(axis=1).astype(jnp.int32)
        feas_all = ok_closed(rel_lower, cnt_lower)
        if search_ops:
            feas_all &= ok_search(lower)

        # Phase 2 — greedy reprieve, most-important-first = reverse slot
        # order (slots are least-important-first, PDB-violating last, so
        # violating victims get their reprieve attempt first — exactly
        # filterPodsWithPDBViolation + the two reprieve loops).  Nodes
        # failing an unsimulated-resolvable op skip reprieve entirely.
        # The release sums ride the carry INCREMENTALLY — each step
        # adjusts (N, R) by one slot instead of re-reducing (N, V, R)
        # (the O(V) full evaluations were the preemption-async device
        # ceiling; search ops still pay their full what-if per step).
        can_reprieve = feas_all & ~res_fail

        def reprieve_step(carry, s):
            mask, rel_m, cnt = carry
            has = mask[:, s]
            t_rel = rel_m - jnp.where(has[:, None], vic_req[:, s], 0)
            t_cnt = cnt - has.astype(jnp.int32)
            ok = ok_closed(t_rel, t_cnt)
            tentative = mask & ~(jnp.arange(v)[None, :] == s)
            if search_ops:
                ok &= ok_search(tentative)
            take = can_reprieve & ok & has
            mask = jnp.where(take[:, None], tentative, mask)
            rel_m = jnp.where(take[:, None], t_rel, rel_m)
            cnt = jnp.where(take, t_cnt, cnt)
            return (mask, rel_m, cnt), None

        (vic_mask, rel_all, _cnt_final), _ = lax.scan(
            reprieve_step,
            (lower, rel_lower, cnt_lower),
            jnp.arange(v - 1, -1, -1),
        )

        n_vic = vic_mask.sum(axis=1).astype(jnp.int32)
        # At least one victim, else deletion can't be what fixes this node.
        possible = base_ok & feas_all & (n_vic >= 1) & pf["valid"]

        # Criteria over the FINAL victim set (pickOneNodeForPreemption,
        # preemption.go:424): fewest PDB violations → lowest max victim
        # priority → smallest priority sum → fewest victims → latest
        # earliest start AMONG the highest-priority victims
        # (GetEarliestPodStartTime).
        cnt_p = jnp.einsum(
            "nv,nvp->np", vic_mask.astype(jnp.float32),
            vic_pdb.astype(jnp.float32),
        ).astype(jnp.int64)  # (N, P)
        violations = jnp.maximum(cnt_p - pdb_allowed[None, :], 0).sum(axis=1)
        max_prio = jnp.max(jnp.where(vic_mask, vic_prio, -1), axis=1)
        prio_sum = jnp.sum(
            jnp.where(vic_mask, vic_prio, 0).astype(jnp.int64), axis=1
        )
        min_start = jnp.min(
            jnp.where(
                vic_mask & (vic_prio == max_prio[:, None]), vic_start, jnp.inf
            ),
            axis=1,
        )

        big = jnp.int64(2**62)

        def narrow(mask, key):
            best = jnp.min(jnp.where(mask, key, big))
            return mask & (key == best)

        # Latest earliest-start wins: minimize the negated key, in
        # microseconds so sub-second differences survive the int cast.
        start_key = jnp.where(
            jnp.isfinite(min_start), -min_start * 1e6, -jnp.float64(2**61)
        ).astype(jnp.int64)

        # rel_all rode the reprieve carry; only the nonzero companion needs
        # its (single) masked reduce.
        relnz_all = jnp.sum(
            jnp.where(vic_mask[:, :, None], vic_nonzero, 0), axis=1
        )

        if chunk == 1:
            # Exact lexicographic narrowing (parity-grade semantics).
            mask = possible
            mask = narrow(mask, violations)
            mask = narrow(mask, max_prio.astype(jnp.int64))
            mask = narrow(mask, prio_sum)
            mask = narrow(mask, n_vic.astype(jnp.int64))
            mask = narrow(mask, start_key)
            pick = jnp.argmax(mask).astype(jnp.int32)
            do = possible.any()
            pick = jnp.where(do, pick, -1)
            row = jnp.maximum(pick, 0)
            chosen = vic_mask[row] & do  # (V,)
            rel_vec = jnp.where(do, rel_all[row], 0)
            rel_nz_vec = jnp.where(do, relnz_all[row], 0)
            nvic = jnp.where(do, n_vic[row], 0)
            return (
                pick, chosen, nvic.astype(jnp.int32),
                rel_vec, rel_nz_vec,
            )

        # Chunked mode: the five criteria ride out RAW for an exact
        # lexicographic rank order in the step (jnp.lexsort) — the old
        # saturating bit-packed i64 quantized sub-granularity differences
        # away (a start_key gap under 2^50 collapsed, so the rank-0 pick —
        # the representative's own candidate — could diverge from the
        # chunk=1 narrowing; ISSUE 13's parity oracle pinned it).  The
        # step assigns same-signature chunk-mates the 1st, 2nd, … best
        # nodes in one shot — identical preemptors (the async-preemption
        # shape) otherwise all converge on one node and serialize.
        crit = (
            violations,
            max_prio.astype(jnp.int64),
            prio_sum,
            n_vic.astype(jnp.int64),
            start_key,
        )
        return crit, possible, vic_mask, n_vic, rel_all, relnz_all

    def step(carry, pf, dctx, vfeat, vic_pdb, pdb_allowed):
        state, vic_prio, vic_req, vic_nonzero, vic_start = carry
        c = pf["valid"].shape[0]
        n, v = vic_prio.shape
        if chunk == 1:
            picks, chosens, nvics, rel_vecs, relnz_vecs = jax.vmap(
                lambda p: eval_one(
                    state, vic_prio, vic_req, vic_nonzero, vic_start, p, dctx,
                    vfeat, vic_pdb, pdb_allowed,
                )
            )(pf)
            defer = jnp.zeros((c,), jnp.bool_)
            do = picks >= 0
        else:
            # ONE dry-run per chunk, evaluated for mate 0: chunk-mates with
            # mate-0's signature (priority + request — their dry-runs would
            # be identical) take the 1st, 2nd, … best nodes by the packed
            # key, emulating the sequential take-next-best without C copies
            # of the per-preemptor release tensors.  Mates with a different
            # signature defer to the strict chunk=1 re-run.
            # The representative mate is the first VALID one — under the
            # speculative chained dispatch the chunk is the ORIGINAL batch,
            # whose leading pods may have PLACED (valid False, features
            # gated off); evaluating those would turn the whole rank-split
            # into defers.  (Sync mode stacks failed pods from index 0, so
            # idx0 == 0 there — behavior unchanged.)
            idx0 = jnp.argmax(pf["valid"])
            pf0 = jax.tree_util.tree_map(lambda x: x[idx0], pf)
            crit, possible, vic_mask_all, n_vic_all, rel_all, relnz_all = eval_one(
                state, vic_prio, vic_req, vic_nonzero, vic_start, pf0, dctx,
                vfeat, vic_pdb, pdb_allowed,
            )
            # Signature = the featurize-cache identity (namespace + labels +
            # full spec), computed host-side: equal sigs ⇒ identical feature
            # rows ⇒ identical dry-runs.  Priority/req equality alone would
            # wrongly share the representative's feasibility with pods whose
            # FILTERS differ (node affinity, taints, ports — r2 review).
            samesig = pf["sig"] == pf["sig"][idx0]
            eligible = pf["valid"] & samesig
            big = jnp.int64(2**62)
            # EXACT lexicographic candidate order (pickOneNode criteria,
            # most-significant last in the lexsort key list; lexsort is
            # stable, so full ties keep snapshot row order — exactly the
            # chunk=1 narrowing's argmax-first tie-break).  Infeasible
            # nodes sort last via the sentinel on the primary criterion.
            vio_m = jnp.where(possible, crit[0], big)  # (N,)
            order = jnp.lexsort((crit[4], crit[3], crit[2], crit[1], vio_m))
            srt = vio_m[order]
            rank = jnp.cumsum(eligible.astype(jnp.int32)) - 1  # (C,)
            safe_rank = jnp.clip(rank, 0, n - 1)
            row = order[safe_rank]
            has = eligible & (srt[safe_rank] < big)
            picks = jnp.where(has, row.astype(jnp.int32), -1)
            # Heterogeneous mates retry strictly; exhausted ranks fall back
            # to the strict pass too (the sequential semantics may still
            # place them by deepening a prefix on an already-taken node).
            defer = pf["valid"] & ~has
            do = has
            rows_safe = jnp.where(do, picks, 0)
            nvics = jnp.where(do, n_vic_all[rows_safe], 0).astype(jnp.int32)
            rel_vecs = jnp.where(do[:, None], rel_all[rows_safe], 0)
            relnz_vecs = jnp.where(do[:, None], relnz_all[rows_safe], 0)
            chosens = vic_mask_all[rows_safe] & do[:, None]
        rows = jnp.where(do, picks, 0)
        state = dataclasses.replace(
            state,
            req=state.req.at[rows].add(-jnp.where(do[:, None], rel_vecs, 0)),
            nonzero_req=state.nonzero_req.at[rows].add(
                -jnp.where(do[:, None], relnz_vecs, 0)
            ),
            num_pods=state.num_pods.at[rows].add(-jnp.where(do, nvics, 0)),
        )
        # Consume chosen victims.  Consumption only ever RAISES priorities
        # to the I32_MAX sentinel, so scatter-MAX makes duplicate row
        # entries (the placeholders of non-committing chunk-mates) safe.
        upd = jnp.where(
            do[:, None] & chosens, jnp.int32(I32_MAX), jnp.int32(-(2**31))
        )
        vic_prio = vic_prio.at[rows].max(upd)
        out = PreemptStep(
            picks=jnp.where(defer, -2, picks), vic_mask=chosens, n_victims=nvics
        )
        return (state, vic_prio, vic_req, vic_nonzero, vic_start), out

    @jax.jit
    def run(
        state, batch, inv, vic_prio, vic_req, vic_nonzero, vic_start,
        vfeat, vic_pdb, pdb_allowed,
    ):
        # Domain tables for the filters.  The scan carry releases resources
        # only; the per-mask what-if rebuilds its own tables inside
        # ok_under when an affinity/spread op is active.
        from .engine.pass_ import build_dom

        # Domain tables only when an active op reads them (XLA would DCE
        # the dead matmuls anyway, but the explicit gate keeps the trace —
        # and the compile — small for the fit-only shape).
        dom = (
            build_dom(state, inv["et_slot"], inv["et_host"], schema.DV)
            if needs_dom
            else None
        )
        dctx = dataclasses.replace(ctx, dom=dom)
        k = next(iter(batch.values())).shape[0]
        assert k % chunk == 0, f"preempt batch {k} not a multiple of {chunk}"
        cbatch = jax.tree_util.tree_map(
            lambda x: x.reshape((k // chunk, chunk) + x.shape[1:]), batch
        )
        carry = (state, vic_prio, vic_req, vic_nonzero, vic_start)
        carry, out = lax.scan(
            lambda c, pf: step(c, pf, dctx, vfeat, vic_pdb, pdb_allowed),
            carry, cbatch,
        )
        out = jax.tree_util.tree_map(
            lambda x: x.reshape((k,) + x.shape[2:]), out
        )
        # Final carry feeds the evaluator's strict re-run of deferred
        # preemptors (same-node chunk conflicts).
        return out, carry[0], carry[1]

    return run


class PreemptionEvaluator:
    """Host driver: packs victim tensors once per failed batch, runs the
    scan, applies the chosen victims (prepareCandidate, preemption.go:342)."""

    def __init__(self, scheduler) -> None:
        self.sched = scheduler
        self._cache: dict = {}
        # Incremental victim-staging cache (see pack_victims): staging
        # arrays + per-node victim lists + the last uploaded device result,
        # keyed by per-node pods_gen so an unchanged cluster repacks free.
        self._stage: dict | None = None
        # Sticky hint from the driver: recent batches produced failures, so
        # the next batch prepacks victim tensors concurrently with its
        # device pass (scheduler._batch_traced).
        self.expect_failures = False

    def worth_prepacking(self, pods) -> bool:
        """Cheap eligibility precheck before a speculative pack: packing is
        pure waste when NO pod in the batch could ever have victims (the
        perma-stuck Unschedulable-workload shape, whose failures would
        otherwise keep expect_failures — and the packing walk — on every
        batch).  Mirrors preempt_batch's min-priority prune."""
        cache = self.sched.cache
        if not cache.pods:
            return False
        min_prio = min(pr.pod.spec.priority for pr in cache.pods.values())
        return any(
            p.spec.priority > min_prio
            and p.spec.preemption_policy != t.PREEMPT_NEVER
            for p in pods
        )

    def _pass(
        self, profile, active: frozenset[str] | None, n_pdbs: int, chunk: int
    ):
        b = self.sched.builder
        key = (
            profile, b.schema, tuple(sorted(b.res_col.items())),
            active, n_pdbs, chunk,
        )
        fn = self._cache.get(key)
        if fn is None:
            fn = build_preempt_pass(
                profile, b.schema, b.res_col, active, n_pdbs, chunk
            )
            self._cache[key] = fn
        return fn

    @staticmethod
    def _unpack_spec(layout: dict):
        return (
            layout["r"], layout["n_pdbs"], layout["pdb_words"],
            layout["vf_cols"], layout["v"],
        )

    def pack_victims(self, profile, active: frozenset[str] | None) -> dict:
        """Build (and ship to device) the per-node victim tensors for one
        dry-run — separable from preempt_batch so the driver can OVERLAP
        packing + transfer with the failing batch's device pass
        (_batch_traced prepacks when recent batches produced failures).
        Packed from the CURRENT cache state: prepacking therefore sees the
        pre-batch snapshot, i.e. same-batch placements are not victim
        candidates — the reference's dry-run runs on the cycle snapshot
        the same way (DryRunPreemption, preemption.go:541).

        INCREMENTAL between calls (cache.go:186 UpdateSnapshot's
        generation diff, applied to the victim tensors): each NodeRecord
        carries a pods_gen bumped on any pod-membership or pod-object
        change, so a repack rebuilds only the dirty nodes' staging rows —
        and an unchanged cluster returns the previous device arrays with
        zero staging or transfer work.  Gated off when PDBs exist (the
        violating-victim classification reads mutable budget state) or
        DynamicResources is active (claim reservation state changes
        without touching node pod membership)."""
        sched = self.sched
        cache, builder = sched.cache, sched.builder
        schema = builder.schema
        # PDBs: per-victim matched budgets.  A victim is "violating" when it
        # matches a PDB with no disruptions left; such pods sort LAST in the
        # eviction order (the reference reprieves violating victims first —
        # filterPodsWithPDBViolation + the reprieve loop), so the minimal
        # fitting prefix prefers non-violating victims.
        pdbs = list(getattr(sched, "pdbs", {}).values())
        # Spec-carrying budgets track live pod state (the disruption
        # controller's reconcile, disruption.go:732): recompute before the
        # pack classifies violating victims against disruptionsAllowed.
        dc = getattr(sched, "disruption_controller", None)
        if dc is not None and pdbs:
            dc.sync()  # sync_one no-ops for spec-less (informer-fed) budgets
        n_pdbs = _bucket(len(pdbs), 1)

        def matched_pdbs(p: t.Pod) -> list[int]:
            return [
                i
                for i, pdb in enumerate(pdbs)
                if pdb.namespace == p.namespace
                and t.label_selector_matches(pdb.selector, p.metadata.labels)
            ]

        # What-if release features, gated by what the active filters read
        # (the pass branches on the same key set at trace time).
        names = set(
            profile.filters if active is None else active
        )
        cacheable = not pdbs and "DynamicResources" not in names
        if not cacheable:
            # Drop any retained stage: a profile that turned non-cacheable
            # (gained a PDB / activated DRA) would otherwise pin the
            # multi-MB staging + device tensors for the process lifetime.
            self._stage = None
        st = self._stage if cacheable else None
        if st is not None and not (
            st["n"] == schema.N
            and st["r"] == schema.R
            and st["names"] == names
            and st["profile"] is profile
            and st["active"] == active
        ):
            st = None
        if st is not None:
            return self._pack_incremental(st)

        # Pack every node's pods: non-violating first, least-important-first
        # within each class.  "Violating" is classified with SIMULATED
        # per-PDB budget consumption, walking the node's pods
        # most-important-first (filterPodsWithPDBViolation: the most
        # important matching pods claim the remaining disruptions; the rest
        # are violating and therefore reprieved first).
        per_node: dict[int, list] = {}
        vmax = 1
        for rec in cache.nodes.values():
            viol: dict[str, bool] = {}
            if pdbs:
                remaining = [max(p.disruptions_allowed, 0) for p in pdbs]
                for p in sorted(
                    rec.pods.values(),
                    key=lambda p: (-p.spec.priority, p.status.start_time),
                ):
                    v = False
                    for pi in matched_pdbs(p):
                        if remaining[pi] > 0:
                            remaining[pi] -= 1
                        else:
                            v = True
                    viol[p.uid] = v
            vics = sorted(
                rec.pods.values(),
                key=lambda p: (
                    viol.get(p.uid, False),
                    p.spec.priority,
                    -p.status.start_time,
                ),
            )
            per_node[rec.row] = vics
            vmax = max(vmax, len(vics))
        # Floor 8: the victim axis stays one shape across the common range,
        # so a node gaining a pod mid-run (vmax 1→2) doesn't recompile the
        # pass and re-negotiate every transfer layout inside the measured
        # window (~15ms/array first-shape cost through the tunnel).  The
        # UPLOAD ships only the occupied slots (vu): at vmax=1 the old
        # floor-8 buffer moved 8× the bytes — ~3.6MB vs 0.45MB at 5k nodes,
        # 100ms+ of pure tunnel time — and _unpack_victims pads back to v
        # on device.
        v = _bucket(vmax)
        vu = _bucket(vmax, 1)
        n = schema.N
        vic_prio = np.full((n, vu), I32_MAX, np.int32)
        vic_req = np.zeros((n, vu, schema.R), np.int64)
        vic_nonzero = np.zeros((n, vu, 2), np.int64)
        vic_start = np.full((n, vu), np.inf, np.float64)
        vic_pdb = np.zeros((n, vu, n_pdbs), np.bool_)
        vfeat: dict[str, np.ndarray] = {}
        if names & {"InterPodAffinity", "PodTopologySpread"}:
            ts = _bucket(  # floor 8: shape-stable like the victim axis
                max(
                    (
                        len(cache.pods[p.uid].delta["own_terms"])
                        for vics in per_node.values()
                        for p in vics
                    ),
                    default=1,
                ),
            )
            vfeat["group"] = np.full((n, vu), -1, np.int32)
            vfeat["terms"] = np.full((n, vu, ts), -1, np.int32)
        if "NodePorts" in names:
            from .snapshot import POD_PORT_SLOTS

            vfeat["port_triples"] = np.full((n, vu, POD_PORT_SLOTS), -1, np.int32)
            vfeat["port_keys"] = np.full((n, vu, POD_PORT_SLOTS), -1, np.int32)

        def _slots(key_: str) -> int:
            return _bucket(
                max(
                    (
                        len(cache.pods[p.uid].delta.get(key_, ()))
                        for vics in per_node.values()
                        for p in vics
                    ),
                    default=1,
                ),
                1,
            )

        if "VolumeRestrictions" in names:
            sd = _slots("devices")
            vfeat["vol_dev_ids"] = np.full((n, vu, sd), -1, np.int32)
            vfeat["vol_dev_rw"] = np.zeros((n, vu, sd), np.int32)
        if "NodeVolumeLimits" in names:
            sc = _slots("csivols")
            vfeat["csi_ids"] = np.full((n, vu, sc), -1, np.int32)
            vfeat["csi_drv"] = np.zeros((n, vu, sc), np.int32)
        dra_slot_map: dict[tuple[int, int], list] = {}
        if "DynamicResources" in names:
            # Per-victim claim slots = the pod's own delta slots PLUS a
            # compensating slot per externally-charged claim the victim
            # solely reserves: the external allocation's PHANTOM charge
            # (apply_external_claim) holds the claim count at ≥1 even with
            # the victim gone, but deleting the sole reserver empties
            # status.reservedFor and the claim-release control loop
            # deallocates it — the what-if must see that crossing.
            dra_cat = builder.dra
            mx = 1
            for row, vics in per_node.items():
                node_name = cache.node_name_at_row(row)
                for j, p in enumerate(vics):
                    slots = list(cache.pods[p.uid].delta.get("dra_claims", ()))
                    for claim in dra_cat.pod_claims(p):
                        if (
                            claim is None
                            or claim.allocated_node != node_name
                            or claim.uid in dra_cat.local_reserved
                            or not set(claim.reserved_for) <= {p.uid}
                        ):
                            continue
                        kid = builder.interns.dra_claims.id(claim.uid)
                        # The phantom moved the COUNT once; the pool
                        # charges were applied exactly once between the
                        # phantom and the pod's delta (whichever came
                        # first — apply_external_claim/apply_pod_delta
                        # both gate on prev==0).  The victim's own delta
                        # slots release those charges at the crossing, so
                        # the compensator moves ONLY the count (cnt=0) —
                        # a cnt-carrying duplicate would double-release
                        # (review finding).
                        slots.append((kid, 0, 0, False, True))
                    dra_slot_map[(row, j)] = slots
                    mx = max(mx, len(slots))
            sk = _bucket(mx, 1)
            vfeat["dra_kid"] = np.full((n, vu, sk), -1, np.int32)
            vfeat["dra_cid"] = np.zeros((n, vu, sk), np.int32)
            vfeat["dra_cnt"] = np.zeros((n, vu, sk), np.int32)
            vfeat["dra_first"] = np.zeros((n, vu, sk), np.int32)
        A = dict(
            vic_prio=vic_prio, vic_req=vic_req, vic_nonzero=vic_nonzero,
            vic_start=vic_start, vic_pdb=vic_pdb, vfeat=vfeat, pdbs=pdbs,
            matched_pdbs=matched_pdbs, dra_slot_map=dra_slot_map,
        )
        self._fill_rows(A, per_node.items())
        st_new = (
            dict(
                n=n, r=schema.R, names=names, profile=profile, active=active,
                vmax=vmax, vu=vu, v=v, A=A, per_node=per_node,
                gens={rec.row: rec.pods_gen for rec in cache.nodes.values()},
            )
            if cacheable
            else None
        )
        result = self._assemble(
            A, n, v, n_pdbs, pdbs, matched_pdbs, per_node, profile, active,
            st=st_new,
        )
        if st_new is not None:
            st_new["result"] = result
            st_new["buf_v"] = v
            self._stage = st_new
        return result

    def _pack_incremental(self, st: dict) -> dict:
        """Repack only the nodes whose pods_gen moved since the staged
        pack; an unchanged cluster returns the previous device arrays."""
        cache = self.sched.cache
        A, per_node, gens = st["A"], st["per_node"], st["gens"]
        dirty: list = []
        live: set[int] = set()
        for rec in cache.nodes.values():
            live.add(rec.row)
            if gens.get(rec.row) != rec.pods_gen:
                dirty.append(rec)
        gone = [row for row in gens if row not in live]
        if not dirty and not gone:
            return st["result"]
        items: list[tuple[int, list]] = []
        vmax = st["vmax"]
        for rec in dirty:
            vics = sorted(
                rec.pods.values(),
                key=lambda p: (p.spec.priority, -p.status.start_time),
            )
            items.append((rec.row, vics))
            vmax = max(vmax, len(vics))
        if vmax > st["vmax"]:
            # High-water growth only: shrinking would thrash shapes.
            st["vmax"] = vmax
            self._grow_victim_axis(st, vmax)
        widths_grew = self._grow_widths(st, items)
        self._clear_rows(A, [row for row, _ in items] + gone)
        for row in gone:
            per_node.pop(row, None)
            gens.pop(row, None)
        self._fill_rows(A, items)
        for rec, (row, vics) in zip(dirty, items):
            per_node[row] = vics
            gens[row] = rec.pods_gen
        rows = sorted({row for row, _ in items} | set(gone))
        buf = st.get("buf")
        layout_stable = (
            buf is not None
            and not widths_grew  # vfeat slot dims define the column layout
            and buf.shape[1] == A["vic_req"].shape[1]  # vu unchanged
            and st.get("buf_v") == st["v"]
        )
        if layout_stable and len(rows) <= 64:
            result = self._assemble_rows(st, rows)
        else:
            result = self._assemble(
                A, st["n"], st["v"], 1, A["pdbs"], A["matched_pdbs"],
                per_node, st["profile"], st["active"], st=st,
            )
            st["buf_v"] = st["v"]
        st["result"] = result
        return result

    def _assemble_rows(self, st: dict, rows: list) -> dict:
        """Rewrite only the dirty rows of the persistent mega-buffer and
        scatter them into the device copy — upload bytes scale with the
        number of changed nodes, not the cluster."""
        A, buf = st["A"], st["buf"]
        r = A["vic_req"].shape[2]
        idx = np.asarray(rows, np.int64)
        # No PDBs on the incremental path (cacheable gate): n_pdbs is the
        # floor bucket 1, the pdb word packs all-zero, and pdb_allowed
        # keeps its staged I32_MAX.
        self._pack_buf_rows(A, buf, idx, r, 1)
        nb = 8 if len(rows) <= 8 else 64  # only the two warmed shapes
        rows_pad = np.zeros(nb, np.int32)
        rows_pad[: len(rows)] = rows
        rows_pad[len(rows):] = rows[0]
        sub = buf[rows_pad]
        st["d_buf"] = _scatter_buf_rows(st["d_buf"], rows_pad, sub)
        prev = st["result"]
        layout = {
            "r": r, "n_pdbs": 1, "pdb_words": 1, "v": st["v"],
            "vf_cols": st["vf_cols"],
        }
        unpacked = _unpack_victims(st["d_buf"], self._unpack_spec(layout))
        d_prio, d_vic_req, d_vic_nonzero, d_vic_start, d_pdb, d_allowed = (
            unpacked[:6]
        )
        vf_keys = tuple(sorted(A["vfeat"]))
        d_vfeat = dict(zip(vf_keys, unpacked[6:]))
        return dict(
            prev, per_node=st["per_node"],
            d_prio=d_prio, d_vic_req=d_vic_req,
            d_vic_nonzero=d_vic_nonzero, d_vic_start=d_vic_start,
            d_vfeat=d_vfeat, d_pdb=d_pdb, d_allowed=d_allowed,
        )

    def _grow_victim_axis(self, st: dict, vmax: int) -> None:
        vu_new = _bucket(vmax, 1)
        A = st["A"]
        if vu_new > st["vu"]:
            grow = vu_new - st["vu"]

            def pad1(arr, fill):
                w = [(0, 0)] * arr.ndim
                w[1] = (0, grow)
                return np.pad(arr, w, constant_values=fill)

            A["vic_prio"] = pad1(A["vic_prio"], I32_MAX)
            A["vic_req"] = pad1(A["vic_req"], 0)
            A["vic_nonzero"] = pad1(A["vic_nonzero"], 0)
            A["vic_start"] = pad1(A["vic_start"], np.inf)
            A["vic_pdb"] = pad1(A["vic_pdb"], False)
            for k_ in list(A["vfeat"]):
                A["vfeat"][k_] = pad1(A["vfeat"][k_], _VFEAT_PAD.get(k_, 0))
            st["vu"] = vu_new
        st["v"] = max(st["v"], _bucket(vmax))

    # Paired slot-width groups: members share one width (the fill writes
    # them in lockstep), with the bucket floor the full pack uses.
    _WIDTH_GROUPS = (
        (("terms",), "own_terms", 8),
        (("vol_dev_ids", "vol_dev_rw"), "devices", 1),
        (("csi_ids", "csi_drv"), "csivols", 1),
    )

    def _grow_widths(self, st: dict, items: list) -> bool:
        """Grow per-victim slot dims (high-water) before refilling dirty
        rows — a new victim with more terms/volumes than any staged one
        would otherwise overflow its slots.  Returns True when any dim
        grew: the mega-buffer's column layout changed, so the incremental
        row-scatter path must rebuild the full buffer."""
        grew = False
        vf = st["A"]["vfeat"]
        cache = self.sched.cache
        for keys, delta_key, floor in self._WIDTH_GROUPS:
            if keys[0] not in vf:
                continue
            need = 0
            for _row, vics in items:
                for p in vics:
                    need = max(
                        need,
                        len(cache.pods[p.uid].delta.get(delta_key, ())),
                    )
            cur = vf[keys[0]].shape[2]
            if need > cur:
                grew = True
                target = _bucket(need, floor)
                for k_ in keys:
                    w = [(0, 0), (0, 0), (0, target - cur)]
                    vf[k_] = np.pad(
                        vf[k_], w, constant_values=_VFEAT_PAD.get(k_, 0)
                    )
        return grew

    @staticmethod
    def _clear_rows(A: dict, rows: list) -> None:
        for row in rows:
            A["vic_prio"][row] = I32_MAX
            A["vic_req"][row] = 0
            A["vic_nonzero"][row] = 0
            A["vic_start"][row] = np.inf
            A["vic_pdb"][row] = False
            for k_, arr in A["vfeat"].items():
                arr[row] = _VFEAT_PAD.get(k_, 0)

    def _fill_rows(self, A: dict, items) -> None:
        """Write victim slots for the given (row, victims) pairs into the
        staging arrays — shared by the full pack and the incremental
        dirty-row repack (a fill divergence would split their decisions)."""
        cache = self.sched.cache
        vic_prio, vic_req = A["vic_prio"], A["vic_req"]
        vic_nonzero, vic_start = A["vic_nonzero"], A["vic_start"]
        vic_pdb, vfeat = A["vic_pdb"], A["vfeat"]
        pdbs, matched_pdbs = A["pdbs"], A["matched_pdbs"]
        dra_slot_map = A["dra_slot_map"]
        for row, vics in items:
            for j, p in enumerate(vics):
                pr = cache.pods[p.uid]
                req = pr.delta["req"]
                vic_prio[row, j] = p.spec.priority
                vic_req[row, j, : req.shape[0]] = req
                vic_nonzero[row, j] = pr.delta["nonzero"]
                vic_start[row, j] = p.status.start_time
                if pdbs:
                    for i in matched_pdbs(p):
                        vic_pdb[row, j, i] = True
                if "group" in vfeat:
                    vfeat["group"][row, j] = pr.delta["group"]
                    for a, tid in enumerate(pr.delta["own_terms"]):
                        vfeat["terms"][row, j, a] = tid
                if "port_triples" in vfeat:
                    for a, (triple, pk) in enumerate(pr.delta["ports"]):
                        vfeat["port_triples"][row, j, a] = triple
                        vfeat["port_keys"][row, j, a] = pk
                if "vol_dev_ids" in vfeat:
                    for a, (vid, rw) in enumerate(pr.delta.get("devices", ())):
                        vfeat["vol_dev_ids"][row, j, a] = vid
                        vfeat["vol_dev_rw"][row, j, a] = int(bool(rw))
                if "csi_ids" in vfeat:
                    for a, (vid, did) in enumerate(pr.delta.get("csivols", ())):
                        vfeat["csi_ids"][row, j, a] = vid
                        vfeat["csi_drv"][row, j, a] = did
                if "dra_kid" in vfeat:
                    for a, (kid, cid, cnt, _un, first) in enumerate(
                        dra_slot_map.get((row, j), ())
                    ):
                        vfeat["dra_kid"][row, j, a] = kid
                        vfeat["dra_cid"][row, j, a] = cid
                        vfeat["dra_cnt"][row, j, a] = cnt
                        vfeat["dra_first"][row, j, a] = int(bool(first))

    @staticmethod
    def _pack_buf_rows(A: dict, buf, idx, r: int, n_pdbs: int) -> None:
        """Write the staging arrays' rows ``idx`` into the mega-buffer —
        the ONE definition of the buffer's column layout, shared by the
        full pack (idx = all rows) and the incremental dirty-row scatter
        (a divergence here would corrupt victim tensors on exactly one of
        the two paths)."""
        # ``idx`` may be slice(None) (full pack — plain slice writes, no
        # fancy-index temporaries) or an int row array (incremental).
        nrows = buf.shape[0] if isinstance(idx, slice) else len(idx)
        vic_req = A["vic_req"]
        buf[idx, :, 0] = A["vic_prio"][idx]
        buf[idx, :, 1 : 1 + r] = vic_req[idx]
        buf[idx, :, 1 + r : 3 + r] = A["vic_nonzero"][idx]
        buf[idx, :, 3 + r] = A["vic_start"][idx].view(np.int64)
        pdb_words = max(1, (n_pdbs + 63) // 64)
        # Accumulate each word OFF-buffer, then one assignment:
        # ``out=buf[idx, ...]`` would write into the copy a fancy index
        # returns, silently dropping every PDB bit.
        vic_pdb = A["vic_pdb"]
        for w_i in range(pdb_words):
            word = np.zeros((nrows, buf.shape[1]), np.int64)
            for i in range(w_i * 64, min((w_i + 1) * 64, n_pdbs)):
                word |= vic_pdb[idx, :, i].astype(np.int64) << (i % 64)
            buf[idx, :, 4 + r + w_i] = word
        off = 4 + r + pdb_words
        for key_ in sorted(A["vfeat"]):
            arr = A["vfeat"][key_]
            if arr.ndim == 2:
                buf[idx, :, off] = arr[idx]
                off += 1
            else:
                w = arr.shape[2]
                buf[idx, :, off : off + w] = arr[idx]
                off += w

    def _assemble(
        self, A: dict, n: int, v: int, n_pdbs: int, pdbs, matched_pdbs,
        per_node: dict, profile, active, st: dict | None = None,
    ) -> dict:
        """Pack the staging arrays into the single-transfer mega-buffer,
        ship it, and unpack device-side.  ONE transfer: the tunnel charges
        ~40ms PER ARRAY in latency, so seven device_puts cost ~0.3s while
        the same bytes as a single int64 mega-buffer move in one round
        trip; the jitted unpack (slice + astype + bitcast + pad-to-v,
        memoized per layout) reconstructs the per-field device arrays."""
        vic_req = A["vic_req"]
        vu = vic_req.shape[1]
        r = vic_req.shape[2]
        pdb_allowed = np.full(n_pdbs, I32_MAX, np.int64)
        for i, pdb in enumerate(pdbs):
            pdb_allowed[i] = max(pdb.disruptions_allowed, 0)
        pdb_words = max(1, (n_pdbs + 63) // 64)
        vfeat = A["vfeat"]
        vf_keys = tuple(sorted(vfeat))
        vf_cols: list[tuple[str, int, tuple[int, ...]]] = []
        col = 4 + r + pdb_words  # prio, req[r], nonzero[2], start, pdb words
        layout: dict = {
            "r": r, "n_pdbs": n_pdbs, "pdb_words": pdb_words, "v": v,
        }
        for key_ in vf_keys:
            arr = vfeat[key_]
            width = 1 if arr.ndim == 2 else arr.shape[2]
            vf_cols.append((key_, width, arr.shape))
            col += width
        k_cols = col
        # One extra FINAL column carries pdb_allowed (written below) —
        # allocated upfront so nothing re-copies the multi-MB buffer.
        buf = np.zeros((n, vu, k_cols + 1), np.int64)
        self._pack_buf_rows(A, buf, slice(None), r, n_pdbs)
        # pdb_allowed rides in the DEDICATED final column, one value per
        # node row (buf[i, 0, -1] = allowed[i]) — no extra round trip.
        # Only possible while n_pdbs ≤ N; beyond that (more PDBs than node
        # rows — tiny clusters with many budgets) it pays its own transfer.
        inline_allowed = n_pdbs <= n
        if inline_allowed:
            buf[:n_pdbs, 0, -1] = pdb_allowed
        layout["vf_cols"] = tuple(vf_cols)
        d_buf = jax.device_put(buf)
        if st is not None:
            st["buf"], st["d_buf"] = buf, d_buf
            st["vf_cols"] = tuple(vf_cols)
            # Warm the dirty-row scatter program at its bucketed shapes so
            # the first incremental repack doesn't compile inside a
            # measured window (idempotent: rewrites row 0 with itself).
            for nb in (8, 64):
                rows0 = np.zeros(nb, np.int32)
                st["d_buf"] = _scatter_buf_rows(
                    st["d_buf"], rows0, np.broadcast_to(buf[0], (nb,) + buf.shape[1:])
                )
            d_buf = st["d_buf"]
        unpacked = _unpack_victims(d_buf, self._unpack_spec(layout))
        d_prio, d_vic_req, d_vic_nonzero, d_vic_start, d_pdb, d_allowed = (
            unpacked[:6]
        )
        if not inline_allowed:
            d_allowed = jax.device_put(pdb_allowed)
        d_vfeat = dict(zip(vf_keys, unpacked[6:]))
        return dict(
            profile=profile, active=active, pdbs=pdbs, n_pdbs=n_pdbs,
            matched_pdbs=matched_pdbs, per_node=per_node,
            d_prio=d_prio, d_vic_req=d_vic_req, d_vic_nonzero=d_vic_nonzero,
            d_vic_start=d_vic_start, d_vfeat=d_vfeat, d_pdb=d_pdb,
            d_allowed=d_allowed,
        )

    def preempt_batch(
        self,
        pods: list[t.Pod],
        batch_rows: dict,
        active: frozenset[str] | None = None,
        inv: dict | None = None,
        profile=None,
        candidate_filter=None,
        prepacked: dict | None = None,
        dry_run: bool = False,
    ) -> list[PreemptionResult | None]:
        """Run preemption for the failed pods of one scheduling batch.
        ``batch_rows`` are each pod's already-built feature dict rows.

        ``candidate_filter(pod, node_name, victims) -> bool`` vetoes a
        chosen candidate BEFORE its victims are deleted — the extender
        ProcessPreemption hook (preemption.go:249 callExtenders).  The
        reference consults extenders over the full candidate list before
        selection; the batched engine selects first and filters the one
        chosen candidate (divergence documented in extender.py).

        ``dry_run`` returns the chosen candidates WITHOUT applying them
        (no victim deletion, PDB debit, or nomination) — the fleet's
        cross-shard arbitration evaluates every shard's best candidate
        and executes only the global winner (fleet/router.py)."""
        sched = self.sched
        profile = profile or sched.profile
        cache, builder = sched.cache, sched.builder
        schema = builder.schema

        eligible = self._eligibility(pods, batch_rows.get("req"))
        if not any(eligible):
            return [None] * len(pods)

        pack = prepacked
        if (
            pack is None
            or pack["profile"] is not profile
            or pack["active"] != active
        ):
            pack = self.pack_victims(profile, active)
        pdbs, n_pdbs = pack["pdbs"], pack["n_pdbs"]
        matched_pdbs, per_node = pack["matched_pdbs"], pack["per_node"]
        # Stack the failed pods' feature rows into a (K, …) batch; mark
        # ineligible rows invalid so their step is a no-op.  K is always the
        # scheduler's batch size (failed ⊆ batch): ONE compiled shape, so a
        # 1-pod warm preemption covers the full-batch measured shape (the
        # variable-bucket shapes used to recompile inside the measured
        # window).  Idle padded steps are cheap relative to a recompile.
        k = self.sched.batch_size
        batch: dict = {}
        for key_, rows in batch_rows.items():
            stacked = np.stack(rows)
            pad = [(0, k - len(pods))] + [(0, 0)] * (stacked.ndim - 1)
            batch[key_] = np.pad(stacked, pad)
        batch["valid"] = np.zeros(k, np.bool_)
        batch["valid"][: len(pods)] = eligible
        # Chunk-sharing signature: pods with the same featurize-cache key
        # have identical dry-runs and may split one evaluation's node
        # ranking (build_preempt_pass step).  Reuses the memoized featurize
        # signature — these pods were just featurized by the failing batch.
        sigs, sig_first = self._sig_ids(pods, profile, k)
        batch["sig"] = sigs

        if inv is None:
            inv = builder.batch_invariants()
        state = builder.state()
        # Chunk like the scheduling pass (same dispatch-overhead economics);
        # a batch whose eligible preemptors ALL share one signature (the
        # async-preemption shape: N identical VIPs) runs as ONE rank-split
        # step (_chunk_for).
        chunk = self._chunk_for(sig_first, k)
        # ONE coalesced host→device transfer for the per-call inputs (the
        # victim tensors were shipped by pack_victims, possibly overlapped
        # with the failing batch's device pass).
        batch_d, inv_d = jax.device_put((batch, inv))
        out, _final_state, _final_prio = self._pass(profile, active, n_pdbs, chunk)(
            state, batch_d, inv_d, pack["d_prio"], pack["d_vic_req"],
            pack["d_vic_nonzero"], pack["d_vic_start"], pack["d_vfeat"],
            pack["d_pdb"], pack["d_allowed"],
        )
        picks, vmasks = device_fetch((out.picks, out.vic_mask))
        # Chunk-deferred preemptors (same-node collisions, heterogeneous
        # signatures, exhausted ranks) return None: the scheduler requeues
        # them and the NEXT chunked pass — against post-eviction truth — is
        # far cheaper than a sequential k-step re-scan here (the victims'
        # delete events wake them).

        return self._interpret_dryrun(
            pods, picks, vmasks, pack, candidate_filter, dry_run=dry_run
        )

    def _eligibility(self, pods, batch_req=None) -> list[bool]:
        """Cheap host-side prunes: (a) a pod whose demand exceeds every
        node's allocatable can never be helped by deletion; (b) a pod
        whose priority doesn't exceed the LOWEST bound-pod priority has
        no victims anywhere.  Both prevent repacking victim tensors for
        perma-stuck pods every batch (the Unschedulable-workload shape)."""
        cache, builder = self.sched.cache, self.sched.builder
        max_alloc = builder.host["alloc"].max(axis=0)
        max_allowed = int(builder.host["allowed_pods"].max(initial=0))
        min_prio = min(
            (pr.pod.spec.priority for pr in cache.pods.values()), default=None
        )

        def can_ever_fit(i: int, p: t.Pod) -> bool:
            if batch_req is not None:
                req = np.asarray(batch_req[i])  # already featurized this batch
            else:
                pr = cache.pods.get(p.uid)
                delta = pr.delta if pr else builder.pod_delta_vectors(p)
                req = delta["req"]
            return bool((req <= max_alloc[: req.shape[0]]).all()) and max_allowed >= 1

        return [
            p.spec.preemption_policy != t.PREEMPT_NEVER
            and min_prio is not None
            and p.spec.priority > min_prio
            and can_ever_fit(i, p)
            for i, p in enumerate(pods)
        ]

    def _sig_ids(self, pods, profile, k: int):
        """Chunk-sharing signatures (first-index representative ids) for
        the dry-run's rank-split, padded to k."""
        from .engine.features import pod_sig

        sig_first: dict = {}
        sigs = np.zeros(k, np.int32)
        for i, p in enumerate(pods):
            memo = getattr(p, "_featsig", None)
            if memo is not None:
                key_ = memo
            else:
                key_ = pod_sig(p)
            sigs[i] = sig_first.setdefault(key_, i)
        return sigs, sig_first

    def dispatch_speculative(self, ctx: dict, pack: dict):
        """Dispatch the dry-run CHAINED on the in-flight main pass's
        device-resident verdicts (valid = eligible ∧ pick < 0) — zero host
        round trips between the phases and no re-upload of the pod batch
        (ctx["batch_d"] is reused).  The dry-run sees the post-scan state
        (ctx["new_state"]); strict-tail commits land after dispatch, so
        the scheduler re-validates capacity before an INLINE commit of a
        speculative result (collect path) — nominate-and-retry results
        validate themselves on retry.  Returns a handle for
        collect_speculative, or None when speculation doesn't apply."""
        sched = self.sched
        if ctx.get("pinned") or "batch_d" not in ctx:
            return None
        infos, profile, active = ctx["infos"], ctx["profile"], ctx["active"]
        pods = [qp.pod for qp in infos]
        eligible = self._eligibility(pods, ctx["batch"].get("req"))
        if not any(eligible):
            return None
        k = sched.batch_size
        elig = np.zeros(k, np.bool_)
        elig[: len(pods)] = eligible
        sigs, sig_first = self._sig_ids(pods, profile, k)
        chunk = self._chunk_for(sig_first, k)
        fn = self._pass(profile, active, pack["n_pdbs"], chunk)
        # The scheduler's template-batch flag is a scalar the dry-run's
        # per-pod reshape cannot carry.
        batch_d = {
            k2: v for k2, v in ctx["batch_d"].items() if k2 != "uniform_all"
        }
        out, _fs, _fp = _chain_speculative(
            fn, ctx["new_state"], batch_d, ctx["result"].picks,
            jax.device_put((elig, sigs)), ctx["inv_d"], pack["d_prio"],
            pack["d_vic_req"], pack["d_vic_nonzero"], pack["d_vic_start"],
            pack["d_vfeat"], pack["d_pdb"], pack["d_allowed"],
        )
        return dict(out=out, pack=pack)

    def _chunk_for(self, sig_first: dict, k: int) -> int:
        """Dry-run chunking, shared by the sync and speculative paths (a
        divergence here would double the compiled-pass cache and split
        behavior for the same batch shape): uniform-signature batches
        collapse to ONE rank-split step; otherwise the scheduler's chunk
        clamped to 64, halved until it divides k."""
        if self.sched.chunk_size > 1 and len(sig_first) == 1:
            chunk = k
        else:
            chunk = min(
                self.sched.chunk_size if self.sched.chunk_size > 1 else 1, 64
            )
        chunk = max(1, min(chunk, k))
        while k % chunk:
            chunk //= 2
        return chunk

    def collect_speculative(
        self, spec: dict, fetched, failed_pods_by_index: dict
    ) -> dict:
        """Interpret speculative results for the batch indices that FAILED
        (scan or tail).  ``fetched`` = (picks, vic_mask) numpy arrays from
        the combined fetch; indices that placed in the strict tail are
        skipped (their dry-run was computed but never applied — pure
        compute, no side effects).  Returns {batch index: result}."""
        picks, vmasks = fetched
        idxs = sorted(failed_pods_by_index)
        pods = [failed_pods_by_index[i] for i in idxs]
        results = self._interpret_dryrun(
            pods, picks[idxs], vmasks[idxs], spec["pack"]
        )
        return dict(zip(idxs, results))

    def _interpret_dryrun(
        self, pods, picks, vmasks, pack, candidate_filter=None,
        dry_run: bool = False,
    ) -> list[PreemptionResult | None]:
        """prepareCandidate over fetched dry-run results: delete victims,
        nominate; consumed victims dedup across same-pass preemptors.
        Shared by the synchronous path and collect_speculative.  With
        ``dry_run`` the candidates are returned un-applied (see
        preempt_batch)."""
        sched = self.sched
        cache = sched.cache
        pdbs, matched_pdbs = pack["pdbs"], pack["matched_pdbs"]
        per_node = pack["per_node"]
        results: list[PreemptionResult | None] = []
        consumed: set[str] = set()
        for i, pod in enumerate(pods):
            pick = int(picks[i])
            if pick < 0 or pod is None:
                results.append(None)
                continue
            node_name = cache.node_name_at_row(pick)
            vics = per_node[pick]
            victims = [
                vics[j]
                for j in np.nonzero(vmasks[i])[0]
                if j < len(vics)
                and vics[j].spec.priority < pod.spec.priority
                and vics[j].uid not in consumed
            ]
            if candidate_filter is not None and not candidate_filter(
                pod, node_name, victims
            ):
                results.append(None)
                continue
            if dry_run:
                # Evaluation only: the fleet router compares this shard's
                # candidate against the other shards' before anything is
                # applied.  Victims still dedup within the pass so two
                # same-pass preemptors cannot both claim one victim.
                consumed.update(v.uid for v in victims)
                results.append(
                    PreemptionResult(node_name=node_name, victims=victims)
                )
                continue
            # prepareCandidate: delete victims, nominate the node.  The host
            # deltas mark rows dirty; the next state() flush re-syncs the
            # device (the in-scan release was resources-only).
            for vic in victims:
                consumed.add(vic.uid)
                # Full deletion path (DRA claim release, gang credit); the
                # caller fires ONE batched POD_DELETE for all victims.
                sched.delete_pod(vic.uid, notify=False)
                # Evicting a PDB-covered pod consumes its budget (the
                # disruption controller would rebuild DisruptionsAllowed;
                # in-process we decrement directly).
                for pi in matched_pdbs(vic):
                    pdbs[pi].disruptions_allowed -= 1
            pod.status.nominated_node_name = node_name
            results.append(PreemptionResult(node_name=node_name, victims=victims))
        return results
