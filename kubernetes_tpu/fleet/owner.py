"""One shard's owner: a TPUScheduler scoped to the shard's nodes behind
its own lease epoch and write-ahead journal.

The owner is deliberately thin — the scheduler already knows how to
evaluate, reserve, commit, journal, and recover; this class binds one
instance to a shard identity (the shard-map predicate installed as
``shard_guard``), a ``FileLease`` whose epoch fences the shard's
journal, and the fleet protocol surface the router drives:

- ``propose`` / ``commit`` / ``reserve`` / ``commit_reserved`` /
  ``abort`` — the scatter-gather schedule + gang 2PC halves
  (scheduler.propose_pod and friends);
- ``preempt_propose`` / ``preempt_execute`` — the cross-shard
  preemption halves (a partition cannot pick a victim on a foreign
  shard locally);
- ``export_nodes`` / ``import_nodes`` — the journaled handoff payload
  (split/merge/rebalance/takeover move nodes WITH their bound pods,
  and the acquiring owner write-ahead journals every imported binding
  so its shard stays self-contained for the next failover).

``fleet_dispatch`` is the single wire entry point: the sidecar server's
``fleet`` Envelope frame routes ``{op, payload}`` JSON here, so an
owner process started with ``serve --shard-of k/N`` speaks the same
protocol as an in-process owner."""

from __future__ import annotations

import os
import time

from ..api import serialize, types as t
from ..framework.leaderelection import FileLease, read_epoch
from ..framework.metrics import pod_tenant
from ..framework.tracing import Trace
from ..journal import Journal, recover as journal_recover
from .shardmap import ShardMap


class ShardOwner:
    def __init__(
        self,
        shard_id: int,
        scheduler,
        shard_map: ShardMap | None = None,
        state_dir: str | None = None,
        journal_fsync: bool = True,
        snapshot_every_batches: int = 8,
        lifecycle: dict | None = None,
        observability: bool = True,
    ) -> None:
        self.shard_id = shard_id
        self.sched = scheduler
        self.shard_map = shard_map
        self.state_dir = state_dir
        # Observability surface (ISSUE 12): per-op flight records on the
        # scheduler's ring (logical-clock-stamped, merged fleet-wide by
        # framework/flight.merge_fleet), op spans joining the router's
        # trace, and per-tenant commit tracking.  Purely observational —
        # off, the owner binds bit-identically.
        self.observability = observability
        # The current fleet op's span (router trace context) and logical
        # clock, set per dispatch by fleet_dispatch.
        self._op_span: Trace | None = None
        self._op_lc: float | None = None
        # Monotone per-tenant commit counts (bounded label space via the
        # scheduler's tenant labeler) — `fleet status`'s tenants block.
        self.tenant_commits: dict[str, int] = {}
        self.lease: FileLease | None = None
        self.journal: Journal | None = None
        self.recovery_stats: dict | None = None
        # Mirrored weighted-fair admission document (router push via
        # set_admission) — weights/caps + the router's status snapshot.
        self.admission_doc: dict | None = None
        self.handoffs_in = 0
        self.handoffs_out = 0
        # Monotone commit counter — the owner-side load signal the
        # autoscaler's wire probes diff (`stats`'s ``load`` block):
        # commits made HERE, not bindings adopted via handoff import,
        # so a transfer never reads as served traffic.
        self.commits_total = 0
        # Evictions the shard's OWN controllers decided (node-lifecycle
        # taint eviction, pod GC): the owner's local queue is never
        # drained by the router, so the evicted pod rides the next fleet
        # response back to the router, which requeues it fleet-wide —
        # the cross-shard half of the failure-response loop.  Journal
        # replay routes here too (takeover surfaces crash-interrupted
        # evictions instead of stranding them).
        self.evictions_out: list[dict] = []
        # Replay-surfaced evictions (journal recovery re-applied an
        # ``evict`` record): held apart from the live buffer so they
        # NEVER ride an ordinary response during the takeover's host-
        # truth re-feed — only the adopting router's explicit
        # drain_evictions takes them, and that path filters entries
        # whose pod already rebound (a later bind record).
        self.recovered_evictions: list[dict] = []
        scheduler.eviction_requeue_hook = self._on_eviction
        # Per-owner failure-response loop: the shard's lifecycle
        # controller judges ITS nodes from the Lease frames the router
        # routes here.  Armed BEFORE recovery — replayed taint/evict
        # records must apply under the armed clock semantics.
        if lifecycle and lifecycle.get("node_grace_s", 0) > 0:
            grace = float(lifecycle["node_grace_s"])
            scheduler.node_lifecycle.arm(
                grace_period_s=grace,
                unreachable_after_s=(
                    float(lifecycle.get("node_unreachable_s") or 0)
                    or grace * 2.5
                ),
            )
            scheduler.pod_gc.arm(
                gc_horizon_s=(
                    float(lifecycle.get("gc_horizon_s") or 0) or grace * 6
                )
            )
        if shard_map is not None:
            scheduler.shard_guard = (
                lambda name: shard_map.owner_of(name) == shard_id
            )
        if state_dir:
            os.makedirs(state_dir, exist_ok=True)
            lease_path = os.path.join(state_dir, "lease")
            self.lease = FileLease(
                lease_path, identity=f"shard{shard_id}-{os.getpid()}"
            )
            self.lease.acquire(block=True)
            self.journal = Journal(
                state_dir,
                epoch=self.lease.epoch,
                fence=lambda: read_epoch(lease_path),
                fsync=journal_fsync,
            )
            # Recover BEFORE arming the write-ahead hooks (the replay
            # drives the scheduler's own mutation surface).
            self.recovery_stats = journal_recover(scheduler, self.journal)
            scheduler.attach_journal(
                self.journal, snapshot_every_batches=snapshot_every_batches
            )
        # Journal-authored lifecycle taints must survive the takeover's
        # host-truth node re-feed (the apiserver would have carried the
        # controller's PATCH, so a relist delivers them; here the
        # replayed journal is that authority).  Same overlay contract as
        # informers.Reflector.recovered_taints, applied at the owner's
        # add surface because the fleet re-feed bypasses the Reflector.
        # `serve --shard-of` recovers through the SERVE journal AFTER
        # this constructor runs — SidecarServer refreshes the overlay
        # once its recovery completes.
        self._recovered_taints: dict[str, tuple] = {}
        self.refresh_recovered_taints()

    def refresh_recovered_taints(self) -> None:
        """Snapshot the journal-recovered lifecycle taints for the
        host-truth re-feed overlay.  Called at construction (the
        state_dir recovery path has already replayed by then) and again
        by SidecarServer after a `serve --shard-of` recovery (which runs
        AFTER this owner is built, against the serve journal)."""
        from ..controllers import LIFECYCLE_TAINT_KEYS

        for name, rec in self.sched.cache.nodes.items():
            recovered = tuple(
                taint
                for taint in rec.node.spec.taints
                if taint.key in LIFECYCLE_TAINT_KEYS
            )
            if recovered:
                self._recovered_taints[name] = recovered
        # A SNAPSHOTLESS replay holds taint records for nodes no store
        # entry carries (the WAL-only takeover) — their journaled taints
        # must survive the re-feed too, with observe_node's adoption
        # correcting the GC stamp to the recorded transition clock.
        for name, rec in getattr(
            self.sched, "_recovered_taint_stamps", {}
        ).items():
            taints = tuple(
                taint for taint in rec[0] if taint.key in LIFECYCLE_TAINT_KEYS
            )
            if taints and name not in self._recovered_taints:
                self._recovered_taints[name] = taints

    # -- the failure-response loop (per-owner lifecycle) -------------------

    def _on_eviction(self, uid: str, pod: t.Pod, reason: str) -> None:
        """scheduler.eviction_requeue_hook: buffer the evicted (now
        unbound) pod for the router — it rides the next fleet response
        (fleet_dispatch attaches ``evicted``) and requeues fleet-wide.
        PDB budgets are debited here and broadcast by the router, the
        same cluster-global bookkeeping a cross-shard preemption gets
        (taint eviction is a disruption like any other; the single
        scheduler sees every pod, so its disruption controller recomputes
        — a partition cannot, hence the explicit debit)."""
        debits = self.sched.debit_matching_pdbs(pod)
        bucket = (
            self.recovered_evictions
            if getattr(self.sched, "_in_recovery", False)
            else self.evictions_out
        )
        bucket.append(
            {
                "uid": uid,
                "pod": serialize.to_dict(pod),
                "reason": reason,
                "group": pod.spec.pod_group or "",
                "pdb_debits": [
                    {"name": n, "n": c} for n, c in sorted(debits.items())
                ],
            }
        )

    def drain_evictions(self) -> list[dict]:
        """Everything pending: the replay-surfaced bucket first (the
        incident predates whatever fired live since), then the live
        buffer."""
        out = self.recovered_evictions + self.evictions_out
        self.recovered_evictions = []
        self.evictions_out = []
        return out

    # -- object feed -------------------------------------------------------

    def add_object(self, kind: str, obj) -> None:
        if kind == "Node" and self._recovered_taints:
            recovered = self._recovered_taints.pop(obj.name, None)
            if recovered:
                import copy

                from ..controllers import LIFECYCLE_TAINT_KEYS

                obj = copy.deepcopy(obj)
                obj.spec.taints = tuple(
                    taint
                    for taint in obj.spec.taints
                    if taint.key not in LIFECYCLE_TAINT_KEYS
                ) + tuple(recovered)
        getattr(self.sched, serialize.KINDS[kind][1])(obj)

    def remove_object(self, kind: str, uid: str) -> dict | None:
        """Returns the freed-capacity summary for a Pod delete (the
        router's POD_DELETE wake hint — only this owner can see the
        node's host arrays), or — for a Node delete — the identities of
        the bound pods that vanished with it, so the router can purge
        its routing entries and debit fleet-wide gang credit."""
        if kind == "Node":
            dropped = [
                pr.pod
                for pr in self.sched.cache.pods.values()
                if pr.bound and pr.node_name == uid
            ]
            self.sched.remove_node(uid)
            return {
                "dropped": sorted(p.uid for p in dropped),
                "dropped_groups": sorted(
                    p.spec.pod_group for p in dropped if p.spec.pod_group
                ),
            }
        if kind == "Pod":
            pr = self.sched.cache.pods.get(uid)
            node = pr.node_name if pr is not None else None
            self.sched.delete_pod(uid)
            return self.sched.fleet_free_ctx([node]) if node else None
        raise ValueError(f"cannot remove kind {kind}")

    # -- the scatter-gather schedule surface -------------------------------

    def _tenant_label(self, pod: t.Pod) -> str:
        """The pod's BOUNDED tenant label (the scheduler's labeler when
        attribution is armed; the raw-or-fallback value otherwise never
        leaves this owner's in-memory stats)."""
        tm = self.sched.tenant_metrics
        if tm is not None:
            return tm.labeler.label_for(pod_tenant(pod))
        return pod_tenant(pod) or "-"

    def _flight_op(self, op: str, pod: t.Pod, rec: dict) -> None:
        """One per-op flight record on the scheduler's ring: shard- and
        logical-clock-stamped so merge_fleet can interleave every owner's
        log into one fleet timeline."""
        rec.update(op=op, shard=self.shard_id)
        if self._op_lc is not None:
            rec["lc"] = self._op_lc
        self.sched.flight.record_batch(rec)

    def propose(self, pod: t.Pod) -> dict:
        if not self.observability:
            return self.sched.propose_pod(pod)
        t0 = time.perf_counter()
        span = self._op_span
        res = self.sched.propose_pod(pod, span=span)
        feat_s = res.get("feat_s", 0.0)
        dev_s = res.get("dev_s", 0.0)
        self._flight_op(
            "propose",
            pod,
            {
                "pods": 1,
                "scheduled": 0,
                "wall_s": round(time.perf_counter() - t0, 6),
                "phases": {"featurize": feat_s, "device": dev_s},
            },
        )
        return res

    def explain(
        self, uid: str, pod_data: dict | None = None, seq: int | None = None
    ) -> dict:
        """Decision-provenance readout for this shard's partition
        (scheduler.explain_pod): the local record when the pod lives
        here (plus its serialized pod so the router can scatter), else
        an attribution run of the supplied pod against this shard's
        nodes — the router's merge path.  Read-only."""
        out: dict = {"shard": self.shard_id}
        pr = self.sched.cache.pods.get(uid)
        qp = self.sched.queue._info.get(uid)
        if pr is not None or qp is not None:
            out["record"] = self.sched.explain_pod(uid, seq=seq or None)
            out["pod"] = serialize.to_dict(pr.pod if pr is not None else qp.pod)
            if pr is not None:
                out["bound_node"] = pr.node_name
        elif pod_data is not None:
            pod = serialize.pod_from_data(pod_data)
            # The binding shard serialized its committed copy: strip the
            # binding so NodeName cannot pin the pod to a node this
            # shard does not own.
            pod.spec.node_name = ""
            out["record"] = self.sched.explain_pod(uid, pod=pod)
        else:
            out["record"] = {"uid": uid, "error": "not on this shard"}
        return out

    def commit(self, pod: t.Pod, node_name: str):
        t0 = time.perf_counter()
        out = self.sched.commit_proposed(pod, node_name)
        bound = out is not None and out.node_name
        tlabel = None
        if bound:
            self.commits_total += 1
            if self.observability:
                tlabel = self._tenant_label(pod)
                self.tenant_commits[tlabel] = (
                    self.tenant_commits.get(tlabel, 0) + 1
                )
        if self.observability:
            wall = round(time.perf_counter() - t0, 6)
            rec = {
                "pods": 1,
                "scheduled": 1 if bound else 0,
                "wall_s": wall,
                "phases": {"commit": wall},
            }
            if tlabel is not None:
                rec["tenant"] = tlabel
            if bound:
                # The bounded workload-class|accel key of this bind —
                # the fleet-mode input framework/measured.py folds into
                # measured throughput rows (merge_fleet keeps it on the
                # deterministic timeline).
                hkey = self.sched.hetero_bind_key(pod, node_name)
                if hkey is not None:
                    rec["hetero"] = {hkey: 1}
            self._flight_op("commit", pod, rec)
        return out

    def reserve(self, pod: t.Pod, node_name: str, gang: str) -> bool:
        return self.sched.reserve_proposed(pod, node_name, gang=gang)

    def commit_reserved(self, uid: str):
        t0 = time.perf_counter()
        out = self.sched.commit_reserved(uid)
        if out is not None and out.node_name:
            self.commits_total += 1
            if self.observability:
                tlabel = self._tenant_label(out.pod)
                self.tenant_commits[tlabel] = (
                    self.tenant_commits.get(tlabel, 0) + 1
                )
                rec = {
                    "pods": 1,
                    "scheduled": 1,
                    "tenant": tlabel,
                    "wall_s": round(time.perf_counter() - t0, 6),
                    "phases": {
                        "commit": round(time.perf_counter() - t0, 6)
                    },
                }
                hkey = self.sched.hetero_bind_key(out.pod, out.node_name)
                if hkey is not None:
                    rec["hetero"] = {hkey: 1}
                self._flight_op("commit_reserved", out.pod, rec)
        return out

    def abort(self, uid: str) -> None:
        self.sched.abort_reserved(uid)

    def preempt_propose(self, pod: t.Pod) -> dict | None:
        return self.sched.preempt_propose(pod)

    def preempt_execute(
        self, pod: t.Pod, node_name: str, victim_uids: list[str]
    ) -> dict:
        return self.sched.execute_preemption(pod, node_name, victim_uids)

    # -- handoff (split / merge / rebalance / takeover) --------------------

    def export_nodes(self, names: list[str]) -> dict:
        """Serialize the named nodes + their bound pods for a handoff.
        The exporting side drops them AFTER the acquiring side has
        journaled the import (the router orchestrates the order)."""
        nodes, pods = [], []
        for name in names:
            rec = self.sched.cache.nodes.get(name)
            if rec is None:
                continue
            nodes.append(serialize.to_dict(rec.node))
            for pr in self.sched.cache.pods.values():
                if pr.bound and pr.node_name == name:
                    pods.append(
                        {"pod": serialize.to_dict(pr.pod), "node": name}
                    )
        return {"nodes": nodes, "pods": pods}

    def drop_nodes(self, names: list[str]) -> None:
        """The exporting half's release: forget the nodes (and with them
        their bound pods) once the acquiring owner holds them durably."""
        for name in names:
            if name in self.sched.cache.nodes:
                self.sched.remove_node(name)
        self.handoffs_out += 1
        if self.observability:
            fields = {"shard": self.shard_id, "nodes": len(names)}
            if self._op_lc is not None:
                fields["lc"] = self._op_lc
            self.sched.flight.record_marker("handoff_out", **fields)

    def import_nodes(self, record: dict, payload: dict) -> None:
        """The acquiring half: journal the handoff record FIRST (a crash
        after the append and before the map write is redone from the
        journal — shardmap.py), then apply the transfer.  The WAL rule
        (analysis/rules_wal.py) machine-checks this ordering: the
        apply_handoff marker must be dominated by a journal append."""
        from .. import journal as _journal

        sched = self.sched
        sched._journal_append("handoff", **record)
        # The post-journal/pre-import window (faults.KILL_POINTS
        # "post-handoff-append", ISSUE 11): the record is durable but no
        # node has moved — takeover redoes the lost map write from the
        # journal and the host-truth re-feed routes the nodes here.
        _journal._crash("post-handoff-append")
        self.apply_handoff(payload)

    def apply_handoff(self, payload: dict) -> None:
        """Make a journaled handoff live: adopt the nodes, then journal +
        apply every transferred binding so this shard's journal alone can
        reproduce its state at the next failover."""
        sched = self.sched
        for data in payload.get("nodes", ()):
            node = serialize.build(serialize.KINDS["Node"][0], data)
            sched.add_node(node)
        for entry in payload.get("pods", ()):
            pod = serialize.pod_from_data(entry["pod"])
            pod.spec.node_name = entry["node"]
            sched._journal_bind(pod, entry["node"])
            sched.add_pod(pod)
        self.handoffs_in += 1
        if self.observability:
            fields = {
                "shard": self.shard_id,
                "nodes": len(payload.get("nodes", ())),
                "pods": len(payload.get("pods", ())),
            }
            if self._op_lc is not None:
                fields["lc"] = self._op_lc
            self.sched.flight.record_marker("handoff_in", **fields)

    def apply_recovered_bindings(self) -> int:
        """Journal bind records whose node was unknown at replay time
        (scheduler._recovered_bindings) re-apply once the host-truth
        relist delivered the node — the shard-local half of
        informers.reconcile_after_recovery.  Bindings whose node never
        relisted are dropped (the node is truly gone; the pods
        reschedule through the router)."""
        sched = self.sched
        pending = getattr(sched, "_recovered_bindings", None) or {}
        applied = 0
        for uid, d in sorted(pending.items()):
            if d["node"] in sched.cache.nodes:
                pod = serialize.pod_from_data(d["pod"])
                pod.spec.node_name = d["node"]
                sched.add_pod(pod)
                applied += 1
            pending.pop(uid, None)
        return applied

    def set_map(self, doc: dict) -> None:
        """Adopt a shard-map revision the router is ABOUT to make durable
        (an autoscaler resize): the guard must agree with the new
        ownership before the import lands — a wire owner spawned for a
        split-created shard otherwise rejects every imported node (its
        file-loaded map predates the split), and the losing owner's
        guard must start refusing moved nodes once the drop completes.
        Nothing durable happens here: the map FILE is still written by
        the orchestrating router at the handoff's version (after the
        journaled imports), so a crash before that write leaves the old
        map and takeover's redo converges as ever.  Idempotent; a stale
        doc (older version than the one held) is ignored."""
        held = self.shard_map
        if held is not None and doc.get("version", 0) < held.version:
            return
        new_map = ShardMap(
            buckets=doc["buckets"],
            overrides=doc.get("overrides", {}),
            version=doc.get("version", 0),
            epoch=doc.get("epoch", 0),
        )
        self.shard_map = new_map
        sid = self.shard_id
        self.sched.shard_guard = (
            lambda name: new_map.owner_of(name) == sid
        )

    def set_admission(self, doc: dict) -> None:
        """Mirror the router's weighted-fair admission document (the
        set_map-style push): inherit the fleet weights into this owner's
        OWN armed policy, if any (a shard scheduling its local queue
        under fairness must agree with the fleet on accelerator-time
        shares), and hold the document — including the router's
        per-tenant status snapshot — for the stats surface, where
        `fleet status --sockets` renders the fairness view.  Idempotent;
        nothing durable (weights re-push on every arm/update)."""
        self.admission_doc = dict(doc)
        adm = getattr(self.sched.queue, "admission", None)
        if adm is not None:
            adm.set_weights(doc.get("weights", {}))

    # -- cluster-global side effects mirrored locally ----------------------

    def debit_pdb(self, name: str, n: int) -> None:
        self.sched.apply_pdb_debit(name, n)

    def free_ctx(self, names: list[str]) -> dict | None:
        return self.sched.fleet_free_ctx(names)

    # -- the uniform call surface ------------------------------------------

    def call(self, op: str, payload: dict) -> dict:
        """The router's single entry point — identical semantics whether
        the owner is in-process (here) or behind the sidecar socket
        (WireShardOwner): JSON-dict in, JSON-dict out."""
        return fleet_dispatch(self, op, payload)

    # -- observability -----------------------------------------------------

    def bindings(self) -> dict:
        return {
            uid: pr.node_name
            for uid, pr in sorted(self.sched.cache.pods.items())
            if pr.bound
        }

    def stats(self) -> dict:
        # serve --shard-of owners journal through the SERVE journal
        # (scheduler.attach_journal), not an owner-held one — report
        # whichever is armed.
        journal = self.journal or getattr(self.sched, "journal", None)
        out = {
            "shard": self.shard_id,
            "nodes": len(self.sched.cache.nodes),
            "bound_pods": sum(
                1 for pr in self.sched.cache.pods.values() if pr.bound
            ),
            "rejected_nodes": self.sched.shard_rejected_nodes,
            "handoffs_in": self.handoffs_in,
            "handoffs_out": self.handoffs_out,
            # The autoscaler's owner-side load signal: monotone commit
            # count (wire probes diff successive reads into a window
            # rate) — handoff-imported bindings excluded by design.
            "load": {"commits_total": self.commits_total},
            # Per-tenant commit skew (`fleet status`'s tenants block):
            # top-K tenants by monotone commit count, bounded label
            # space (the scheduler's tenant labeler).  Operators diff
            # successive reads for a window view, same as `load`.
            "tenants": {
                "top": sorted(
                    self.tenant_commits.items(),
                    key=lambda kv: (-kv[1], kv[0]),
                )[:5],
                "distinct": len(self.tenant_commits),
                "commits_total": sum(self.tenant_commits.values()),
            },
            "epoch": (
                self.lease.epoch
                if self.lease
                else getattr(journal, "epoch", 0)
            ),
            # Per-owner failure-response state (`fleet status` renders
            # this): armed flag, ready/notready/unreachable counts, the
            # logical clock, eviction/GC counters, pending requeues the
            # router has not yet drained.
            "lifecycle": {
                "armed": self.sched.node_lifecycle.armed,
                "states": self.sched.node_lifecycle.stats()["states"],
                "logical_now": self.sched.node_lifecycle.now(),
                "transitions": self.sched.node_lifecycle.transitions,
                "taint_evictions": self.sched.taint_eviction.evictions,
                "pod_gc_collected": dict(self.sched.pod_gc.collected),
                "pending_eviction_requeues": (
                    len(self.evictions_out) + len(self.recovered_evictions)
                ),
            },
        }
        if journal is not None:
            out["journal"] = journal.stats()
        if self.recovery_stats is not None:
            out["recovery"] = self.recovery_stats
        if self.admission_doc is not None:
            # The mirrored fairness view (router push, set_admission):
            # weights/caps plus the per-tenant status snapshot as of the
            # last push — credit balances, virtual-time lag, SLO verdicts.
            out["fairness"] = self.admission_doc
        return out

    def close(self) -> None:
        if self.journal is not None:
            self.journal.close()
        if self.lease is not None:
            self.lease.release()


# Ops whose handling can FIRE controller evictions on the owner (a Lease
# renewal ticking the lifecycle loop, a taint-carrying node update, a
# commit onto a NoExecute-tainted node, an imported incident, a replayed
# journal surfacing at reconcile): their responses carry the drained
# eviction buffer so the router requeues fleet-wide without an extra
# round trip.  Read-only ops (stats/bindings/propose) never drain — a
# CLI probe must not swallow evictions the router is owed.
_EVICTION_BEARING_OPS = frozenset(
    {
        "add",
        "remove",
        "tick",
        "import_nodes",
        "reconcile",
        "commit",
        "commit_reserved",
        "preempt_execute",
    }
)


def fleet_dispatch(owner: ShardOwner, op: str, payload: dict) -> dict:
    """The wire entry point: one ``fleet`` Envelope frame = one op.
    Pods ride as canonical JSON dicts (the AddObject convention); every
    response is a JSON-clean dict.

    Observability envelope keys (popped before dispatch, all optional):
    ``trace_id``/``parent_span_id`` — the router's span context; the op
    runs under an owner-side span that joins the router's trace (its
    serialized tree rides back as ``_span``, so the router's slow-span
    dump shows the complete router→owner→sidecar path) — and ``lc``, the
    router's logical clock, stamped onto the owner's flight records so
    merge_fleet interleaves per-owner logs deterministically."""
    # A `serve --standby` child parks a StandbyServe shim here until
    # adopted (ISSUE 18): it answers standby_status/adopt_shard itself
    # and, once the real ShardOwner exists, delegates every op straight
    # back through this dispatcher.
    hook = getattr(owner, "standby_dispatch", None)
    if hook is not None:
        return hook(op, dict(payload))
    payload = dict(payload)
    trace_id = payload.pop("trace_id", None)
    parent_span_id = payload.pop("parent_span_id", None)
    lc = payload.pop("lc", None)
    span = None
    if trace_id and owner.observability:
        span = Trace(
            f"FleetOp:{op}",
            threshold_s=getattr(owner.sched, "trace_threshold_s", 2.0),
            trace_id=trace_id,
            parent_span_id=parent_span_id,
            on_slow=owner.sched._note_slow_span,
            shard=owner.shard_id,
        )
    owner._op_span = span
    owner._op_lc = lc if lc is not None else owner._op_lc
    try:
        res = _dispatch_op(owner, op, payload)
    finally:
        owner._op_span = None
        if span is not None:
            span.end()
            span.log_if_long()
    if span is not None:
        res = dict(res)
        res["_span"] = span.as_dict()
    if owner.evictions_out and op in _EVICTION_BEARING_OPS:
        # Live evictions only — the recovered bucket waits for the
        # explicit drain (its staleness filter needs adopted routing).
        # COPIED, not cleared: the buffer empties only on the router's
        # ``ack_evictions`` — a response lost to a deadline would
        # otherwise take the only copy with it, and the idempotent
        # retry's empty response would leave the pod unbound forever.
        # Re-delivery is safe: the router dedupes on evicted_pending.
        res = dict(res)
        res["evicted"] = list(owner.evictions_out)
    return res


def _dispatch_op(owner: ShardOwner, op: str, payload: dict) -> dict:
    if op == "drain_evictions":
        # The explicit drain (router takeover/adopt): crash-interrupted
        # evictions the journal replay re-surfaced come back to whichever
        # router adopts the shard.  Copied, ack-cleared — like the live
        # attach above.
        return {"evicted": owner.recovered_evictions + owner.evictions_out}
    if op == "ack_evictions":
        # The router durably absorbed (queued or staleness-filtered)
        # these evictions; stop re-delivering them.  Idempotent.
        acked = set(payload.get("uids", ()))
        owner.evictions_out = [
            e for e in owner.evictions_out if e["uid"] not in acked
        ]
        owner.recovered_evictions = [
            e for e in owner.recovered_evictions if e["uid"] not in acked
        ]
        return {}
    if op == "tick":
        # A fleet-wide logical-clock advance (the router saw a renewal
        # elsewhere): judge this shard's nodes at the new clock.  No-op
        # while disarmed — and for an armed shard this is exactly how a
        # shard whose only leased node died learns that time passed.
        return {
            "fired": owner.sched.node_lifecycle.tick(payload.get("now"))
        }
    if op == "propose":
        return owner.propose(serialize.pod_from_data(payload["pod"]))
    if op == "explain":
        return owner.explain(
            payload["uid"], payload.get("pod"), payload.get("seq")
        )
    if op == "commit":
        o = owner.commit(
            serialize.pod_from_data(payload["pod"]), payload["node"]
        )
        return {"bound": o.node_name if o is not None else None}
    if op == "reserve":
        ok = owner.reserve(
            serialize.pod_from_data(payload["pod"]),
            payload["node"],
            payload.get("gang", ""),
        )
        return {"ok": ok}
    if op == "commit_reserved":
        o = owner.commit_reserved(payload["uid"])
        return {"bound": o.node_name if o is not None else None}
    if op == "abort":
        owner.abort(payload["uid"])
        return {}
    if op == "preempt_propose":
        cand = owner.preempt_propose(serialize.pod_from_data(payload["pod"]))
        return cand if cand is not None else {}
    if op == "preempt_execute":
        return owner.preempt_execute(
            serialize.pod_from_data(payload["pod"]),
            payload["node"],
            payload.get("victims", []),
        )
    if op == "add":
        owner.add_object(
            payload["kind"],
            serialize.build(
                serialize.KINDS[payload["kind"]][0], payload["object"]
            ),
        )
        return {}
    if op == "remove":
        res = owner.remove_object(payload["kind"], payload["uid"])
        if payload["kind"] == "Node":
            return res or {}
        return {"freed": res} if res is not None else {}
    if op == "reconcile":
        return {"applied": owner.apply_recovered_bindings()}
    if op == "pdb_debit":
        owner.debit_pdb(payload["name"], payload["n"])
        return {}
    if op == "free_ctx":
        ctx = owner.free_ctx(payload["names"])
        return ctx if ctx is not None else {}
    if op == "export_nodes":
        return owner.export_nodes(payload["names"])
    if op == "drop_nodes":
        owner.drop_nodes(payload["names"])
        return {}
    if op == "import_nodes":
        owner.import_nodes(payload["record"], payload["payload"])
        return {}
    if op == "set_map":
        owner.set_map(payload["doc"])
        return {}
    if op == "set_admission":
        owner.set_admission(payload["doc"])
        return {}
    if op == "bindings":
        return {
            "bindings": owner.bindings(),
            # Per-gang bound counts on THIS shard — the router sums them
            # to rebuild fleet-wide quorum credit after a takeover.
            "gang_bound": dict(owner.sched.gang_bound),
        }
    if op == "stats":
        return owner.stats()
    raise ValueError(f"unknown fleet op {op!r}")


class FleetOwnerUnreachable(ConnectionError):
    """A wire shard owner exhausted its deadline/retry budget (hung, or
    dead and not coming back on reconnect).  The fleet's answer is
    TAKEOVER (fleet/takeover.py) — restart or survivor-absorb the shard
    behind an epoch bump — never host-side scheduling around it."""


# Ops a WireShardOwner must NOT blindly re-issue after a connection
# failure: the first attempt may have applied server-side (a commit that
# landed before the response was lost would double-assume on retry).
# The fleet-level recovery path — takeover + journal replay + idempotent
# re-feed — resolves their fate instead.
_NON_RETRIABLE_OPS = frozenset(
    {
        "commit",
        "commit_reserved",
        "reserve",
        "abort",
        "preempt_execute",
        "import_nodes",
        "drop_nodes",
        "pdb_debit",
    }
)


class WireShardOwner:
    """A shard owner behind the sidecar socket (``serve --shard-of``):
    the same ``call`` surface as an in-process ShardOwner, carried by the
    ``fleet`` Envelope frame (sidecar/server.py).  The router cannot tell
    the difference — which is the point: the in-process fleet the tests
    oracle against and the multi-process fleet an operator deploys run
    the same protocol.

    Every call is bounded by the client's per-call deadline; a timeout
    or dropped connection on an idempotent op reconnects and retries up
    to ``max_retries`` times (counted as ``scheduler_fleet_call_*``),
    then — or immediately for non-idempotent ops — raises
    ``FleetOwnerUnreachable`` so the driver degrades to takeover instead
    of wedging scatter-gather on one hung owner forever."""

    def __init__(
        self,
        client=None,
        *,
        path: str | None = None,
        deadline_s: float | None = None,
        max_retries: int = 2,
        registry=None,
        shard_id: int | None = None,
    ) -> None:
        if client is None:
            if path is None:
                raise ValueError("WireShardOwner needs a client or a path")
            from ..sidecar.server import SidecarClient

            client = SidecarClient(path, deadline_s=deadline_s)
        self.client = client  # SidecarClient / ResyncingClient
        self.path = path
        self.deadline_s = deadline_s
        self.max_retries = max_retries
        self.shard_id = shard_id
        if registry is None:
            from ..framework.metrics import MetricsRegistry

            registry = MetricsRegistry()
        self.registry = registry
        self._timeouts = registry.counter(
            "scheduler_fleet_call_timeouts_total",
            "Wire fleet-protocol calls that exceeded the per-call "
            "deadline, by op.",
        )
        self._retry_counter = registry.counter(
            "scheduler_fleet_call_retries_total",
            "Wire fleet-protocol calls re-issued after a timeout or "
            "dropped connection, by op.",
        )

    def _reconnect(self) -> None:
        from ..sidecar.server import SidecarClient

        try:
            self.client.close()
        except OSError:
            pass
        self.client = SidecarClient(self.path, deadline_s=self.deadline_s)

    def close(self) -> None:
        try:
            self.client.close()
        except OSError:
            pass

    def call(self, op: str, payload: dict) -> dict:
        from ..sidecar.server import DeadlineExceeded

        attempts = 0
        while True:
            try:
                return self.client.fleet(op, payload)
            except (ConnectionError, TimeoutError, OSError) as exc:
                if isinstance(exc, (DeadlineExceeded, TimeoutError)):
                    self._timeouts.inc(op=op)
                shard = (
                    f"shard {self.shard_id}"
                    if self.shard_id is not None
                    else "shard owner"
                )
                if (
                    op in _NON_RETRIABLE_OPS
                    or attempts >= self.max_retries
                    or self.path is None
                ):
                    err = FleetOwnerUnreachable(
                        f"{shard}: fleet op {op!r} failed after "
                        f"{attempts + 1} attempt(s) ({exc}) — take the "
                        "shard over"
                    )
                    err.shard_id = self.shard_id
                    raise err from exc
                attempts += 1
                self._retry_counter.inc(op=op)
                try:
                    self._reconnect()
                except OSError as rexc:
                    err = FleetOwnerUnreachable(
                        f"{shard}: reconnect for fleet op {op!r} refused "
                        f"({rexc}) — take the shard over"
                    )
                    err.shard_id = self.shard_id
                    raise err from rexc
