"""One shard's owner: a TPUScheduler scoped to the shard's nodes behind
its own lease epoch and write-ahead journal.

The owner is deliberately thin — the scheduler already knows how to
evaluate, reserve, commit, journal, and recover; this class binds one
instance to a shard identity (the shard-map predicate installed as
``shard_guard``), a ``FileLease`` whose epoch fences the shard's
journal, and the fleet protocol surface the router drives:

- ``propose`` / ``commit`` / ``reserve`` / ``commit_reserved`` /
  ``abort`` — the scatter-gather schedule + gang 2PC halves
  (scheduler.propose_pod and friends);
- ``preempt_propose`` / ``preempt_execute`` — the cross-shard
  preemption halves (a partition cannot pick a victim on a foreign
  shard locally);
- ``export_nodes`` / ``import_nodes`` — the journaled handoff payload
  (split/merge/rebalance/takeover move nodes WITH their bound pods,
  and the acquiring owner write-ahead journals every imported binding
  so its shard stays self-contained for the next failover).

``fleet_dispatch`` is the single wire entry point: the sidecar server's
``fleet`` Envelope frame routes ``{op, payload}`` JSON here, so an
owner process started with ``serve --shard-of k/N`` speaks the same
protocol as an in-process owner."""

from __future__ import annotations

import os

from ..api import serialize, types as t
from ..framework.leaderelection import FileLease, read_epoch
from ..journal import Journal, recover as journal_recover
from .shardmap import ShardMap


class ShardOwner:
    def __init__(
        self,
        shard_id: int,
        scheduler,
        shard_map: ShardMap | None = None,
        state_dir: str | None = None,
        journal_fsync: bool = True,
        snapshot_every_batches: int = 8,
    ) -> None:
        self.shard_id = shard_id
        self.sched = scheduler
        self.shard_map = shard_map
        self.state_dir = state_dir
        self.lease: FileLease | None = None
        self.journal: Journal | None = None
        self.recovery_stats: dict | None = None
        self.handoffs_in = 0
        self.handoffs_out = 0
        if shard_map is not None:
            scheduler.shard_guard = (
                lambda name: shard_map.owner_of(name) == shard_id
            )
        if state_dir:
            os.makedirs(state_dir, exist_ok=True)
            lease_path = os.path.join(state_dir, "lease")
            self.lease = FileLease(
                lease_path, identity=f"shard{shard_id}-{os.getpid()}"
            )
            self.lease.acquire(block=True)
            self.journal = Journal(
                state_dir,
                epoch=self.lease.epoch,
                fence=lambda: read_epoch(lease_path),
                fsync=journal_fsync,
            )
            # Recover BEFORE arming the write-ahead hooks (the replay
            # drives the scheduler's own mutation surface).
            self.recovery_stats = journal_recover(scheduler, self.journal)
            scheduler.attach_journal(
                self.journal, snapshot_every_batches=snapshot_every_batches
            )

    # -- object feed -------------------------------------------------------

    def add_object(self, kind: str, obj) -> None:
        getattr(self.sched, serialize.KINDS[kind][1])(obj)

    def remove_object(self, kind: str, uid: str) -> dict | None:
        """Returns the freed-capacity summary for a Pod delete (the
        router's POD_DELETE wake hint — only this owner can see the
        node's host arrays), or — for a Node delete — the identities of
        the bound pods that vanished with it, so the router can purge
        its routing entries and debit fleet-wide gang credit."""
        if kind == "Node":
            dropped = [
                pr.pod
                for pr in self.sched.cache.pods.values()
                if pr.bound and pr.node_name == uid
            ]
            self.sched.remove_node(uid)
            return {
                "dropped": sorted(p.uid for p in dropped),
                "dropped_groups": sorted(
                    p.spec.pod_group for p in dropped if p.spec.pod_group
                ),
            }
        if kind == "Pod":
            pr = self.sched.cache.pods.get(uid)
            node = pr.node_name if pr is not None else None
            self.sched.delete_pod(uid)
            return self.sched.fleet_free_ctx([node]) if node else None
        raise ValueError(f"cannot remove kind {kind}")

    # -- the scatter-gather schedule surface -------------------------------

    def propose(self, pod: t.Pod) -> dict:
        return self.sched.propose_pod(pod)

    def commit(self, pod: t.Pod, node_name: str):
        return self.sched.commit_proposed(pod, node_name)

    def reserve(self, pod: t.Pod, node_name: str, gang: str) -> bool:
        return self.sched.reserve_proposed(pod, node_name, gang=gang)

    def commit_reserved(self, uid: str):
        return self.sched.commit_reserved(uid)

    def abort(self, uid: str) -> None:
        self.sched.abort_reserved(uid)

    def preempt_propose(self, pod: t.Pod) -> dict | None:
        return self.sched.preempt_propose(pod)

    def preempt_execute(
        self, pod: t.Pod, node_name: str, victim_uids: list[str]
    ) -> dict:
        return self.sched.execute_preemption(pod, node_name, victim_uids)

    # -- handoff (split / merge / rebalance / takeover) --------------------

    def export_nodes(self, names: list[str]) -> dict:
        """Serialize the named nodes + their bound pods for a handoff.
        The exporting side drops them AFTER the acquiring side has
        journaled the import (the router orchestrates the order)."""
        nodes, pods = [], []
        for name in names:
            rec = self.sched.cache.nodes.get(name)
            if rec is None:
                continue
            nodes.append(serialize.to_dict(rec.node))
            for pr in self.sched.cache.pods.values():
                if pr.bound and pr.node_name == name:
                    pods.append(
                        {"pod": serialize.to_dict(pr.pod), "node": name}
                    )
        return {"nodes": nodes, "pods": pods}

    def drop_nodes(self, names: list[str]) -> None:
        """The exporting half's release: forget the nodes (and with them
        their bound pods) once the acquiring owner holds them durably."""
        for name in names:
            if name in self.sched.cache.nodes:
                self.sched.remove_node(name)
        self.handoffs_out += 1

    def import_nodes(self, record: dict, payload: dict) -> None:
        """The acquiring half: journal the handoff record FIRST (a crash
        after the append and before the map write is redone from the
        journal — shardmap.py), then apply the transfer.  The WAL rule
        (analysis/rules_wal.py) machine-checks this ordering: the
        apply_handoff marker must be dominated by a journal append."""
        sched = self.sched
        sched._journal_append("handoff", **record)
        self.apply_handoff(payload)

    def apply_handoff(self, payload: dict) -> None:
        """Make a journaled handoff live: adopt the nodes, then journal +
        apply every transferred binding so this shard's journal alone can
        reproduce its state at the next failover."""
        sched = self.sched
        for data in payload.get("nodes", ()):
            node = serialize.build(serialize.KINDS["Node"][0], data)
            sched.add_node(node)
        for entry in payload.get("pods", ()):
            pod = serialize.pod_from_data(entry["pod"])
            pod.spec.node_name = entry["node"]
            sched._journal_bind(pod, entry["node"])
            sched.add_pod(pod)
        self.handoffs_in += 1

    def apply_recovered_bindings(self) -> int:
        """Journal bind records whose node was unknown at replay time
        (scheduler._recovered_bindings) re-apply once the host-truth
        relist delivered the node — the shard-local half of
        informers.reconcile_after_recovery.  Bindings whose node never
        relisted are dropped (the node is truly gone; the pods
        reschedule through the router)."""
        sched = self.sched
        pending = getattr(sched, "_recovered_bindings", None) or {}
        applied = 0
        for uid, d in sorted(pending.items()):
            if d["node"] in sched.cache.nodes:
                pod = serialize.pod_from_data(d["pod"])
                pod.spec.node_name = d["node"]
                sched.add_pod(pod)
                applied += 1
            pending.pop(uid, None)
        return applied

    # -- cluster-global side effects mirrored locally ----------------------

    def debit_pdb(self, name: str, n: int) -> None:
        self.sched.apply_pdb_debit(name, n)

    def free_ctx(self, names: list[str]) -> dict | None:
        return self.sched.fleet_free_ctx(names)

    # -- the uniform call surface ------------------------------------------

    def call(self, op: str, payload: dict) -> dict:
        """The router's single entry point — identical semantics whether
        the owner is in-process (here) or behind the sidecar socket
        (WireShardOwner): JSON-dict in, JSON-dict out."""
        return fleet_dispatch(self, op, payload)

    # -- observability -----------------------------------------------------

    def bindings(self) -> dict:
        return {
            uid: pr.node_name
            for uid, pr in sorted(self.sched.cache.pods.items())
            if pr.bound
        }

    def stats(self) -> dict:
        out = {
            "shard": self.shard_id,
            "nodes": len(self.sched.cache.nodes),
            "bound_pods": sum(
                1 for pr in self.sched.cache.pods.values() if pr.bound
            ),
            "rejected_nodes": self.sched.shard_rejected_nodes,
            "handoffs_in": self.handoffs_in,
            "handoffs_out": self.handoffs_out,
            "epoch": self.lease.epoch if self.lease else 0,
        }
        if self.journal is not None:
            out["journal"] = self.journal.stats()
        if self.recovery_stats is not None:
            out["recovery"] = self.recovery_stats
        return out

    def close(self) -> None:
        if self.journal is not None:
            self.journal.close()
        if self.lease is not None:
            self.lease.release()


def fleet_dispatch(owner: ShardOwner, op: str, payload: dict) -> dict:
    """The wire entry point: one ``fleet`` Envelope frame = one op.
    Pods ride as canonical JSON dicts (the AddObject convention); every
    response is a JSON-clean dict."""
    if op == "propose":
        return owner.propose(serialize.pod_from_data(payload["pod"]))
    if op == "commit":
        o = owner.commit(
            serialize.pod_from_data(payload["pod"]), payload["node"]
        )
        return {"bound": o.node_name if o is not None else None}
    if op == "reserve":
        ok = owner.reserve(
            serialize.pod_from_data(payload["pod"]),
            payload["node"],
            payload.get("gang", ""),
        )
        return {"ok": ok}
    if op == "commit_reserved":
        o = owner.commit_reserved(payload["uid"])
        return {"bound": o.node_name if o is not None else None}
    if op == "abort":
        owner.abort(payload["uid"])
        return {}
    if op == "preempt_propose":
        cand = owner.preempt_propose(serialize.pod_from_data(payload["pod"]))
        return cand if cand is not None else {}
    if op == "preempt_execute":
        return owner.preempt_execute(
            serialize.pod_from_data(payload["pod"]),
            payload["node"],
            payload.get("victims", []),
        )
    if op == "add":
        owner.add_object(
            payload["kind"],
            serialize.build(
                serialize.KINDS[payload["kind"]][0], payload["object"]
            ),
        )
        return {}
    if op == "remove":
        res = owner.remove_object(payload["kind"], payload["uid"])
        if payload["kind"] == "Node":
            return res or {}
        return {"freed": res} if res is not None else {}
    if op == "reconcile":
        return {"applied": owner.apply_recovered_bindings()}
    if op == "pdb_debit":
        owner.debit_pdb(payload["name"], payload["n"])
        return {}
    if op == "free_ctx":
        ctx = owner.free_ctx(payload["names"])
        return ctx if ctx is not None else {}
    if op == "export_nodes":
        return owner.export_nodes(payload["names"])
    if op == "drop_nodes":
        owner.drop_nodes(payload["names"])
        return {}
    if op == "import_nodes":
        owner.import_nodes(payload["record"], payload["payload"])
        return {}
    if op == "bindings":
        return {
            "bindings": owner.bindings(),
            # Per-gang bound counts on THIS shard — the router sums them
            # to rebuild fleet-wide quorum credit after a takeover.
            "gang_bound": dict(owner.sched.gang_bound),
        }
    if op == "stats":
        return owner.stats()
    raise ValueError(f"unknown fleet op {op!r}")


class WireShardOwner:
    """A shard owner behind the sidecar socket (``serve --shard-of``):
    the same ``call`` surface as an in-process ShardOwner, carried by the
    ``fleet`` Envelope frame (sidecar/server.py).  The router cannot tell
    the difference — which is the point: the in-process fleet the tests
    oracle against and the multi-process fleet an operator deploys run
    the same protocol."""

    def __init__(self, client) -> None:
        self.client = client  # SidecarClient / ResyncingClient

    def call(self, op: str, payload: dict) -> dict:
        return self.client.fleet(op, payload)
