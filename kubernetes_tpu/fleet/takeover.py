"""Takeover: a dead owner's shard comes back, bit-identically.

Two shapes, both fenced by the lease epoch:

- **Restart takeover** (``recover_shard``): a fresh process re-acquires
  the dead owner's lease — the flock is free the instant the holder dies,
  and the acquire bumps the fencing epoch, so a deposed owner that is
  merely wedged (not dead) can never append past its successor.
  ShardOwner construction then replays snapshot + write-ahead log
  (journal.recover): the same records produce the same state, which is
  what the shard-failover kill matrix asserts
  (scripts/run_fault_matrix.py --fleet-kill).

- **Survivor takeover** (``absorb_shard``): a surviving owner adopts the
  dead shard wholesale.  The dead journal is first recovered behind its
  own epoch bump (a ghost owner — nothing schedules on it), then the
  shard transfers through the SAME journaled handoff path a planned
  merge uses: ``shard_map.merge`` bumps the map version and yields the
  handoff record, the survivor journals it and imports the nodes with
  their bindings (each re-journaled into ITS log, so the survivor's
  journal alone reproduces the merged shard at the next failover), and
  only then is the map file rewritten.

A crash BETWEEN the handoff append and the map rewrite is the window
``redo_lost_map_writes`` closes: recovery surfaces journaled handoff
records (scheduler._recovered_handoffs); any record whose version
exceeds the on-disk map's is re-applied idempotently — the transfer
converges no matter where the writer died."""

from __future__ import annotations

from .owner import ShardOwner
from .shardmap import ShardMap, read_version


def redo_handoff(shard_map: ShardMap, record: dict) -> None:
    """Re-apply one journaled handoff record to a (possibly stale) map —
    the idempotent redo: records carry the full bucket/override delta, so
    applying one twice lands on the same map."""
    op = record["op"]
    if op in ("split", "merge"):
        for i in record.get("buckets", ()):
            shard_map.buckets[i] = record["to"]
        # A split that explicitly dropped the source's pins records the
        # names — the redo must replay the same choice (pins otherwise
        # SURVIVE a split; shardmap.split never silently remaps them).
        for n in record.get("pins_dropped", ()):
            shard_map.overrides.pop(n, None)
    elif op == "assign":
        for n in record.get("nodes", ()):
            shard_map.overrides[n] = record["to"]
    elif op == "rebalance":
        ids = sorted(
            record.get("ids") or range(max(record["n_shards"], 1))
        )
        shard_map.buckets = [
            ids[i % len(ids)] for i in range(len(shard_map.buckets))
        ]
        for n in record.get("pins_dropped", ()):
            shard_map.overrides.pop(n, None)
    shard_map.version = max(shard_map.version, record["version"])


def redo_lost_map_writes(owner: ShardOwner, map_path: str) -> int:
    """Close the append→rewrite crash window: every recovered handoff
    record newer than the on-disk map is redone and the map rewritten.
    Returns how many records were redone."""
    recovered = getattr(owner.sched, "_recovered_handoffs", None) or []
    disk_version = read_version(map_path)
    lost = [r for r in recovered if r["version"] > disk_version]
    if not lost:
        return 0
    shard_map = owner.shard_map or ShardMap.load(map_path)
    for rec in sorted(lost, key=lambda r: r["version"]):
        redo_handoff(shard_map, rec)
    shard_map.save(map_path)
    return len(lost)


def recover_shard(
    state_dir: str,
    scheduler_factory,
    shard_id: int,
    shard_map: ShardMap | None = None,
    map_path: str | None = None,
    lifecycle: dict | None = None,
) -> ShardOwner:
    """Restart takeover: re-own a dead owner's shard from its journal
    directory.  The lease acquire fences the deposed epoch; construction
    replays snapshot + WAL; lost map writes are redone.  The caller
    reconciles against the host-truth LIST afterwards
    (informers.reconcile_after_recovery) exactly like a single-scheduler
    restart — recovery parks journal bindings whose nodes the snapshot
    did not cover, and the relist re-applies them.  ``lifecycle``
    re-arms the per-owner failure-response loop BEFORE replay (an armed
    shard must recover armed, or replayed taint records would apply
    under disarmed clock semantics and a mid-incident death would stall
    at the taint); crash-interrupted evictions the replay re-surfaces
    sit in ``owner.evictions_out`` until the adopting router drains
    them (router.drain_evictions)."""
    owner = ShardOwner(
        shard_id,
        scheduler_factory(),
        shard_map,
        state_dir=state_dir,
        lifecycle=lifecycle,
    )
    if map_path:
        redo_lost_map_writes(owner, map_path)
    if shard_map is not None:
        # Enforce the (possibly just-redone) map on recovered state: a
        # crash between a handoff's import and the exporter's drop leaves
        # the SOURCE's snapshot still holding transferred nodes — the
        # guard only filters live adds, so takeover finishes the drop.
        for name in sorted(owner.sched.cache.nodes):
            if shard_map.owner_of(name) != shard_id:
                owner.sched.remove_node(name)
                owner.handoffs_out += 1
    return owner


def absorb_shard(
    survivor: ShardOwner,
    dead_state_dir: str,
    dead_shard_id: int,
    scheduler_factory,
    shard_map: ShardMap,
    map_path: str | None = None,
    lifecycle: dict | None = None,
) -> dict:
    """Survivor takeover: recover the dead shard behind an epoch bump,
    then merge it into the survivor through the journaled handoff path.
    The ghost replay may re-surface a mid-incident eviction (the dead
    owner journaled the evict but never handed the pod to a router) —
    those transfer to the SURVIVOR's eviction buffer, so the next router
    drain finishes the loop on whichever shard has room.  The dead
    shard's lifecycle bookkeeping (heartbeats, taints, GC clocks) rides
    the node objects and the survivor's own controller adopts it at
    import.  Returns the handoff record."""
    ghost = ShardOwner(
        dead_shard_id,
        scheduler_factory(),
        None,
        state_dir=dead_state_dir,
        lifecycle=lifecycle,
    )
    try:
        record = shard_map.merge(
            into=survivor.shard_id, absorbed=dead_shard_id
        )
        # Heartbeat history moves with the nodes — merged BEFORE the
        # import so the survivor's clock judges the adopted nodes
        # against their real last renewals, not as freshly unleased (a
        # dead node absorbed mid-incident must keep aging toward its
        # eviction/GC horizons).
        nl = survivor.sched.node_lifecycle
        for name, ts in sorted(ghost.sched.node_lifecycle.heartbeats.items()):
            if ts > nl.heartbeats.get(name, -1.0):
                nl.heartbeats[name] = ts
            if ts > nl._hw:
                nl._hw = ts
        payload = ghost.export_nodes(sorted(ghost.sched.cache.nodes))
        survivor.import_nodes(record, payload)
        # The import adopted unreachable state at the survivor's current
        # clock; the ghost's transition stamps are the true zero points
        # of the GC horizon — the earlier stamp wins.
        for name, ts in sorted(
            ghost.sched.pod_gc._unreachable_since.items()
        ):
            cur = survivor.sched.pod_gc._unreachable_since.get(name)
            if cur is None or ts < cur:
                survivor.sched.pod_gc._unreachable_since[name] = ts
        # The absorbed incident's pending requeues survive with the
        # survivor — in its RECOVERED bucket, so only the adopting
        # router's explicit drain (which filters entries whose pod
        # already rebound) takes them.  The ghost's LOCAL PDB debits died
        # with it, and the router's later broadcast skips the reporting
        # shard (it assumes the evicting owner debited itself) — so the
        # survivor applies them now, or its budget would permit one
        # disruption too many.
        moved = ghost.drain_evictions()
        for rec in moved:
            for debit in rec.get("pdb_debits", ()):
                survivor.sched.apply_pdb_debit(debit["name"], debit["n"])
        survivor.recovered_evictions.extend(moved)
        # Journal-authored lifecycle taints the ghost replayed must also
        # survive the SURVIVOR's next host-truth node re-feed.
        survivor._recovered_taints.update(ghost._recovered_taints)
        if map_path:
            shard_map.save(map_path)
    finally:
        ghost.close()
    return record
