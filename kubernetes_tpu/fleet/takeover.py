"""Takeover: a dead owner's shard comes back, bit-identically.

Two shapes, both fenced by the lease epoch:

- **Restart takeover** (``recover_shard``): a fresh process re-acquires
  the dead owner's lease — the flock is free the instant the holder dies,
  and the acquire bumps the fencing epoch, so a deposed owner that is
  merely wedged (not dead) can never append past its successor.
  ShardOwner construction then replays snapshot + write-ahead log
  (journal.recover): the same records produce the same state, which is
  what the shard-failover kill matrix asserts
  (scripts/run_fault_matrix.py --fleet-kill).

- **Survivor takeover** (``absorb_shard``): a surviving owner adopts the
  dead shard wholesale.  The dead journal is first recovered behind its
  own epoch bump (a ghost owner — nothing schedules on it), then the
  shard transfers through the SAME journaled handoff path a planned
  merge uses: ``shard_map.merge`` bumps the map version and yields the
  handoff record, the survivor journals it and imports the nodes with
  their bindings (each re-journaled into ITS log, so the survivor's
  journal alone reproduces the merged shard at the next failover), and
  only then is the map file rewritten.

A crash BETWEEN the handoff append and the map rewrite is the window
``redo_lost_map_writes`` closes: recovery surfaces journaled handoff
records (scheduler._recovered_handoffs); any record whose version
exceeds the on-disk map's is re-applied idempotently — the transfer
converges no matter where the writer died."""

from __future__ import annotations

from .owner import ShardOwner
from .shardmap import ShardMap, read_version


def redo_handoff(shard_map: ShardMap, record: dict) -> None:
    """Re-apply one journaled handoff record to a (possibly stale) map —
    the idempotent redo: records carry the full bucket/override delta, so
    applying one twice lands on the same map."""
    op = record["op"]
    if op in ("split", "merge"):
        for i in record.get("buckets", ()):
            shard_map.buckets[i] = record["to"]
    elif op == "assign":
        for n in record.get("nodes", ()):
            shard_map.overrides[n] = record["to"]
    elif op == "rebalance":
        n_shards = record["n_shards"]
        shard_map.buckets = [
            i % max(n_shards, 1) for i in range(len(shard_map.buckets))
        ]
        shard_map.overrides = {}
    shard_map.version = max(shard_map.version, record["version"])


def redo_lost_map_writes(owner: ShardOwner, map_path: str) -> int:
    """Close the append→rewrite crash window: every recovered handoff
    record newer than the on-disk map is redone and the map rewritten.
    Returns how many records were redone."""
    recovered = getattr(owner.sched, "_recovered_handoffs", None) or []
    disk_version = read_version(map_path)
    lost = [r for r in recovered if r["version"] > disk_version]
    if not lost:
        return 0
    shard_map = owner.shard_map or ShardMap.load(map_path)
    for rec in sorted(lost, key=lambda r: r["version"]):
        redo_handoff(shard_map, rec)
    shard_map.save(map_path)
    return len(lost)


def recover_shard(
    state_dir: str,
    scheduler_factory,
    shard_id: int,
    shard_map: ShardMap | None = None,
    map_path: str | None = None,
) -> ShardOwner:
    """Restart takeover: re-own a dead owner's shard from its journal
    directory.  The lease acquire fences the deposed epoch; construction
    replays snapshot + WAL; lost map writes are redone.  The caller
    reconciles against the host-truth LIST afterwards
    (informers.reconcile_after_recovery) exactly like a single-scheduler
    restart — recovery parks journal bindings whose nodes the snapshot
    did not cover, and the relist re-applies them."""
    owner = ShardOwner(
        shard_id, scheduler_factory(), shard_map, state_dir=state_dir
    )
    if map_path:
        redo_lost_map_writes(owner, map_path)
    if shard_map is not None:
        # Enforce the (possibly just-redone) map on recovered state: a
        # crash between a handoff's import and the exporter's drop leaves
        # the SOURCE's snapshot still holding transferred nodes — the
        # guard only filters live adds, so takeover finishes the drop.
        for name in sorted(owner.sched.cache.nodes):
            if shard_map.owner_of(name) != shard_id:
                owner.sched.remove_node(name)
                owner.handoffs_out += 1
    return owner


def absorb_shard(
    survivor: ShardOwner,
    dead_state_dir: str,
    dead_shard_id: int,
    scheduler_factory,
    shard_map: ShardMap,
    map_path: str | None = None,
) -> dict:
    """Survivor takeover: recover the dead shard behind an epoch bump,
    then merge it into the survivor through the journaled handoff path.
    Returns the handoff record."""
    ghost = ShardOwner(
        dead_shard_id, scheduler_factory(), None, state_dir=dead_state_dir
    )
    try:
        record = shard_map.merge(
            into=survivor.shard_id, absorbed=dead_shard_id
        )
        payload = ghost.export_nodes(sorted(ghost.sched.cache.nodes))
        survivor.import_nodes(record, payload)
        if map_path:
            shard_map.save(map_path)
    finally:
        ghost.close()
    return record
