"""Partitioned scheduler fleet: N scheduler processes, each owning a
disjoint node shard behind its own lease-epoch fence and WAL journal.

The single-process scheduler is fast (BENCH_r05: 10k pods/s at 5k
nodes), but millions of users means more than one scheduler process.
This package composes the primitives PRs 3–6 built — `FileLease` epoch
fencing, the write-ahead journal, the flight recorder, the soak harness
— into a horizontally scalable control plane, the shape Tesserae
(arxiv 2508.04953) gives placement policies: partition the cluster,
preserve the global constraints.

- ``shardmap``: the fsync'd, epoch-versioned shard-map file — which
  owner holds which nodes — with split/merge/rebalance and journaled
  handoff records.
- ``owner``: one shard's scheduler process: a TPUScheduler scoped to the
  shard's nodes behind its own lease epoch and journal, exposing the
  propose/commit/reserve protocol surface (in-process or over the
  sidecar Envelope wire via the ``fleet`` frame).
- ``router``: the thin fleet front door — assigns pods to shards by
  feasibility-aware hashing with a forwarding path for misroutes, and
  arbitrates the two decisions a partition cannot make locally:
  cross-shard preemption and gang admission spanning shards (two-phase
  reserve/commit with journaled intent records).
- ``takeover``: a dead owner's shard is taken over by a survivor with
  bit-identical journal replay behind an epoch bump.
- ``autoscaler``: the elastic half (ISSUE 11) — a deterministic
  load-driven control loop that watches per-shard binding-rate
  imbalance / queue depth / SLO / reachability on the logical clock
  and issues live split/merge/rebalance handoffs through the same
  journaled path, with hysteresis, cooldowns, and an actions-per-window
  budget so flapping load cannot thrash the map.

The oracle discipline carries over: an N-shard fleet binds
bit-identically to the single-scheduler run on the golden scenarios
(tests/test_fleet.py), and the SIGKILL crash matrix extends to shard
failover (scripts/run_fault_matrix.py --kill)."""

from .router import FleetRouter  # noqa: F401
from .shardmap import ShardMap  # noqa: F401
from .owner import (  # noqa: F401
    FleetOwnerUnreachable,
    ShardOwner,
    WireShardOwner,
    fleet_dispatch,
)
from .takeover import absorb_shard, recover_shard  # noqa: F401
from .standby import StandbyPool, StandbyServe  # noqa: F401
from .autoscaler import (  # noqa: F401
    AutoscalerConfig,
    FleetAutoscaler,
    choose_action,
    imbalance_ratios,
)
