"""The shard map: which owner holds which nodes, durably.

The fleet's ownership record is one fsync'd JSON file shared by every
owner and the router — the analog of the consistent-hash ring a
partitioned placement service keeps in its coordination store (Tesserae
partitions the cluster the same way).  Assignment is two-level:

- ``buckets``: a fixed-size array (B entries) of shard ids; a node maps
  to ``buckets[crc32(name) % B]``.  Fixed buckets make split/merge/
  rebalance a bucket-remapping, not a node-by-node migration plan, and
  crc32 (not builtin ``hash``) keeps the mapping identical across
  processes and PYTHONHASHSEED values.
- ``overrides``: explicit node → shard pins that beat the bucket rule
  (targeted rebalance, takeover pinning).

Every write is epoch-versioned and atomic: ``version`` increments
monotonically, ``epoch`` records the writer's lease epoch, and the file
lands via temp + fsync + ``os.replace`` + directory fsync, so a crash
mid-write leaves the previous map intact.  Readers reject a version
that moves backwards — a deposed owner replaying a stale map cannot
roll ownership back.

Handoffs (split/merge/rebalance/takeover) are JOURNALED by the
acquiring owner BEFORE the map file is rewritten (the WAL
journal-before-apply discipline): a crash between the append and the
replace leaves a handoff record whose ``version`` exceeds the file's,
and recovery redoes the idempotent rewrite — the transfer converges."""

from __future__ import annotations

import json
import os
import zlib

DEFAULT_BUCKETS = 64


def stable_shard_hash(name: str, modulus: int) -> int:
    """Cross-process-stable bucket index for a node or pod name."""
    return zlib.crc32(name.encode()) % max(modulus, 1)


class StaleMapError(RuntimeError):
    """A shard-map write lost the version race: the file on disk is
    newer than the map this writer loaded.  Reload and retry."""


class ShardMap:
    def __init__(
        self,
        n_shards: int = 1,
        n_buckets: int = DEFAULT_BUCKETS,
        buckets: list[int] | None = None,
        overrides: dict[str, int] | None = None,
        version: int = 0,
        epoch: int = 0,
    ) -> None:
        if buckets is None:
            # Initial layout: buckets dealt round-robin, so shard sizes
            # differ by at most one bucket.
            buckets = [i % max(n_shards, 1) for i in range(n_buckets)]
        self.buckets = list(buckets)
        self.overrides = dict(overrides or {})
        self.version = version
        self.epoch = epoch

    # -- assignment --------------------------------------------------------

    def shard_ids(self) -> list[int]:
        present = {s for s in self.buckets} | {
            s for s in self.overrides.values()
        }
        return sorted(present)

    def owner_of(self, node_name: str) -> int:
        ov = self.overrides.get(node_name)
        if ov is not None:
            return ov
        return self.buckets[stable_shard_hash(node_name, len(self.buckets))]

    def nodes_of(self, shard: int, node_names) -> list[str]:
        """The subset of ``node_names`` this shard owns, in given order."""
        return [n for n in node_names if self.owner_of(n) == shard]

    # -- reshaping ---------------------------------------------------------

    def assign(self, node_name: str, shard: int) -> dict:
        """Pin one node to a shard (targeted rebalance / takeover pin).
        Returns the handoff record describing the transfer."""
        prev = self.owner_of(node_name)
        self.overrides[node_name] = shard
        return self._handoff("assign", prev, shard, nodes=[node_name])

    def split(
        self, shard: int, new_shard: int, drop_pins: bool = False
    ) -> dict:
        """Split a shard: the second half of its buckets (in bucket
        order) moves to ``new_shard``.  Returns the handoff record.

        Override pins naming ``shard`` are never silently remapped to the
        new shard: by default they SURVIVE on the source (a pin is an
        operator/takeover decision the autoscaler must not second-guess);
        ``drop_pins=True`` explicitly drops them instead — the pinned
        nodes fall back to the bucket rule, and the dropped names ride
        the handoff record (``pins_dropped``) so a takeover redo replays
        the same choice.  A shard owning fewer than two buckets cannot
        split (moving its only bucket would be a rename that empties the
        source) — ValueError, before any version bump."""
        owned = [i for i, s in enumerate(self.buckets) if s == shard]
        if len(owned) < 2:
            raise ValueError(
                f"shard {shard} owns {len(owned)} bucket(s); a split "
                "needs at least 2 to leave both sides non-empty"
            )
        moving = owned[len(owned) // 2 :]
        for i in moving:
            self.buckets[i] = new_shard
        pins_dropped: list[str] = []
        if drop_pins:
            for n, s in sorted(self.overrides.items()):
                if s == shard:
                    del self.overrides[n]
                    pins_dropped.append(n)
        return self._handoff(
            "split", shard, new_shard, buckets=moving,
            pins_dropped=pins_dropped,
        )

    def merge(self, into: int, absorbed: int) -> dict:
        """Merge ``absorbed``'s buckets and overrides into ``into`` —
        the takeover shape: a dead owner's whole shard transfers.
        Merging a shard into itself is refused (the no-op would still
        bump the version and look like a transfer to takeover); merging
        the last two shards down to N=1 is legal — the map degenerates
        to the single-scheduler shape and the router serves it."""
        if into == absorbed:
            raise ValueError(f"cannot merge shard {into} into itself")
        moving = [i for i, s in enumerate(self.buckets) if s == absorbed]
        for i in moving:
            self.buckets[i] = into
        for n, s in sorted(self.overrides.items()):
            if s == absorbed:
                self.overrides[n] = into
        return self._handoff("merge", absorbed, into, buckets=moving)

    def rebalance(
        self,
        n_shards: int | None = None,
        ids: list[int] | None = None,
        drop_pins: bool = False,
    ) -> dict:
        """Re-deal every bucket round-robin over the given shard ids —
        the from-scratch layout for a resized fleet.  ``ids`` names the
        LIVE shards explicitly (after merges the id space has gaps;
        dealing to ``range(n)`` would assign buckets to an ownerless
        shard); ``n_shards`` alone means ids ``0..n-1``.  Pins follow
        the split contract: they SURVIVE unless ``drop_pins`` explicitly
        drops them, recorded on the handoff record for the redo."""
        if ids is None:
            ids = list(range(max(n_shards or 1, 1)))
        ids = sorted(ids)
        self.buckets = [ids[i % len(ids)] for i in range(len(self.buckets))]
        pins_dropped: list[str] = []
        if drop_pins:
            pins_dropped = sorted(self.overrides)
            self.overrides = {}
        return self._handoff(
            "rebalance", -1, -1, n_shards=len(ids), ids=ids,
            pins_dropped=pins_dropped,
        )

    def _handoff(self, op: str, src: int, dst: int, **extra) -> dict:
        """The journaled transfer record: version is bumped HERE, before
        any file write, so the acquiring owner appends the record first
        and the map write at that version is idempotently redoable."""
        self.version += 1
        rec = {"op": op, "from": src, "to": dst, "version": self.version}
        rec.update(extra)
        return rec

    # -- durability --------------------------------------------------------

    def to_doc(self) -> dict:
        return {
            "version": self.version,
            "epoch": self.epoch,
            "buckets": list(self.buckets),
            "overrides": dict(sorted(self.overrides.items())),
        }

    def save(self, path: str, epoch: int | None = None) -> None:
        """Atomic, fsync'd write.  Refuses to clobber unless strictly
        NEWER than the file (a deposed writer whose version merely caught
        up to the successor's must not roll ownership back either) —
        StaleMapError; the caller reloads and reapplies.  A version-0
        file (fresh init) may be rewritten."""
        if epoch is not None:
            self.epoch = epoch
        cur = read_version(path)
        if cur and cur >= self.version:
            raise StaleMapError(
                f"shard map at {path} is at version {cur}, "
                f"writer holds {self.version}"
            )
        from .. import journal as _journal

        # The handoff crash window under test (faults.KILL_POINTS
        # "pre-map-write"): the acquiring owner has journaled the
        # transfer but the map file still shows the old layout — takeover
        # redoes the rewrite from the journal (takeover.py
        # redo_lost_map_writes).
        _journal._crash("pre-map-write")
        blob = json.dumps(self.to_doc(), sort_keys=True).encode()
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        try:
            dfd = os.open(os.path.dirname(path) or ".", os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
        except OSError:
            pass

    @classmethod
    def load(cls, path: str) -> "ShardMap":
        with open(path, "rb") as f:
            doc = json.loads(f.read())
        return cls(
            buckets=doc["buckets"],
            overrides=doc.get("overrides", {}),
            version=doc.get("version", 0),
            epoch=doc.get("epoch", 0),
        )


def read_version(path: str) -> int:
    """The on-disk map's version (0 when absent/corrupt) — the cheap
    staleness probe writers consult before replacing the file."""
    try:
        with open(path, "rb") as f:
            return int(json.loads(f.read()).get("version", 0))
    except (OSError, ValueError, AttributeError, TypeError):
        return 0
