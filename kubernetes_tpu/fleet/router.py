"""The fleet router: the thin front door of the partitioned control plane.

One router + N shard owners reproduce ONE scheduler's decisions.  The
router owns global admission (a real ``SchedulingQueue`` — arrival order,
backoff, precise fit-wake hints, gang parking) and the two decisions a
partition cannot make locally; the owners own everything else (evaluation,
reserve chains, journaling, recovery) behind their own lease epochs.

Scatter-gather scheduling
    For each popped pod the router gathers eval-only per-node verdicts
    from every shard (``propose`` — the same compiled pass the extender
    path uses) and makes the global selectHost decision ITSELF, with the
    device kernel's exact math mirrored on the host: highest total score
    wins, ties resolved by the splitmix32 counter hash over snapshot row
    order (engine/pass_.py ``select_host`` / ``_hash_u32``), the counter
    being the same ``_cycle`` sequence a single scheduler would have
    burned.  Global row order is reconstructed by mirroring the cache's
    row allocator (LIFO free list) over the fleet-wide node feed.  The
    winner commits on its shard.  This reproduces the single scheduler
    bit-identically whenever per-node verdicts are shard-independent —
    true for filter semantics and additive per-node scores; score ops
    that normalize over the global candidate set trade exactness for
    partition locality (the Tesserae compromise: partition the cluster,
    preserve the constraints that matter).

Routing and misroutes
    Each pod hashes to a HOME shard (crc32 over its uid, skipping shards
    that currently own no nodes — the feasibility-aware part).  The hash
    predicts locality; the global argmax decides.  A winner other than
    the home shard is a MISROUTE: the pod is forwarded to the winning
    owner and counted (``scheduler_fleet_forwarded_pods_total``).

Cross-shard preemption
    A pod with no feasible node scatter-gathers DRY-RUN candidates
    (``preempt_propose`` — nothing applied), compares them by the
    pickOneNodeForPreemption lexicographic key + global row order, and
    executes only the winner on its owning shard.  Per-shard minimization
    followed by a cross-shard key compare equals one global minimization
    because every criterion is a per-candidate property.  PDB debits are
    broadcast so every shard's future violation counts stay global;
    nominations and their fit-overlay claims need no broadcast — the
    freed node lives on the shard that holds the nominator entry.

Gang admission spanning shards (two-phase reserve/commit)
    Members admitted by the queue's quorum gate reserve on their winning
    shards (phase 1: ``gang_reserve`` intent journaled, resources
    assumed, Reserve chain run); when reserved + already-bound credit
    reaches minMember the router commits every reservation (phase 2:
    journaled bind).  A crash between phases leaves intents without bind
    records — recovery resolves them PRESUMED ABORT (journal.recover) and
    the router re-admits the gang from scratch, so the fleet converges.
"""

from __future__ import annotations

import time
from collections import OrderedDict, deque
from dataclasses import dataclass, field

import numpy as np

from ..api import serialize, types as t
from ..framework.flight import FlightRecorder
from ..framework.tracing import Trace
from ..queue import Event, EventCtx, QueuedPodInfo, SchedulingQueue
from ..scheduler import ScheduleOutcome
from .shardmap import ShardMap, stable_shard_hash


def _hash_u32(x: int) -> int:
    """Host mirror of engine/pass_.py ``_hash_u32`` (splitmix32-style
    avalanche, uint32 wraparound) — the tie-break RNG must be bit-equal
    to the device kernel's or fleet and single-scheduler picks diverge on
    score ties."""
    x &= 0xFFFFFFFF
    x = ((x ^ (x >> 16)) * 0x7FEB352D) & 0xFFFFFFFF
    x = ((x ^ (x >> 15)) * 0x846CA68B) & 0xFFFFFFFF
    return (x ^ (x >> 16)) & 0xFFFFFFFF


# The Tesserae compromise, as a registry instead of a docstring: score
# ops whose math reduces over the GLOBAL candidate axis (a max/min/sum
# across all feasible nodes) cannot be reproduced exactly by per-shard
# evaluation — each shard normalizes against its own candidates, so the
# gathered verdicts the router argmaxes over may differ from what one
# scheduler would have computed.  Every op listed here accepts that
# divergence deliberately (partition the cluster, preserve the
# constraints that matter); an op that reduces over the candidate set
# WITHOUT being listed is a silent fleet-vs-single divergence, and
# tpulint's ``jax-partition-unsafe`` rule fails the build on it.  The
# same rule flags stale entries, so this set mirrors ops/ exactly.
#
# Orthogonal to engine/pass_.py PINNED_SAFE_OPS (node-axis-only *state*):
# ImageLocality reads only node-axis state yet normalizes its spread
# ratio over the feasible count, so it is pinned-safe but
# partition-inexact.
PARTITION_INEXACT_OPS = frozenset({
    # spread = nodes-with-image / total valid nodes (state.valid.sum()).
    "ImageLocality",
    # min/max over jnp.where(feasible, raw, ±big) rescales to [0, 100].
    "InterPodAffinity",
    # DefaultNormalizeScore: raw * 100 // max over feasible (helpers.py).
    "NodeAffinity",
    # topoSize/domain minima count *scored* (feasible ∧ keys) candidates,
    # and the final rescale min/maxes over the scored mask.
    "PodTopologySpread",
    # DefaultNormalizeScore, reversed (fewer intolerable taints is
    # better) — same feasible-set max.
    "TaintToleration",
})


@dataclass
class _GangRoom:
    """Reserved-but-uncommitted members of one gang (phase 1 done)."""

    members: list[tuple[str, int]] = field(default_factory=list)  # (uid, shard)
    pods: dict[str, t.Pod] = field(default_factory=dict)
    # The queue infos, kept so a rollback re-parks members with their
    # attempt counts intact (queue.requeue_gang_member's contract).
    qps: dict[str, QueuedPodInfo] = field(default_factory=dict)
    # The ScheduleOutcome emitted at reserve time, per member — phase 2
    # flips node_name on these in place, so the batch that reached
    # quorum reports EVERY member bound (the queue admits a gang into
    # one batch, so the outcomes are still in flight when commit runs).
    outcomes: dict[str, "ScheduleOutcome"] = field(default_factory=dict)


class FleetRouter:
    def __init__(
        self,
        owners: dict,
        shard_map: ShardMap,
        batch_size: int = 256,
        tie_break_seed: int = 0,
        registry=None,
        observability: bool = True,
    ) -> None:
        self.owners = dict(owners)
        self.shard_map = shard_map
        self.batch_size = batch_size
        self.tie_break_seed = tie_break_seed
        self.queue = SchedulingQueue()
        # Fleet-wide gang credit: bound members across EVERY shard plus
        # reservations held in the 2PC rooms — the same quantity the
        # single scheduler's gang_bound+permit_waiting lambda feeds its
        # queue (scheduler.py), so quorum admission decisions agree.
        self.gang_bound: dict[str, int] = {}
        self._gang_rooms: dict[str, _GangRoom] = {}
        self.gang_min: dict[str, int] = {}
        self.queue.gang_credit = lambda g: self.gang_bound.get(g, 0) + (
            len(self._gang_rooms[g].members) if g in self._gang_rooms else 0
        )
        for owner in self.owners.values():
            # In-process owners consult the fleet-wide credit from their
            # own admission gates too (scheduler.fleet_gang_credit).
            sched = getattr(owner, "sched", None)
            if sched is not None:
                sched.fleet_gang_credit = (
                    lambda g: self.gang_bound.get(g, 0)
                )
        # Mirror of the single scheduler's cache row allocator (LIFO free
        # list) over the FLEET-WIDE node feed: global position ==
        # the snapshot row a single scheduler would have assigned, which
        # is the tie-break enumeration order select_host uses.
        self._node_pos: dict[str, int] = {}
        self._free_pos: list[int] = []
        self._next_pos = 0
        # Live nodes per shard, maintained incrementally (add_node /
        # remove_object / apply_handoff) — home_shard consults this per
        # pod, and recomputing it would cost one crc32 per node per pod.
        self._shard_node_count: dict[int, int] = {}
        # Where each bound pod lives (commit bookkeeping + removals).
        self._pod_shard: dict[str, int] = {}
        # Monotone per-shard commit counters — the binding-rate signal
        # the autoscaler windows by differencing (handoff-imported
        # bindings deliberately excluded: a transfer is not served load).
        self.binds_by_shard: dict[int, int] = {}
        # Outcomes flipped by a gang commit — drained by schedule_batch,
        # so a member reserved in an EARLIER batch (reported unbound
        # there) still surfaces as bound in the batch whose quorum
        # committed it.
        self._gang_committed: list[ScheduleOutcome] = []
        # The single scheduler's _cycle sequence (tie-break step counter).
        self._cycle = 0
        # Decision provenance: the tie-break step each scheduled pod's
        # _select drew — bounded, insert-ordered, consumed by explain()
        # to reconstruct the router-side selectHost bit-for-bit.
        self._decision_steps: "OrderedDict[str, int]" = OrderedDict()
        self.profile_filters: tuple[str, ...] = ()
        # -- observability (the scheduler_fleet_* families) ---------------
        if registry is None:
            from ..framework.metrics import MetricsRegistry

            registry = MetricsRegistry()
        self.registry = registry
        self._shard_nodes = registry.gauge(
            "scheduler_fleet_shard_nodes",
            "Nodes owned per shard (the shard-map ownership gauge).",
        )
        self._cross_calls = registry.counter(
            "scheduler_fleet_cross_shard_calls_total",
            "Fleet protocol calls issued to shard owners, by op.",
        )
        self._forwarded = registry.counter(
            "scheduler_fleet_forwarded_pods_total",
            "Pods committed on a shard other than their hash-routed home "
            "(misroutes forwarded to the global winner).",
        )
        self._handoffs = registry.counter(
            "scheduler_fleet_handoffs_total",
            "Shard-map handoffs orchestrated (split/merge/assign/"
            "rebalance/takeover), by op.",
        )
        self._preempt_xshard = registry.counter(
            "scheduler_fleet_cross_shard_preemptions_total",
            "Preemptions where the preemptor's home and the victim's "
            "shard differ.",
        )
        self._gang_commits = registry.counter(
            "scheduler_fleet_gang_commits_total",
            "Gang 2PC phase transitions, by phase (reserve/commit/abort).",
        )
        # -- the fleet-native failure-response loop -----------------------
        self._lease_frames = registry.counter(
            "scheduler_fleet_lifecycle_lease_frames_total",
            "Lease renewals routed to the owning shard's lifecycle "
            "controller, by shard.",
        )
        self._lifecycle_evictions = registry.counter(
            "scheduler_fleet_lifecycle_evictions_total",
            "Controller evictions absorbed from shard owners and "
            "requeued fleet-wide, by shard (the shard that evicted).",
        )
        self._lifecycle_rebinds = registry.counter(
            "scheduler_fleet_lifecycle_rebinds_total",
            "Evicted pods rebound through the router, by whether the "
            "new shard differs from the evicting one (cross_shard).",
        )
        # Evicted pods absorbed but not yet rebound: uid → (pod, the
        # shard that evicted it).  A cold router restart re-adopts these
        # (readopt_evictions) the way `pending` pods re-feed.
        self.evicted_pending: dict[str, tuple[t.Pod, int]] = {}
        # True only inside drain_evictions (takeover/adopt): replayed
        # evict records whose pod REBOUND before the crash are stale —
        # the just-adopted _pod_shard is owner truth there, so bound
        # uids are skipped instead of re-queued.
        self._adopt_filter = False
        # The fleet-wide logical clock (Lease renew_time high-water
        # mark): advances broadcast a ``tick`` to non-owning shards.
        self._lifecycle_hw = 0.0
        # -- fleet observability (ISSUE 12) -------------------------------
        # Everything below is OBSERVATIONAL: with observability off the
        # router routes and binds bit-identically (the soak's on-vs-off
        # determinism check holds exactly this).
        self.observability = observability
        # Fleet-aggregated per-tenant counters: the router counts at ITS
        # admission/commit sites, so the scheduler_tenant_* families on
        # this registry are the cross-shard totals while each owner's
        # registry carries the per-shard split.
        from ..framework.metrics import TenantMetrics

        self.tenant_metrics = (
            TenantMetrics(registry) if observability else None
        )
        if self.tenant_metrics is not None:
            self.queue.tenant_note = self.tenant_metrics.note_pod
        # The router's own flight ring: one record per scatter-gather
        # batch, logical-clock-stamped — merge_fleet folds it with the
        # owners' rings into the fleet timeline.
        self.flight = FlightRecorder(component="router")
        # Driver-fed logical clock (the soak's scenario clock); None →
        # the tie-break cycle counter (monotone, deterministic).
        self._lc: float | None = None
        # Cross-process slow-span ring: a slow fleet batch logs its
        # whole router→owner→sidecar tree here (owners' op spans ride
        # back on the RPC responses and attach as remote children).
        self.slow_spans: deque = deque(maxlen=16)
        self.trace_threshold_s = 2.0
        # Per-batch phase accumulator (scatter/commit/postfilter wall
        # slices), filled by _schedule_one and finalized into one
        # router flight record per schedule_batch.
        self._batch_phases: dict | None = None

    # -- observability helpers ---------------------------------------------

    def lc(self) -> float:
        """The current logical clock: the driver's scenario clock when
        fed (note_logical_time), else the tie-break cycle counter —
        either way a pure function of the op stream."""
        return self._lc if self._lc is not None else float(self._cycle)

    def note_logical_time(self, t: float) -> None:
        self._lc = float(t)

    def _note_slow_span(self, tr: Trace) -> None:
        self.slow_spans.append(tr.as_dict())

    def _note_tenant(self, event: str, pod_or_tenant) -> None:
        if self.tenant_metrics is None:
            return
        if isinstance(pod_or_tenant, (str, type(None))):
            self.tenant_metrics.note(event, pod_or_tenant)
        else:
            self.tenant_metrics.note_pod(event, pod_or_tenant)

    # -- owner RPC ---------------------------------------------------------

    def _call(
        self, shard: int, op: str, payload: dict, span: Trace | None = None
    ) -> dict:
        self._cross_calls.inc(op=op)
        if self.observability:
            # The observability envelope: the logical clock every call
            # (owners stamp their flight records with it) and — when the
            # caller opened a span — the trace context, so the owner's
            # op span joins this trace and rides back as a remote child.
            payload = dict(payload)
            payload["lc"] = self.lc()
            if span is not None:
                payload["trace_id"] = span.trace_id
                payload["parent_span_id"] = span.span_id
        res = self.owners[shard].call(op, payload)
        if isinstance(res, dict):
            rspan = res.pop("_span", None)
            if rspan is not None and span is not None:
                span.attach_remote(rspan)
            evicted = res.pop("evicted", None)
            if evicted:
                self._absorb_evictions(shard, evicted)
        return res

    def shard_ids(self) -> list[int]:
        return sorted(self.owners)

    # -- elastic membership (the autoscaler's owner lifecycle) -------------

    def add_owner(self, shard: int, owner) -> None:
        """Register a freshly created owner for a split-created shard —
        the in-process half of what the ctor does per owner (fleet-wide
        gang credit visibility).  The shard owns nothing until a handoff
        imports nodes into it."""
        self.owners[shard] = owner
        sched = getattr(owner, "sched", None)
        if sched is not None:
            sched.fleet_gang_credit = lambda g: self.gang_bound.get(g, 0)

    def remove_owner(self, shard: int):
        """Deregister a merged-away shard's owner AFTER its handoff
        drained it (apply_handoff moved every node and binding).  Returns
        the owner for the caller to retire (close journals / stop the
        serve child); refuses while the shard still owns nodes."""
        if self._shard_node_count.get(shard):
            raise ValueError(
                f"shard {shard} still owns "
                f"{self._shard_node_count[shard]} node(s); merge it away "
                "before removing its owner"
            )
        self._shard_node_count.pop(shard, None)
        self.binds_by_shard.pop(shard, None)
        return self.owners.pop(shard)

    def push_map(self) -> None:
        """Ship the CURRENT in-memory shard map to every owner
        (``set_map``): guards must agree with a just-mutated map before
        the handoff's imports land — a wire owner's file-loaded copy
        predates the resize.  Nothing durable; the map file write stays
        where apply_handoff puts it (after the journaled imports)."""
        doc = self.shard_map.to_doc()
        for shard in self.shard_ids():
            self._call(shard, "set_map", {"doc": doc})

    # -- weighted-fair admission (framework/fairness, ISSUE 17) -------------

    def arm_admission(self, policy) -> None:
        """Arm weighted-fair admission on the router's queue — the
        fleet-wide admission point (owners receive already-admitted
        assignments, so fairness decided here IS the fleet's admission
        order).  The policy inherits the router's logical clock unless
        the caller injected one, and the weight/cap document ships to
        every owner immediately (set_map-style push)."""
        if policy.clock is None:
            policy.clock = self.lc
        self.queue.arm_admission(policy)
        self.push_admission()

    def admission_doc(self) -> dict:
        """The admission document owners mirror: the weight/cap/SLO
        knobs plus the router's current per-tenant fairness status
        (weight, credit balance, virtual-time lag, SLO verdict) — the
        state mirror `fleet status --sockets` renders per owner."""
        adm = self.queue.admission
        return {
            "weights": {t: adm.weights[t] for t in sorted(adm.weights)},
            "rate_pods_per_s": adm.rate,
            "burst": adm.burst,
            "aging_max_wait_s": adm.aging_max_wait_s,
            "slo_wait_budget_s": adm.slo_wait_budget_s,
            "status": adm.status(),
        }

    def push_admission(self) -> None:
        """Ship the admission document to every owner (``set_admission``
        — the push_map pattern: idempotent, nothing durable).  Owners
        inherit the weights for their own armed policies, if any, and
        mirror the document into their stats surface."""
        if self.queue.admission is None:
            return
        payload = {"doc": self.admission_doc()}
        for shard in self.shard_ids():
            self._call(shard, "set_admission", payload)

    # -- the object feed (the informer surface, partitioned) ---------------

    def add_object(self, kind: str, obj) -> None:
        if kind == "Node":
            self.add_node(obj)
            return
        if kind == "Lease":
            # A node heartbeat concerns exactly one lifecycle controller:
            # the owning shard's.  The FRAME routes there (crc32 shard
            # map — the same deterministic hash every owner consults; a
            # foreign owner tracking the Lease would taint a node it
            # does not hold), but the logical CLOCK it advances is
            # global knowledge — upstream's apiserver stamps one clock
            # for every controller.  So when a renewal advances the
            # fleet-wide high-water mark, every OTHER shard gets a bare
            # ``tick`` at the new clock: a shard whose only leased node
            # went silent would otherwise never judge it (its local
            # clock would freeze at the last renewal it ever saw).
            # Evictions either call fires ride back on the responses
            # (_call absorbs them).
            shard = self.shard_map.owner_of(obj.node_name)
            self._lease_frames.inc(shard=str(shard))
            advanced = obj.renew_time > self._lifecycle_hw
            self._call(
                shard,
                "add",
                {"kind": "Lease", "object": serialize.to_dict(obj)},
            )
            if advanced:
                for other in self.shard_ids():
                    if other != shard:
                        self._call(
                            other, "tick", {"now": obj.renew_time}
                        )
                # Advance the mark only after every call landed: a
                # FleetOwnerUnreachable mid-broadcast leaves it behind,
                # so the post-takeover re-issue broadcasts again (ticks
                # at an already-seen clock are idempotent no-ops).
                self._lifecycle_hw = obj.renew_time
            return
        if kind == "Pod" and not obj.spec.node_name:
            self.add_pod(obj)
            return
        data = serialize.to_dict(obj)
        if kind == "Pod":
            # A bound pod belongs to the shard owning its node; everything
            # else is cluster-scoped state every owner needs (PDBs for
            # violation counts, PodGroups for reserve plugins, volumes…).
            shard = self.shard_map.owner_of(obj.spec.node_name)
            known = obj.uid in self._pod_shard
            self._pod_shard[obj.uid] = shard
            self._call(shard, "add", {"kind": kind, "object": data})
            g = obj.spec.pod_group
            if g and not known:
                # Re-deliveries (and takeover re-feeds of adopted
                # bindings) must not double-count quorum credit.
                self.gang_bound[g] = self.gang_bound.get(g, 0) + 1
            return
        if kind == "PodGroup":
            self.gang_min[obj.name] = obj.min_member
            self.queue.register_gang(obj.name, obj.min_member)
        for shard in self.shard_ids():
            self._call(shard, "add", {"kind": kind, "object": data})

    def add_node(self, node: t.Node) -> None:
        shard = self.shard_map.owner_of(node.name)
        if node.name not in self._node_pos:
            pos = self._free_pos.pop() if self._free_pos else self._next_pos
            if pos == self._next_pos:
                self._next_pos += 1
            self._node_pos[node.name] = pos
            self._shard_node_count[shard] = (
                self._shard_node_count.get(shard, 0) + 1
            )
        self._call(
            shard, "add", {"kind": "Node", "object": serialize.to_dict(node)}
        )
        self._shard_nodes.set(
            self._call(shard, "stats", {})["nodes"], shard=str(shard)
        )
        ctx = self._call(shard, "free_ctx", {"names": [node.name]})
        self.queue.on_event(Event.NODE_ADD, self._ctx(ctx))

    def add_pod(self, pod: t.Pod) -> None:
        if pod.uid in self._pod_shard:
            # Already bound on some shard (a recovery re-feed, or an
            # at-least-once informer re-delivery): the committed placement
            # IS the decision — re-queueing would double-schedule.
            return
        self.queue.add(pod)

    def reconcile_recovered(self) -> int:
        """After a takeover's node re-feed: every owner re-applies journal
        bind records that were parked because their node was unknown at
        replay time (owner.apply_recovered_bindings).  Call before
        adopt_bindings so adopted routing covers the late bindings."""
        return sum(
            self._call(s, "reconcile", {})["applied"] for s in self.shard_ids()
        )

    def adopt_bindings(self) -> None:
        """Rebuild the router's bookkeeping from the owners' recovered
        truth (takeover/restart): pod→shard routing and fleet-wide gang
        credit come back from each shard's journal-recovered cache, so an
        idempotent re-feed of the scenario skips what already committed."""
        for shard in self.shard_ids():
            res = self._call(shard, "bindings", {})
            for uid in res["bindings"]:
                self._pod_shard[uid] = shard
            for g, n in res.get("gang_bound", {}).items():
                self.gang_bound[g] = self.gang_bound.get(g, 0) + n

    def _absorb_evictions(self, shard: int, evicted: list[dict]) -> None:
        """Close the cross-shard half of the failure-response loop: a
        shard owner's controller evicted these pods (taint eviction /
        pod GC — journaled owner-side).  The router purges its routing
        entry, debits fleet-wide gang credit, broadcasts the PDB debits
        to every other owner, and requeues the unbound pod through ITS
        queue — the next scatter-gather can rebind it on any shard."""
        for rec in evicted:
            uid = rec["uid"]
            if self._adopt_filter and uid in self._pod_shard:
                # Takeover drain: the journal replay re-surfaced an evict
                # whose pod rebound before the crash (a later bind record
                # adopt_bindings just re-read) — requeueing would
                # double-schedule it.
                continue
            if uid in self.evicted_pending:
                # Already absorbed by THIS router (live at-least-once
                # delivery): debits and counters were applied then.
                continue
            self._pod_shard.pop(uid, None)
            g = rec.get("group")
            if g:
                left = self.gang_bound.get(g, 0) - 1
                if left > 0:
                    self.gang_bound[g] = left
                else:
                    self.gang_bound.pop(g, None)
            # PDB debits broadcast at-least-once: a FRESH router draining
            # a replayed evict record cannot know whether the dead router
            # already broadcast this debit pre-crash (the same window
            # preemption's pdb_debits have) — budget accounting errs
            # toward conservative.
            for debit in rec.get("pdb_debits", ()):
                for other in self.shard_ids():
                    if other != shard:
                        self._call(other, "pdb_debit", debit)
            self._lifecycle_evictions.inc(shard=str(shard))
            pod = serialize.pod_from_data(rec["pod"])
            self.evicted_pending[uid] = (pod, shard)
            self.queue.add(pod)
        # Ack only after the WHOLE list is absorbed: the owner keeps
        # re-delivering until then, so a lost response, a retried call,
        # or an exception mid-absorb (a pdb_debit broadcast hitting a
        # dead owner) never strands an eviction — re-delivery is deduped
        # on evicted_pending above.
        self._call(
            shard,
            "ack_evictions",
            {"uids": [rec["uid"] for rec in evicted]},
        )

    def drain_evictions(self) -> int:
        """Explicitly drain every owner's eviction buffer (takeover /
        cold-router adopt): crash-interrupted evictions the journal
        replay re-surfaced requeue here.  Call AFTER adopt_bindings —
        the adopted routing is what filters replay-stale records whose
        pod already rebound.  Returns the pods requeued."""
        before = len(self.evicted_pending)
        self._adopt_filter = True
        try:
            for shard in self.shard_ids():
                self._call(shard, "drain_evictions", {})
        finally:
            self._adopt_filter = False
        return len(self.evicted_pending) - before

    def readopt_evictions(
        self, prior: dict[str, tuple[t.Pod, int]]
    ) -> int:
        """A cold router restart inherits the dead router's absorbed-but-
        unbound evictions (the soak's router-restart path): requeue the
        ones still unbound, keeping the evicting-shard attribution so
        cross-shard rebind accounting survives the restart."""
        n = 0
        for uid, (pod, shard) in sorted(prior.items()):
            if uid in self._pod_shard or uid in self.evicted_pending:
                continue
            self.evicted_pending[uid] = (pod, shard)
            self.queue.add(pod)
            n += 1
        return n

    def _note_rebind(self, uid: str, shard: int) -> None:
        ev = self.evicted_pending.pop(uid, None)
        if ev is not None:
            self._lifecycle_rebinds.inc(
                cross_shard="true" if shard != ev[1] else "false"
            )

    def lifecycle_stats(self) -> dict:
        """Fleet-wide failure-response summary (`fleet status`, the
        fleet soak's node_loss block): per-owner lifecycle state plus
        the router's eviction/rebind loop-closure counters."""
        return {
            "per_shard": {
                str(s): self._call(s, "stats", {}).get("lifecycle", {})
                for s in self.shard_ids()
            },
            "evictions_absorbed": int(self._lifecycle_evictions.total()),
            "rebinds": int(self._lifecycle_rebinds.total()),
            "cross_shard_rebinds": int(
                self._lifecycle_rebinds.get(cross_shard="true")
            ),
            "pending_rebinds": len(self.evicted_pending),
        }

    def remove_object(self, kind: str, uid: str) -> None:
        if kind == "Node":
            shard = self.shard_map.owner_of(uid)
            res = self._call(shard, "remove", {"kind": "Node", "uid": uid})
            pos = self._node_pos.pop(uid, None)
            if pos is not None:
                self._free_pos.append(pos)
                left = self._shard_node_count.get(shard, 0) - 1
                if left > 0:
                    self._shard_node_count[shard] = left
                else:
                    self._shard_node_count.pop(shard, None)
            # The node's bound pods vanished with it on the owner —
            # purge the router's routing entries (an informer re-feed
            # must be able to reschedule them, like the single
            # scheduler's unbound re-add) and debit fleet-wide gang
            # credit for evaporated members, or a later gang would
            # count ghosts toward quorum.
            for puid in res.get("dropped", ()):
                self._pod_shard.pop(puid, None)
            for g in res.get("dropped_groups", ()):
                n = self.gang_bound.get(g, 0) - 1
                if n > 0:
                    self.gang_bound[g] = n
                else:
                    self.gang_bound.pop(g, None)
            self._shard_nodes.set(
                self._call(shard, "stats", {})["nodes"], shard=str(shard)
            )
            return
        if kind != "Pod":
            raise ValueError(f"cannot remove kind {kind}")
        self.evicted_pending.pop(uid, None)
        shard = self._pod_shard.pop(uid, None)
        if shard is not None:
            res = self._call(shard, "remove", {"kind": "Pod", "uid": uid})
            self.queue.on_event(Event.POD_DELETE, self._ctx(res.get("freed")))
        else:
            self.queue.delete(uid)

    @staticmethod
    def _ctx(doc: dict | None) -> EventCtx | None:
        if not doc:
            return None
        return EventCtx(
            max_free=np.asarray(doc["max_free"], np.int64),
            max_slots=doc["max_slots"],
        )

    # -- routing -----------------------------------------------------------

    def home_shard(self, pod: t.Pod) -> int:
        """Feasibility-aware hash route: crc32 over the pod uid across
        the shards that currently own nodes (an empty shard can never
        host, so hashing a pod there would guarantee a misroute).  The
        per-shard node counts are maintained incrementally — this runs
        once per scheduled pod."""
        viable = sorted(
            s for s in self.shard_ids() if self._shard_node_count.get(s)
        ) or self.shard_ids()
        return viable[stable_shard_hash(pod.uid, len(viable))]

    # -- scatter-gather scheduling ----------------------------------------

    def _propose_all(
        self, pod: t.Pod, span: Trace | None = None
    ) -> dict[int, dict]:
        data = serialize.to_dict(pod)
        out: dict[int, dict] = {}
        for shard in self.shard_ids():
            child = (
                span.nest("ProposeRPC", shard=shard)
                if span is not None
                else None
            )
            out[shard] = self._call(
                shard, "propose", {"pod": data}, span=child
            )
            if child is not None:
                child.end()
        return out

    def _select(
        self, proposals: dict[int, dict], pod: t.Pod, step: int
    ) -> tuple[str, int] | None:
        """The global selectHost: (node, shard) or None.  Mirrors
        select_host exactly — nominated fast path first, then argmax with
        the counter-hash tie-break enumerated in global row order."""
        nn = pod.status.nominated_node_name
        if nn:
            for shard, prop in proposals.items():
                if prop.get("nominated") == nn:
                    return nn, shard
        cands: list[tuple[int, str, int, int]] = []  # (pos, name, shard, score)
        for shard, prop in proposals.items():
            for name, score in zip(prop["feasible"], prop["scores"]):
                pos = self._node_pos.get(name)
                if pos is not None:
                    cands.append((pos, name, shard, score))
        if not cands:
            return None
        cands.sort()
        best = max(c[3] for c in cands)
        ties = [c for c in cands if c[3] == best]
        tie_rand = _hash_u32(
            (self.tie_break_seed * 0x9E3779B1 + step) & 0xFFFFFFFF
        )
        pick = ties[tie_rand % len(ties)]
        return pick[1], pick[2]

    def _schedule_one(
        self, qp: QueuedPodInfo, step: int, span: Trace | None = None
    ) -> tuple[ScheduleOutcome, bool]:
        """One scatter-gather cycle.  Returns (outcome, run_postfilter):
        preemption is NOT attempted here — the single scheduler runs
        PostFilter after the whole batch scan (scheduler._complete_batch),
        and committing evictions mid-batch would show later batch-mates a
        state the oracle's in-scan evaluation never saw."""
        pod = qp.pod  # attempts already bumped by pop_batch
        acc = self._batch_phases
        if self.observability:
            # Provenance: remember the tie-break step this decision drew
            # so explain() can replay _select exactly (newest wins).
            self._decision_steps.pop(pod.uid, None)
            self._decision_steps[pod.uid] = step
            while len(self._decision_steps) > 4096:
                self._decision_steps.popitem(last=False)
        home = self.home_shard(pod)
        t0 = time.perf_counter()
        proposals = self._propose_all(pod, span)
        if acc is not None:
            acc["scatter"] = (
                acc.get("scatter", 0.0) + time.perf_counter() - t0
            )
        req = proposals[home].get("req")
        if req is not None:
            # The fit-wake hint's request vector (the single scheduler
            # keeps the featurized delta on the queued info the same way).
            qp.delta = {"req": np.asarray(req, np.int64)}
        picked = self._select(proposals, pod, step)
        g = pod.spec.pod_group
        if picked is None:
            if g and g in self.gang_min:
                # A gang member with no feasible node sinks the whole
                # gang (all-or-nothing): abort every held reservation
                # and re-admit damped — leaving the partial room parked
                # would strand reserved capacity on the other shards.
                self._rollback_gang(g)
                self.queue.add_backoff(qp)
                return ScheduleOutcome(pod, None), False
            return ScheduleOutcome(pod, None), True
        node_name, shard = picked
        if shard != home:
            self._forwarded.inc()
        if g and g in self.gang_min:
            return self._reserve_gang_member(qp, node_name, shard, g), False
        child = (
            span.nest("CommitRPC", shard=shard, node=node_name)
            if span is not None
            else None
        )
        t1 = time.perf_counter()
        res = self._call(
            shard,
            "commit",
            {"pod": serialize.to_dict(pod), "node": node_name},
            span=child,
        )
        if child is not None:
            child.end()
        if acc is not None:
            acc["commit"] = (
                acc.get("commit", 0.0) + time.perf_counter() - t1
            )
        if res.get("bound") is None:
            # A Reserve plugin refused on the winner — the cycle-error
            # path: retry behind backoff (handleSchedulingFailure), no
            # PostFilter (the pod was feasible; the refusal is transient).
            self.queue.add_backoff(qp)
            return ScheduleOutcome(pod, None), False
        self._pod_shard[pod.uid] = shard
        self.binds_by_shard[shard] = self.binds_by_shard.get(shard, 0) + 1
        self.queue.done(pod.uid)
        self._note_rebind(pod.uid, shard)
        self._note_tenant("bound", pod)
        return ScheduleOutcome(pod, node_name), False

    def _postfilter(self, qp: QueuedPodInfo, outcome: ScheduleOutcome) -> None:
        """The batch-completion failure path (one failed pod): cross-shard
        preemption, else the unschedulable pool.  Known divergence from
        the single scheduler: same-batch preemptors dry-run sequentially
        here (each sees the previous one's evictions) where the batched
        engine dry-runs them against one snapshot with consumed-victim
        dedup — identical for a single preemptor per batch."""
        pod = qp.pod
        res = self._preempt(pod)
        if res is not None:
            outcome.nominated_node = res["node"]
            outcome.victims = len(res["victims"])
            outcome.victim_uids = tuple(res["victims"])
            # The nominated retry re-enters the ACTIVE queue (the single
            # scheduler's _record_preemption does queue.add, not backoff).
            self.queue.add(pod)
            return
        # No candidate anywhere: park on the unschedulable pool.  The
        # proposals carry no per-plugin diagnosis, so the requeue mask is
        # the profile's whole filter set — the same fallback the single
        # scheduler takes for an empty diagnosis.
        self.queue.add_unschedulable(
            qp, set(self.profile_filters) or {"NodeResourcesFit"}
        )

    # -- cross-shard preemption -------------------------------------------

    def _preempt(self, pod: t.Pod) -> dict | None:
        data = serialize.to_dict(pod)
        cands: list[tuple[list, int, int, dict]] = []
        for shard in self.shard_ids():
            prop = self._call(shard, "preempt_propose", {"pod": data})
            if not prop or "node" not in prop:
                continue
            pos = self._node_pos.get(prop["node"])
            if pos is None:
                continue
            cands.append((prop["key"], pos, shard, prop))
        if not cands:
            return None
        key, _pos, shard, prop = min(cands, key=lambda c: (c[0], c[1]))
        res = self._call(
            shard,
            "preempt_execute",
            {
                "pod": data,
                "node": prop["node"],
                "victims": [v["uid"] for v in prop["victims"]],
            },
        )
        if shard != self.home_shard(pod):
            self._preempt_xshard.inc()
        # Cluster-global side effects of a shard-local eviction: PDB
        # budgets everywhere, fleet-wide gang credit, the router's own
        # pod→shard map, and the freed-capacity wake hint.
        for debit in res.get("pdb_debits", ()):
            for other in self.shard_ids():
                if other != shard:
                    self._call(other, "pdb_debit", debit)
        for g in res.get("victim_groups", ()):
            left = self.gang_bound.get(g, 0) - 1
            if left > 0:
                self.gang_bound[g] = left
            else:
                self.gang_bound.pop(g, None)
        for tenant in res.get("victim_tenants", ()):
            self._note_tenant("preempted", tenant or None)
        for uid in res["victims"]:
            self._pod_shard.pop(uid, None)
        pod.status.nominated_node_name = res["node"]
        self.queue.on_event(Event.POD_DELETE, self._ctx(res.get("freed")))
        return res

    # -- gang 2PC ----------------------------------------------------------

    def _reserve_gang_member(
        self, qp: QueuedPodInfo, node_name: str, shard: int, g: str
    ) -> ScheduleOutcome:
        pod = qp.pod
        ok = self._call(
            shard,
            "reserve",
            {"pod": serialize.to_dict(pod), "node": node_name, "gang": g},
        )
        if not ok.get("ok"):
            self._rollback_gang(g)
            self.queue.add_backoff(qp)
            return ScheduleOutcome(pod, None)
        self._gang_commits.inc(phase="reserve")
        room = self._gang_rooms.setdefault(g, _GangRoom())
        room.members.append((pod.uid, shard))
        room.pods[pod.uid] = pod
        room.qps[pod.uid] = qp
        out = ScheduleOutcome(pod, None)
        room.outcomes[pod.uid] = out
        self.queue.done(pod.uid)
        # Phase 2 fires the moment quorum is reachable: reservations in
        # the room plus members already bound anywhere in the fleet.
        if len(room.members) + self.gang_bound.get(g, 0) >= self.gang_min.get(
            g, 1
        ):
            self._commit_gang(g, pod)
        else:
            # Reserve credit grew (the room counts toward gang_credit):
            # parked mates may now be admissible — the router's analog of
            # the coscheduling plugin's post-batch re-attempt.  Damped:
            # re-admission goes through backoff.
            self.queue.readmit_gang(g)
        return out

    def _commit_gang(self, g: str, trigger: t.Pod) -> None:
        room = self._gang_rooms.pop(g)
        for uid, shard in room.members:
            res = self._call(shard, "commit_reserved", {"uid": uid})
            self._gang_commits.inc(phase="commit")
            self._pod_shard[uid] = shard
            self.binds_by_shard[shard] = (
                self.binds_by_shard.get(shard, 0) + 1
            )
            self._note_rebind(uid, shard)
            self._note_tenant("bound", room.pods[uid])
            self.gang_bound[g] = self.gang_bound.get(g, 0) + 1
            room.outcomes[uid].node_name = res.get("bound")
            self._gang_committed.append(room.outcomes[uid])

    def _rollback_gang(self, g: str) -> None:
        """Abort every held reservation of gang ``g`` (a member failed
        phase 1): journaled gang_abort per member, resources released,
        members re-queued behind backoff — the damped re-admission the
        single scheduler's rollback path takes."""
        room = self._gang_rooms.pop(g, None)
        if room is None:
            return
        for uid, shard in room.members:
            self._call(shard, "abort", {"uid": uid})
            self._gang_commits.inc(phase="abort")
            # Park without instant re-admission (the gang just failed
            # with exactly these members), attempts preserved.
            self.queue.requeue_gang_member(room.qps[uid])
        # Retry damped, behind backoff — in a quiet cluster no event
        # would ever re-admit an already-quorate parked gang.
        self.queue.readmit_gang(g)

    # -- the batch loop ----------------------------------------------------

    def schedule_batch(self) -> list[ScheduleOutcome]:
        infos = self.queue.pop_batch(self.batch_size)
        if not infos:
            return []
        if self.queue.admission is not None:
            # No journal fronts the router's fairness ledger (a cold
            # restart rebuilds it from scratch — deterministically, the
            # restart is a seeded scenario event), so debit intents
            # finalize AT pop: the durable ledger and admitted_log
            # advance in admission order with nothing left in flight.
            adm = self.queue.admission
            # tpulint: disable=wal-unjournaled-apply
            adm.apply_admission(
                adm.take_intents([qp.pod.uid for qp in infos])
            )
        t0 = time.perf_counter()
        tr: Trace | None = None
        if self.observability:
            # The batch root span: per-pod child spans fan out with the
            # owner RPCs, whose op spans ride back as remote children —
            # a slow batch dumps the whole router→owner→sidecar tree.
            tr = Trace(
                "FleetScheduleBatch",
                threshold_s=self.trace_threshold_s,
                on_slow=self._note_slow_span,
                pods=len(infos),
            )
            self._batch_phases = {}
        base = self._cycle
        outcomes: list[ScheduleOutcome] = []
        failed: list[tuple[QueuedPodInfo, ScheduleOutcome]] = []
        try:
            for i, qp in enumerate(infos):
                sp = (
                    tr.nest("SchedulePod", pod=qp.pod.uid)
                    if tr is not None
                    else None
                )
                out, run_pf = self._schedule_one(qp, base + i, span=sp)
                if sp is not None:
                    sp.end()
                outcomes.append(out)
                if run_pf:
                    failed.append((qp, out))
            # The single scheduler burns one tie-break step per popped pod
            # (scheduler.py _dispatch_batch: _cycle += len(infos)).
            self._cycle += len(infos)
            # PostFilter phase, batch order — evictions land only after
            # the whole scan, like scheduler._complete_batch.
            t_pf = time.perf_counter()
            for qp, out in failed:
                self._postfilter(qp, out)
            if self._batch_phases is not None and failed:
                self._batch_phases["postfilter"] = (
                    time.perf_counter() - t_pf
                )
        finally:
            acc, self._batch_phases = self._batch_phases, None
            if tr is not None:
                tr.end()
                tr.log_if_long()
        bound = [o for o in outcomes if o.node_name]
        seen = {o.pod.uid for o in outcomes}
        # Members reserved in an earlier batch whose gang committed now.
        bound.extend(o for o in self._gang_committed if o.pod.uid not in seen)
        self._gang_committed.clear()
        if self.observability:
            wall = time.perf_counter() - t0
            phases = {k: round(v, 6) for k, v in (acc or {}).items()}
            phases["other"] = round(
                max(wall - sum(phases.values()), 0.0), 6
            )
            rec = {
                "lc": self.lc(),
                "pods": len(infos),
                "scheduled": len(bound),
                "wall_s": round(wall, 6),
                "phases": phases,
            }
            if tr is not None:
                rec["trace_id"] = tr.trace_id
                rec["span_id"] = tr.span_id
            self.flight.record_batch(rec)
        return bound

    def schedule_all_pending(
        self, max_rounds: int = 10_000, wait_backoff: bool = False
    ) -> list[ScheduleOutcome]:
        all_outcomes: list[ScheduleOutcome] = []
        for _ in range(max_rounds):
            out = self.schedule_batch()
            if out:
                all_outcomes.extend(out)
                continue
            if len(self.queue):
                if self.queue.last_pop_throttled:
                    # Weighted-fair admission: queued pods remain but
                    # every tenant is credit-blocked — only logical-clock
                    # advance (refill / aging escape) can admit them, so
                    # polling again this instant would spin max_rounds.
                    break
                continue
            if wait_backoff and self.queue.sleep_until_backoff():
                continue
            break
        return all_outcomes

    # -- reshaping (split / merge / rebalance) -----------------------------

    def apply_handoff(self, record: dict, map_path: str | None = None) -> None:
        """Execute one shard-map transfer end to end, in the order that
        makes a crash anywhere convergent: the ACQUIRING owner journals
        the handoff record and imports the nodes (with their bound pods,
        each binding re-journaled into ITS journal), the map file is
        rewritten at the record's version, and only then does the losing
        owner drop its copies.  The map on ``self.shard_map`` is already
        mutated (split/merge/assign bumped the version and returned
        ``record``); fleet/takeover.py replays exactly this sequence when
        recovery finds a handoff record newer than the on-disk map."""
        if record.get("op") == "rebalance":
            # Every owner may owe nodes to every other: the record names
            # no single (src, dst) pair, so sweep all ordered pairs —
            # export filters to the source's actual copies, so pairs
            # with nothing to move are skipped cheaply.
            moves = [
                (s, d)
                for s in self.shard_ids()
                for d in self.shard_ids()
                if s != d
            ]
        else:
            src, dst = record.get("from", -1), record["to"]
            if src not in self.owners or dst not in self.owners:
                raise ValueError(f"handoff {record} names an unknown shard")
            moves = [(src, dst)]
        # Imports first (each journaled by its acquirer), ONE map write,
        # then the drops — a crash anywhere leaves every transfer either
        # redoable from a journal or still held by its source.
        drops: list[tuple[int, list[str]]] = []
        touched: set[int] = set()
        for src, dst in moves:
            # The nodes that move: everything the NEW map assigns to dst
            # that the source owner still holds (export filters to its
            # copies).
            names = [
                n
                for n in sorted(self._node_pos)
                if self.shard_map.owner_of(n) == dst
            ]
            payload = self._call(src, "export_nodes", {"names": names})
            moved = [n["metadata"]["name"] for n in payload["nodes"]]
            if not moved:
                continue
            self._call(
                dst, "import_nodes", {"record": record, "payload": payload}
            )
            drops.append((src, moved))
            touched |= {src, dst}
            for name in moved:
                left = self._shard_node_count.get(src, 0) - 1
                if left > 0:
                    self._shard_node_count[src] = left
                else:
                    self._shard_node_count.pop(src, None)
                self._shard_node_count[dst] = (
                    self._shard_node_count.get(dst, 0) + 1
                )
            for entry in payload.get("pods", ()):
                meta = entry["pod"]["metadata"]
                uid = meta.get("uid") or f"{meta['namespace']}/{meta['name']}"
                self._pod_shard[uid] = dst
        if map_path:
            self.shard_map.save(map_path)
        # The mid-drop window (faults.KILL_POINTS, ISSUE 11): the map is
        # durable at the new version but the losing owner still holds
        # its copies — takeover's map-enforcement sweep finishes the
        # interrupted drop (takeover.recover_shard).
        from .. import journal as _journal

        _journal._crash("mid-drop")
        for src, moved in drops:
            self._call(src, "drop_nodes", {"names": moved})
        self._handoffs.inc(op=record.get("op", "?"))
        for shard in sorted(touched):
            self._shard_nodes.set(
                self._call(shard, "stats", {})["nodes"], shard=str(shard)
            )

    # -- observability -----------------------------------------------------

    def bindings(self) -> dict:
        out: dict[str, str] = {}
        for shard in self.shard_ids():
            out.update(self._call(shard, "bindings", {})["bindings"])
        return out

    def stats(self) -> dict:
        out = {
            "shards": {
                str(s): self._call(s, "stats", {}) for s in self.shard_ids()
            },
            "cycle": self._cycle,
            "queue": self.queue.depths(),
            "binds_by_shard": {
                str(k): v for k, v in sorted(self.binds_by_shard.items())
            },
            "gang_bound": dict(self.gang_bound),
            "gang_rooms": {
                g: sorted(r.pods) for g, r in self._gang_rooms.items()
            },
        }
        if self.tenant_metrics is not None:
            # Fleet-aggregated per-tenant view (the per-shard split rides
            # each owner's stats["tenants"] above).
            out["tenants"] = self.tenant_metrics.snapshot()
        if self.queue.admission is not None:
            # Live fairness view at the fleet-wide admission point:
            # per-tenant weight, credit balance, virtual-time lag, and
            # starvation-SLO verdict (owners mirror the pushed copy).
            out["fairness"] = self.queue.admission.status()
        # Which score families are shard-approximate in this deployment —
        # operators comparing fleet vs single-scheduler transcripts read
        # this before filing a parity bug.
        out["partition_inexact_ops"] = sorted(PARTITION_INEXACT_OPS)
        return out

    def explain(self, uid: str, seq: int = 0) -> dict:
        """Fleet-wide decision provenance: locate the pod's shard, pull
        its local record (and serialized pod), scatter an explain of the
        SAME pod to every other shard, and merge the partitions — global
        per-node totals in row order (_node_pos, the single scheduler's
        enumeration), the union of first-reject verdicts, and the
        router-side selectHost reconstructed from the recorded tie-break
        step.  Annotates the routing path: home shard, binding shard,
        misroute, and which active score families are shard-approximate
        (PARTITION_INEXACT_OPS)."""
        shards = self.shard_ids()
        base = pod_data = None
        bound_shard = self._pod_shard.get(uid)
        if bound_shard is not None and bound_shard in self.owners:
            base = self._call(
                bound_shard, "explain", {"uid": uid, "seq": seq}
            )
        else:
            for s in shards:
                r = self._call(s, "explain", {"uid": uid, "seq": seq})
                if r.get("pod") is not None:
                    bound_shard, base = s, r
                    break
        if base is None or base.get("pod") is None:
            return {"uid": uid, "error": "unknown pod (no shard owns it)"}
        pod_data = base["pod"]
        pod = serialize.pod_from_data(pod_data)
        per_shard: dict[int, dict] = {bound_shard: base["record"]}
        for s in shards:
            if s == bound_shard:
                continue
            per_shard[s] = self._call(
                s, "explain", {"uid": uid, "pod": pod_data}
            )["record"]
        # Merge the partitions by node name into global row order.
        total: dict[str, int] = {}
        feasible: dict[str, int] = {}
        first_reject: dict[str, str] = {}
        shard_of: dict[str, int] = {}
        for s in sorted(per_shard):
            rec = per_shard[s]
            if "error" in rec:
                continue
            for i, name in enumerate(rec["nodes"]):
                total[name] = rec["total"][i]
                feasible[name] = rec["feasible"][i]
                shard_of[name] = s
            first_reject.update(rec.get("first_reject", {}))
        step = self._decision_steps.get(uid)
        cands = sorted(
            (pos, name)
            for name, pos in self._node_pos.items()
            if feasible.get(name)
        )
        select: dict = {
            "tie_break_seed": self.tie_break_seed,
            "step": step,
            "tie_count": 0,
            "pick": None,
        }
        pick = None
        nn = pod.status.nominated_node_name
        if nn and feasible.get(nn):
            # The nominated fast path _select takes before ranking.
            pick = nn
            select["nominated_fast_path"] = True
        elif cands:
            best = max(total[n] for _, n in cands)
            ties = [(p, n) for p, n in cands if total[n] == best]
            tie_rand = None
            if step is not None:
                tie_rand = _hash_u32(
                    (self.tie_break_seed * 0x9E3779B1 + step) & 0xFFFFFFFF
                )
            kth = (tie_rand or 0) % len(ties)
            pick = ties[kth][1]
            select.update(
                best=best,
                tie_count=len(ties),
                tie_rand=tie_rand,
                kth=kth,
                tie_rows=[p for p, _ in ties[:64]],
                nominated_fast_path=False,
            )
        select["pick"] = pick
        home = self.home_shard(pod)
        bound_node = base.get("bound_node")
        active = base["record"].get("active") or []
        doc = {
            "uid": uid,
            "mode": "fleet",
            "home_shard": home,
            "bound_shard": bound_shard,
            "misrouted": bound_node is not None and bound_shard != home,
            "partition_inexact_ops": sorted(
                PARTITION_INEXACT_OPS & set(active)
            ),
            "shards": {str(s): per_shard[s] for s in sorted(per_shard)},
            "nodes": [n for _, n in sorted(
                (p, n) for n, p in self._node_pos.items()
            )],
            "total": {n: total[n] for n in sorted(total)},
            "feasible": sorted(n for n in feasible if feasible[n]),
            "first_reject": first_reject,
            "picked_shard": shard_of.get(pick) if pick else None,
            "select": select,
            "picked_node": pick,
            "bound_node": bound_node,
        }
        # Current-mode shard records re-rank against the LIVE stores, so
        # a pick differing from the binding is expected once later pods
        # shifted the landscape — the field name says exactly what the
        # comparison means.
        doc["would_pick_again"] = (
            (pick == bound_node) if bound_node and step is not None else None
        )
        return doc

    def fleet_flight_snapshots(
        self, limit: int | None = None
    ) -> tuple[list[dict], list[str]]:
        """Every component's flight snapshot + merge labels — the input
        pair ``framework/flight.merge_fleet`` takes: each owner's ring
        (over the wire via the ``flight`` frame for serve children,
        in-process via the scheduler's recorder) plus the router's own."""
        snaps: list[dict] = []
        names: list[str] = []
        for shard in self.shard_ids():
            owner = self.owners[shard]
            sched = getattr(owner, "sched", None)
            if sched is not None:
                snap = sched.flight.snapshot(limit)
            else:
                client = getattr(owner, "client", None)
                try:
                    snap = client.flight(limit or 0) if client else {}
                except (ConnectionError, TimeoutError, OSError):
                    snap = {}
            snaps.append(snap or {"records": []})
            names.append(f"owner-{shard}")
        snaps.append(self.flight.snapshot(limit))
        names.append("router")
        return snaps, names

    def measured_throughput(
        self, lc_lo: float | None = None, lc_hi: float | None = None
    ) -> dict:
        """Fold the whole fleet's flight rings (owners + router) into one
        measured throughput-matrix artifact (framework/measured.py): the
        live analog of ``kubernetes-tpu measured --socket`` for an
        in-process fleet.  Deterministic — derived purely from per-batch
        hetero bind counts on the logical window, never wall time."""
        from ..framework import measured

        snaps, _names = self.fleet_flight_snapshots()
        return measured.derive(snaps, lc_lo=lc_lo, lc_hi=lc_hi)
