"""Warm-standby owner pool: pre-warmed `serve` children a promotion
turns into shard owners in O(handoff) instead of ~15s of cold boot.

ROADMAP named the gap after SOAK_FLEET_r11: mid-incident elasticity —
an autoscale split under a crest, or a takeover replacing a SIGKILLed
owner — paid the new child's boot + XLA compile (~15s in the
two_process_leg) right when the fleet could least afford it.  Tesserae
(arxiv 2508.04953) frames the requirement: scaling actions are only
usable under load when their cost is O(handoff), not O(cold start).

This module keeps N children WARM: XLA programs compiled against the
live featurization schema (a probe propose/remove cycle at spawn),
journal dir pre-created, lease UNCLAIMED — the child owns nothing until
promoted.  Promotion is then: claim the slot (O_EXCL file — the
cross-process race arbiter), append the pool's own WAL record, apply
(``finish_promotion``), and hand the payload to the caller, who drives
the ordinary journaled handoff + lease claim.  Fleet-state correctness
across a SIGKILL anywhere in that window is the EXISTING takeover/redo
machinery's job — the pool only has to never double-offer a slot, which
the claim file + WAL replay guarantee (crash points
``standby-pre-claim`` / ``standby-mid-promotion`` /
``standby-post-promote``; scripts/run_fault_matrix.py --standby-kill).

A standby whose compiled schema no longer matches the live vocab is
retired and respawned (``sync_schema``), never promoted — a stale XLA
cache would recompile mid-incident, which is the exact cost the pool
exists to pre-pay.

Pool health is observable (``scheduler_fleet_standby_*`` families, one
construction site in framework/metrics.StandbyMetrics) and mirrored to
an atomic ``standby.json`` (temp + fsync + replace + dir-fsync, the
shardmap discipline) that `fleet status --sockets` renders without
touching the pool."""

from __future__ import annotations

import json
import os
import time

from .. import journal as _journal
from ..framework.metrics import MetricsRegistry, StandbyMetrics

MIRROR_NAME = "standby.json"
JOURNAL_NAME = "standby.journal"


def atomic_write_json(path: str, doc: dict) -> None:
    """Shardmap-grade atomic document write: temp + fsync + os.replace +
    directory fsync, so a reader never sees a torn mirror and a crash
    never loses the previous complete one."""
    tmp = f"{path}.tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    dfd = os.open(os.path.dirname(path) or ".", os.O_RDONLY)
    try:
        os.fsync(dfd)
    finally:
        os.close(dfd)


class _PoolJournal:
    """The pool's own tiny WAL: fsync'd JSONL of spawn/promote/evict
    records.  Reopen replays it so a slot consumed by a crashed
    promotion is never offered twice."""

    def __init__(self, path: str):
        self.path = path
        self._f = open(path, "a", encoding="utf-8")

    def append(self, rec: dict) -> None:
        self._f.write(json.dumps(rec, sort_keys=True) + "\n")
        self._f.flush()
        os.fsync(self._f.fileno())

    def close(self) -> None:
        try:
            self._f.close()
        except OSError:
            pass

    @staticmethod
    def replay(path: str) -> list[dict]:
        recs: list[dict] = []
        try:
            with open(path, "r", encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        recs.append(json.loads(line))
                    except ValueError:
                        break  # torn tail: the complete prefix stands
        except OSError:
            pass
        return recs


class StandbySlot:
    """One warm child.  ``payload`` is whatever the factory produced —
    an in-process warmed scheduler bundle, or a handle to a spawned
    `serve --standby` process; the pool never looks inside it."""

    __slots__ = ("slot_id", "schema_version", "born_mono", "payload", "state")

    def __init__(self, slot_id: int, schema_version: int, payload):
        self.slot_id = slot_id
        self.schema_version = schema_version
        self.born_mono = time.monotonic()
        self.payload = payload
        self.state = "warm"

    def warm_age_s(self) -> float:
        return time.monotonic() - self.born_mono


class StandbyPool:
    """The pre-forked pool.  ``factory(slot_id) -> payload`` spawns and
    WARMS one child (XLA compiled against the live schema) up front;
    ``promote`` hands the oldest schema-matching slot to a caller in
    O(claim + WAL append) and refills the pool behind it.

    Cross-process safety: promoters racing over a shared ``state_dir``
    are arbitrated by O_EXCL claim files — exactly one wins each slot,
    the loser retries the next.  ``retire(payload)`` (optional) is
    called when a slot is evicted so a real child process can be
    reaped."""

    def __init__(
        self,
        state_dir: str,
        factory,
        size: int = 2,
        schema_version: int = 0,
        registry: MetricsRegistry | None = None,
        retire=None,
        mirror_path: str | None = None,
        fill: bool = True,
    ):
        os.makedirs(state_dir, exist_ok=True)
        self.state_dir = state_dir
        self.factory = factory
        self.size = int(size)
        self.schema_version = int(schema_version)
        self.retire = retire
        self.mirror_path = mirror_path or os.path.join(state_dir, MIRROR_NAME)
        self.metrics = StandbyMetrics(registry or MetricsRegistry())
        self.slots: list[StandbySlot] = []
        self.promotions: dict[str, int] = {}
        self.stale_evictions = 0
        self.misses = 0
        # WAL replay: slots a previous incarnation consumed (promoted or
        # evicted) stay consumed; ids are never reused.  A claim file
        # without a promote record is a promotion that died between
        # claim and append — conservatively consumed (the existing
        # takeover machinery owns the fleet-state half).
        consumed: set[int] = set()
        next_id = 0
        for rec in _PoolJournal.replay(
            os.path.join(state_dir, JOURNAL_NAME)
        ):
            sid = int(rec.get("slot", -1))
            next_id = max(next_id, sid + 1)
            op = rec.get("op")
            if op == "promote":
                consumed.add(sid)
                reason = rec.get("reason", "unknown")
                self.promotions[reason] = self.promotions.get(reason, 0) + 1
            elif op == "evict":
                consumed.add(sid)
                self.stale_evictions += int(
                    rec.get("why") == "schema-stale"
                )
        for name in sorted(os.listdir(state_dir)):
            if name.startswith("slot-") and name.endswith(".claim"):
                try:
                    consumed.add(int(name[len("slot-"):-len(".claim")]))
                except ValueError:
                    pass
        next_id = max(next_id, max(consumed) + 1 if consumed else 0)
        self._next_id = next_id
        self.journal = _PoolJournal(os.path.join(state_dir, JOURNAL_NAME))
        if fill:
            self.fill()
        self._write_mirror()

    # -- spawn / fill ------------------------------------------------------

    def _spawn(self) -> StandbySlot:
        sid = self._next_id
        self._next_id += 1
        # Spawn is journaled before the (expensive) warm factory runs so
        # a crash mid-warmup still retires the id: warmth is
        # reconstructible, identity is not.
        self.journal.append(
            {"op": "spawn", "slot": sid, "schema": self.schema_version}
        )
        slot = StandbySlot(sid, self.schema_version, self.factory(sid))
        self.slots.append(slot)
        return slot

    def fill(self) -> int:
        """Top the pool back up to ``size`` warm slots; returns how many
        were spawned."""
        spawned = 0
        while len(self.idle()) < self.size:
            self._spawn()
            spawned += 1
        if spawned:
            self._write_mirror()
        return spawned

    def idle(self) -> list[StandbySlot]:
        return [s for s in self.slots if s.state == "warm"]

    # -- promotion ---------------------------------------------------------

    def _claim_path(self, slot_id: int) -> str:
        return os.path.join(self.state_dir, f"slot-{slot_id}.claim")

    def _try_claim(self, slot_id: int) -> bool:
        """O_EXCL claim file: the cross-process race arbiter.  Exactly
        one promoter creates it; the loser moves on to the next slot."""
        try:
            fd = os.open(
                self._claim_path(slot_id),
                os.O_CREAT | os.O_EXCL | os.O_WRONLY,
            )
        except FileExistsError:
            return False
        try:
            os.write(fd, str(os.getpid()).encode("ascii"))
            os.fsync(fd)
        finally:
            os.close(fd)
        return True

    def promote(self, shard_id: int, reason: str = "promote"):
        """Hand the oldest schema-matching warm slot to the caller:
        claim → WAL append → apply (``finish_promotion``) → refill.
        Returns the slot's payload, or None on a pool miss (caller falls
        back to the cold-boot path it always had).

        Stale-schema slots are NEVER candidates — their compiled
        programs would recompile mid-incident."""
        t0 = time.perf_counter()
        for slot in sorted(
            self.idle(), key=lambda s: (s.born_mono, s.slot_id)
        ):
            if slot.schema_version != self.schema_version:
                continue
            _journal._crash("standby-pre-claim")
            if not self._try_claim(slot.slot_id):
                slot.state = "claimed-elsewhere"
                continue
            self.journal.append(
                {
                    "op": "promote",
                    "slot": slot.slot_id,
                    "shard": int(shard_id),
                    "reason": reason,
                    "schema": slot.schema_version,
                }
            )
            _journal._crash("standby-mid-promotion")
            self.finish_promotion(slot, shard_id, reason)
            _journal._crash("standby-post-promote")
            self.fill()
            self.metrics.promotion_seconds.observe(
                time.perf_counter() - t0, reason=reason
            )
            return slot.payload
        self.misses += 1
        self._write_mirror()
        return None

    def finish_promotion(self, slot: StandbySlot, shard_id: int, reason: str) -> None:
        """The promotion's apply half (WAL marker — journaled first by
        ``promote``): pool bookkeeping + metrics + mirror.  The fleet-
        side truth (map write, handoff, lease claim) belongs to the
        CALLER's journaled path."""
        slot.state = "promoted"
        self.promotions[reason] = self.promotions.get(reason, 0) + 1
        self.metrics.promotions.inc(reason=reason)
        self._write_mirror()

    # -- schema staleness --------------------------------------------------

    def sync_schema(self, live_version: int) -> int:
        """Adopt the live featurization schema version; retire + respawn
        every warm slot compiled against an older one.  Returns the
        eviction count.  A stale slot is never promoted — eviction is
        the only exit."""
        live_version = int(live_version)
        self.schema_version = live_version
        evicted = 0
        for slot in list(self.slots):
            if slot.state == "warm" and slot.schema_version != live_version:
                self.journal.append(
                    {
                        "op": "evict",
                        "slot": slot.slot_id,
                        "why": "schema-stale",
                        "schema": slot.schema_version,
                        "live": live_version,
                    }
                )
                slot.state = "evicted"
                self.stale_evictions += 1
                self.metrics.stale_evictions.inc()
                if self.retire is not None:
                    self.retire(slot.payload)
                evicted += 1
        if evicted:
            self.fill()
        self._write_mirror()
        return evicted

    # -- observability -----------------------------------------------------

    def status(self) -> dict:
        """JSON-clean pool health (the `fleet status` standby block's
        shape — also what the mirror file holds)."""
        idle = sorted(self.idle(), key=lambda s: s.slot_id)
        doc = {
            "size_target": self.size,
            "pool_size": len(idle),
            "schema_version": self.schema_version,
            "slots": [
                {
                    "slot": s.slot_id,
                    "warm_age_s": round(s.warm_age_s(), 3),
                    "schema": s.schema_version,
                }
                for s in idle
            ],
            "promotions": dict(sorted(self.promotions.items())),
            "promotions_total": sum(self.promotions.values()),
            "schema_stale_evictions": self.stale_evictions,
            "misses": self.misses,
        }
        self.metrics.pool_size.set(len(idle))
        for s in idle:
            self.metrics.warm_age.set(s.warm_age_s(), slot=str(s.slot_id))
        return doc

    def _write_mirror(self) -> None:
        atomic_write_json(self.mirror_path, self.status())

    def close(self) -> None:
        if self.retire is not None:
            for slot in self.slots:
                if slot.state == "warm":
                    self.retire(slot.payload)
        self.journal.close()


class StandbyServe:
    """The in-child half of a `serve --standby` process: sits in
    ``sched._fleet_owner`` while the child waits unclaimed, answering
    only ``standby_status`` and ``adopt_shard`` (fleet_dispatch routes
    here via the ``standby_dispatch`` hook).  Adoption builds the REAL
    ShardOwner around the already-warm scheduler — lease claim, journal
    recovery, shard guard — after which every fleet op flows through the
    ordinary dispatch table."""

    def __init__(self, sched, schema_version: int = 0):
        self.sched = sched
        self.schema_version = int(schema_version)
        self.born_mono = time.monotonic()
        self.owner = None

    def refresh_recovered_taints(self) -> None:
        # SidecarServer refreshes every fleet owner's recovered-taints
        # overlay at boot; a parked standby owns no journal to recover
        # from, so this is a no-op until adoption (which builds the real
        # ShardOwner against the adopted shard's journal).
        if self.owner is not None:
            self.owner.refresh_recovered_taints()

    def standby_dispatch(self, op: str, payload: dict) -> dict:
        from .owner import fleet_dispatch

        if self.owner is not None and op not in (
            "standby_status",
            "adopt_shard",  # idempotent: a retried adopt must not error
        ):
            return fleet_dispatch(self.owner, op, payload)
        if op == "standby_status":
            return {
                "standby": self.owner is None,
                "adopted_shard": (
                    None if self.owner is None else self.owner.shard_id
                ),
                "schema_version": self.schema_version,
                "warm_age_s": round(time.monotonic() - self.born_mono, 3),
            }
        if op == "adopt_shard":
            return self._adopt(payload)
        if op == "preempt_propose":
            # Eval-only dry run, allowed BEFORE adoption: the warm wave
            # compiles the preemption programs while the child is still
            # parked (nothing is deleted or nominated), so a promotion
            # never pays that compile mid-incident.
            from ..api import serialize

            cand = self.sched.preempt_propose(
                serialize.pod_from_data(payload["pod"])
            )
            return cand if cand is not None else {}
        raise ValueError(
            f"standby child not adopted; fleet op {op!r} unavailable"
        )

    def _adopt(self, payload: dict) -> dict:
        from .owner import ShardOwner
        from .shardmap import ShardMap

        if self.owner is not None:
            return {
                "adopted": self.owner.shard_id,
                "already": True,
                "recovery": self.owner.recovery_stats,
            }
        t0 = time.perf_counter()
        shard_id = int(payload["shard_id"])
        live = getattr(self.sched, "journal", None)
        if live is not None and payload.get("journal_dir") and (
            os.path.abspath(payload["journal_dir"])
            == os.path.abspath(getattr(live, "dir", ""))
        ):
            # The standby's own serve journal (pre-created at boot) is
            # NOT the adopted shard's WAL — re-opening the attached dir
            # from inside the serve thread deadlocks; fail loudly.
            raise ValueError(
                "adopt_shard journal_dir is the standby's own serve "
                "journal; pass the adopted shard's journal dir"
            )
        smap = None
        if payload.get("map_path"):
            smap = ShardMap.load(payload["map_path"])
        elif payload.get("map"):
            doc = payload["map"]
            smap = ShardMap(
                buckets=doc["buckets"],
                overrides=doc.get("overrides", {}),
                version=doc.get("version", 0),
                epoch=doc.get("epoch", 0),
            )
        self.owner = ShardOwner(
            shard_id,
            self.sched,
            shard_map=smap,
            state_dir=payload.get("journal_dir") or None,
            journal_fsync=bool(payload.get("journal_fsync", True)),
            snapshot_every_batches=int(payload.get("snapshot_every", 8)),
            lifecycle=payload.get("lifecycle") or None,
        )
        self.sched._fleet_owner = self
        return {
            "adopted": shard_id,
            "already": False,
            "recovery": self.owner.recovery_stats,
            "adopt_s": round(time.perf_counter() - t0, 6),
        }
