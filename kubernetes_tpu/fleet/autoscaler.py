"""Elastic shard autoscaler: load-driven live resharding (ISSUE 11).

The shard map has journaled split/merge/rebalance and a live takeover
path; nothing drove them — PR 10's 100%/0% shard-skew incident showed
the fleet cannot heal its own imbalance.  This module closes that gap:
a deterministic control loop that watches the per-shard signals the
fleet already exports and issues live handoffs through the SAME
journaled ``apply_handoff`` path the kill matrix proves crash-safe, so
a mid-resize SIGKILL is a non-event (``run_fault_matrix.py
--autoscale-kill``).  Tesserae (arxiv 2508.04953) is the grounding:
partitions must resize under load, and resizing must be as crash-safe
as the placements themselves.

Signals (gathered at each tick, on the LOGICAL clock the caller feeds —
the soak's scenario clock, the kill matrix's scripted clock — never a
wall read; this module rides tpulint's determinism family):

- **binding-rate imbalance** — the router's monotone per-shard commit
  counters (``router.binds_by_shard``) differenced into a per-tick
  window; a shard's share × N is its imbalance ratio (1.0 = fair).
  This is the DECIDING signal: a pure function of the op stream, so
  same-seed soaks replay the same action sequence bit for bit.
- **queue depth** — the router queue's backlog, reported in the status
  block (pressure context for operators; not a trigger by itself).
- **SLO latency** — per-shard decision latencies fed by the driver
  (``note_latency``); wall-derived, so ADVISORY by default: the p99
  snapshot rides the status block, and only an explicitly configured
  ``slo_split_gate_ms`` makes it gate splits (documented trade: the
  gate costs same-seed reproducibility under real pacing).
- **owner reachability** — a ``FleetOwnerUnreachable`` out of the tick's
  stats probe (or reported by the driver via ``note_unreachable``)
  DEFERS the whole tick: the loop never acts on stale stats; the shard
  is additionally held out of actions for ``unreachable_holdoff_s``.

Damping — flapping load must not thrash the map:

- **hysteresis band**: split at ratio ≥ ``split_imbalance_hi``, merge at
  ratio ≤ ``merge_imbalance_lo``; anything between is the dead band and
  produces zero actions.
- **per-shard cooldowns**: every shard a handoff touched is held for
  ``cooldown_s`` of logical time.
- **actions-per-window budget**: at most ``max_actions_per_window``
  handoffs per trailing ``window_s``, fleet-wide.
- **quiet gate**: fewer than ``min_window_decisions`` commits in the
  window is noise, not signal — no action.

Actions, all through the journaled handoff path:

- **split** the hottest shard into a fresh shard id (``max(ids)+1``;
  ``owner_provider`` supplies the new owner — a ShardOwner in-process, a
  ``serve --shard-of`` child + WireShardOwner in the real fleet).
  Override pins survive by default (shardmap.split's contract) unless
  ``split_drops_pins`` explicitly drops them.
- **merge** the coldest shard into the next-coldest (``owner_retirer``
  stops the drained owner), never below ``min_shards``.
- **rebalance** when the fleet is at ``max_shards`` and still hot — the
  round-robin re-deal is the only remaining lever.
"""

from __future__ import annotations

import json
import os
from dataclasses import asdict, dataclass

from ..framework.flight import FlightRecorder
from .owner import FleetOwnerUnreachable


@dataclass(frozen=True)
class AutoscalerConfig:
    # Hysteresis band on the imbalance ratio (window share × N shards;
    # 1.0 = perfectly fair).  Between lo and hi nothing happens.
    split_imbalance_hi: float = 1.6
    merge_imbalance_lo: float = 0.35
    # Decision cadence and damping, all in LOGICAL seconds.
    decide_every_s: float = 5.0
    cooldown_s: float = 20.0
    window_s: float = 60.0
    max_actions_per_window: int = 2
    # Fewer window commits than this is noise, not load signal.
    min_window_decisions: int = 12
    # Fleet-size clamps.
    min_shards: int = 1
    max_shards: int = 8
    # A shard reported unreachable is held out of actions this long
    # after the report (on top of the tick-wide stale-stats deferral).
    unreachable_holdoff_s: float = 15.0
    # shardmap.split: pins survive unless this explicitly drops them.
    split_drops_pins: bool = False
    # 0 disables the wall-latency gate (the deterministic default); > 0
    # requires the hot shard's window p99 (ms) to exceed it before a
    # split fires — trades same-seed reproducibility for SLO coupling.
    slo_split_gate_ms: float = 0.0
    # Bounded per-shard latency sample ring (status snapshot only).
    latency_samples: int = 512


def imbalance_ratios(
    window_binds: dict[int, int],
    shards: list[int],
    nodes_owned: dict[int, int] | None = None,
) -> dict[int, float]:
    """Per-shard imbalance ratio, 1.0 = fair.  CAPACITY-AWARE when node
    counts are known: a shard's window binding share is measured against
    its NODE share, so a shard holding half the fleet's nodes serving
    half the binds reads 1.0 — fair for what it hosts — instead of the
    capacity-blind ``share × N`` that read it as permanently hot (the
    ROADMAP follow-up from PR 11).  Without node counts (or for a shard
    with zero nodes) the ``share × N`` baseline stands in."""
    n = len(shards)
    total = sum(window_binds.get(s, 0) for s in shards)
    nodes_total = (
        sum(nodes_owned.get(s, 0) for s in shards) if nodes_owned else 0
    )
    out: dict[int, float] = {}
    for s in shards:
        share = (window_binds.get(s, 0) / total) if total else 0.0
        node_share = (
            nodes_owned.get(s, 0) / nodes_total if nodes_total else 0.0
        )
        out[s] = share / node_share if node_share > 0 else share * n
    return out


def choose_action(
    window_binds: dict[int, int],
    buckets_owned: dict[int, int],
    cfg: AutoscalerConfig,
    blocked: frozenset[int] = frozenset(),
    nodes_owned: dict[int, int] | None = None,
) -> tuple[dict | None, str | None]:
    """The pure decision core, shared by the live loop and the ``fleet
    autoscale`` CLI: given the window's per-shard commit counts and the
    map's per-shard bucket counts, return ``(action, None)`` or
    ``(None, deferral_reason)``.  Deterministic: shards iterate sorted,
    ties break toward the lowest id.  ``blocked`` shards (cooldown,
    unreachable holdoff) can neither source nor receive a handoff.
    ``nodes_owned`` makes the imbalance signal capacity-aware (see
    ``imbalance_ratios``)."""
    shards = sorted(buckets_owned)
    n = len(shards)
    total = sum(window_binds.get(s, 0) for s in shards)
    if n == 0:
        return None, "no-shards"
    if total < cfg.min_window_decisions:
        return None, "quiet"
    ratios = imbalance_ratios(window_binds, shards, nodes_owned)
    hot = min(shards, key=lambda s: (-ratios[s], s))
    cold = min(shards, key=lambda s: (ratios[s], s))
    if ratios[hot] >= cfg.split_imbalance_hi:
        if hot in blocked:
            return None, "cooldown"
        if n < cfg.max_shards:
            if buckets_owned.get(hot, 0) < 2:
                # A one-bucket (or pure-pin) shard cannot split without
                # emptying itself — shardmap.split refuses; so do we.
                return None, "atomic-shard"
            return (
                {"op": "split", "from": hot, "to": max(shards) + 1},
                None,
            )
        if any(s in blocked for s in shards):
            return None, "cooldown"
        # At max_shards and still hot: the round-robin re-deal is the
        # only remaining lever.  The LIVE ids ride the action — after a
        # merge the id space has gaps, and dealing to range(n) would
        # hand buckets to an ownerless shard.
        return {"op": "rebalance", "n_shards": n, "shards": shards}, None
    if ratios[cold] <= cfg.merge_imbalance_lo and n > cfg.min_shards:
        into = min(
            (s for s in shards if s != cold), key=lambda s: (ratios[s], s)
        )
        if cold in blocked or into in blocked:
            return None, "cooldown"
        return {"op": "merge", "from": cold, "to": into}, None
    return None, "in-band"


class FleetAutoscaler:
    """The live control loop over one FleetRouter.  Drive ``tick(now)``
    on the logical clock (the soak wires it to ``autoscale_tick``
    scenario events); feed ``note_latency``/``note_unreachable`` as
    decisions and failures happen.  ``owner_provider(shard_id)`` must
    return a registered-ready owner for split-created shards;
    ``owner_retirer(shard_id, owner)`` stops a merged-away one (default:
    ``owner.close()``)."""

    def __init__(
        self,
        router,
        config: AutoscalerConfig | None = None,
        *,
        map_path: str | None = None,
        owner_provider=None,
        owner_retirer=None,
        registry=None,
        state_path: str | None = None,
        flight: FlightRecorder | None = None,
    ) -> None:
        self.router = router
        self.cfg = config or AutoscalerConfig()
        self.map_path = map_path
        self.owner_provider = owner_provider
        self.owner_retirer = owner_retirer
        self.state_path = state_path
        self._now = 0.0
        if flight is None:
            # The marker ring is timestamped on the LOGICAL clock — a
            # wall read here would put this module's decisions one
            # import away from nondeterminism.
            flight = FlightRecorder(
                capacity=256,
                component="fleet-autoscaler",
                clock=lambda: self._now,
            )
        self.flight = flight
        self._last_decide: float | None = None
        self._bind_marks: dict[int, int] = {}
        self._window_binds: dict[int, int] = {}
        self._window_total = 0
        self._cooldown_until: dict[int, float] = {}
        self._unreachable_until: dict[int, float] = {}
        self._action_times: list[float] = []
        self.actions: list[dict] = []
        self.last_action: dict | None = None
        self.deferrals: dict[str, int] = {}
        self._lat: dict[int, list[float]] = {}
        if registry is None:
            registry = router.registry
        self.registry = registry
        self._m_actions = registry.counter(
            "scheduler_fleet_autoscaler_actions_total",
            "Live resharding actions the autoscaler issued, by op "
            "(split/merge/rebalance).",
        )
        self._m_deferrals = registry.counter(
            "scheduler_fleet_autoscaler_deferrals_total",
            "Autoscaler ticks that chose not to act, by reason "
            "(in-band/quiet/cooldown/budget/owner-unreachable/"
            "atomic-shard/no-owner-provider/slo-gate).",
        )
        self._m_imbalance = registry.gauge(
            "scheduler_fleet_autoscaler_imbalance_ratio",
            "Per-shard window binding share × shard count (1.0 = fair), "
            "as of the last tick.",
        )
        self._m_shards = registry.gauge(
            "scheduler_fleet_autoscaler_shards",
            "Shard count after the last autoscaler tick.",
        )
        self._m_budget = registry.gauge(
            "scheduler_fleet_autoscaler_budget_remaining",
            "Actions still allowed in the trailing budget window.",
        )

    # -- driver-fed signals ------------------------------------------------

    def note_latency(self, shard: int, seconds: float) -> None:
        """Per-decision SLO latency attributed to the committing shard
        (status snapshot; gates nothing unless slo_split_gate_ms > 0)."""
        ring = self._lat.setdefault(shard, [])
        ring.append(seconds)
        if len(ring) > self.cfg.latency_samples:
            del ring[: len(ring) - self.cfg.latency_samples]

    def note_unreachable(self, shard: int) -> None:
        """A fleet call to this owner just exhausted its deadline/retry
        budget: hold it out of actions — takeover owns its fate."""
        self._unreachable_until[shard] = (
            self._now + self.cfg.unreachable_holdoff_s
        )

    def rebind_router(self, router) -> None:
        """Follow a rebuilt front door (cold router restart / takeover
        re-adopt): the decision window restarts at the new router's
        commit counters — half-old, half-new windows would read restart
        churn as load skew."""
        self.router = router
        self._bind_marks = dict(router.binds_by_shard)

    def prime_from_bindings(self) -> None:
        """Seed the decision window from the router's ADOPTED binding
        distribution (takeover/restart: the fresh window counters would
        otherwise read an imbalanced fleet as quiet).  The cumulative
        distribution is the same pure function of the op stream the
        window rates derive from, so a recovery re-decision matches the
        decision the dead fleet made."""
        dist: dict[int, int] = {}
        for shard in self.router._pod_shard.values():
            dist[shard] = dist.get(shard, 0) + 1
        self._window_binds = dist
        self._window_total = sum(dist.values())
        self._bind_marks = dict(self.router.binds_by_shard)
        self._primed = True

    _primed = False

    # -- the control loop --------------------------------------------------

    def tick(self, now: float) -> list[dict]:
        """One pass of the control loop at logical time ``now``.
        Returns the actions taken (at most one per tick — the damped
        cadence; the budget bounds the trailing window besides)."""
        self._now = float(now)
        if (
            self._last_decide is not None
            and now - self._last_decide < self.cfg.decide_every_s
        ):
            return []
        self._last_decide = now
        # Stale-stats gate: probe every owner before reading anything —
        # a hung owner means the imbalance picture is partial, and a
        # partial picture must DEFER, never act.
        try:
            for shard in self.router.shard_ids():
                self.router._call(shard, "stats", {})
        except FleetOwnerUnreachable as exc:
            shard = getattr(exc, "shard_id", None)
            if shard is not None:
                self.note_unreachable(shard)
            self._defer("owner-unreachable")
            return []
        cur = dict(self.router.binds_by_shard)
        if not self._primed:
            window = {
                s: cur.get(s, 0) - self._bind_marks.get(s, 0)
                for s in self.router.shard_ids()
            }
            self._window_binds = window
            self._window_total = sum(window.values())
        self._primed = False
        self._bind_marks = cur
        buckets_owned = self._buckets_owned()
        n = len(buckets_owned)
        self._m_shards.set(n)
        nodes_owned = self._nodes_owned()
        ratios = imbalance_ratios(
            self._window_binds, sorted(buckets_owned), nodes_owned
        )
        for s in sorted(buckets_owned):
            self._m_imbalance.set(round(ratios[s], 4), shard=str(s))
        used = sum(
            1 for t in self._action_times if t > now - self.cfg.window_s
        )
        self._m_budget.set(max(0, self.cfg.max_actions_per_window - used))
        if used >= self.cfg.max_actions_per_window:
            self._defer("budget")
            return []
        blocked = frozenset(
            s
            for s in buckets_owned
            if self._cooldown_until.get(s, -1.0) > now
            or self._unreachable_until.get(s, -1.0) > now
        )
        action, reason = choose_action(
            self._window_binds, buckets_owned, self.cfg, blocked,
            nodes_owned=nodes_owned,
        )
        if action is None:
            self._defer(reason or "in-band")
            return []
        if action["op"] == "split" and self.cfg.slo_split_gate_ms > 0:
            p99 = self._p99_ms(action["from"])
            if p99 < self.cfg.slo_split_gate_ms:
                self._defer("slo-gate")
                return []
        done = self._execute(action, now)
        return [done] if done is not None else []

    def _nodes_owned(self) -> dict[int, int]:
        """Per-shard live node counts (the router maintains them
        incrementally) — the capacity denominator of the imbalance
        signal.  Deterministic: a pure function of the object feed."""
        return dict(self.router._shard_node_count)

    def _buckets_owned(self) -> dict[int, int]:
        """Per-shard bucket counts, derived from the MAP — the ownership
        truth.  A registered owner that holds no buckets (a recovered
        directory whose handoff record was torn before it became
        durable) is not a fleet member for sizing purposes, so a
        takeover's re-decision picks the SAME new shard id the dead
        fleet picked."""
        smap = self.router.shard_map
        owned: dict[int, int] = {}
        for s in smap.buckets:
            owned[s] = owned.get(s, 0) + 1
        for s in smap.overrides.values():
            owned.setdefault(s, 0)
        return owned

    def _defer(self, reason: str) -> None:
        self.deferrals[reason] = self.deferrals.get(reason, 0) + 1
        self._m_deferrals.inc(reason=reason)
        self._persist()

    def _p99_ms(self, shard: int) -> float:
        ring = sorted(self._lat.get(shard, ()))
        if not ring:
            return 0.0
        idx = min(len(ring) - 1, int(len(ring) * 0.99))
        return ring[idx] * 1e3

    # -- execution ---------------------------------------------------------

    def _execute(self, action: dict, now: float) -> dict | None:
        router = self.router
        smap = router.shard_map
        op = action["op"]
        if op == "split":
            new_id = action["to"]
            if new_id not in router.owners:
                # A takeover's re-decision may find the target owner
                # already recovered from its journal directory (the
                # dead fleet created it before the record tore) —
                # reuse it; a second construction would fight its lease.
                if self.owner_provider is None:
                    self._defer("no-owner-provider")
                    return None
                router.add_owner(new_id, self.owner_provider(new_id))
            rec = smap.split(
                action["from"], new_id,
                drop_pins=self.cfg.split_drops_pins,
            )
            touched = [action["from"], new_id]
        elif op == "merge":
            rec = smap.merge(into=action["to"], absorbed=action["from"])
            touched = [action["from"], action["to"]]
        else:  # rebalance — over the LIVE ids; pins survive unless the
            # split policy explicitly drops them (an autonomous re-deal
            # must not silently erase operator/takeover pins).
            rec = smap.rebalance(
                ids=action.get("shards") or router.shard_ids(),
                drop_pins=self.cfg.split_drops_pins,
            )
            touched = router.shard_ids()
        # Guards first (set_map — nothing durable), then the journaled
        # transfer: the acquiring owner appends the handoff record,
        # imports, the map file lands at the record's version, the
        # source drops.  A SIGKILL anywhere inside is exactly what
        # --autoscale-kill sweeps.
        router.push_map()
        # The journal duty is the ACQUIRING owner's: owner.import_nodes
        # appends the handoff record before a node moves — the loop only
        # orchestrates, so the WAL rule's apply-site check is satisfied
        # one layer down (exactly like the matrix/soak call sites).
        # tpulint: disable=wal-unjournaled-apply
        router.apply_handoff(rec, self.map_path)
        if op == "merge":
            drained = router.remove_owner(action["from"])
            if self.owner_retirer is not None:
                self.owner_retirer(action["from"], drained)
            else:
                drained.close()
        self._action_times.append(now)
        self._action_times = [
            t for t in self._action_times if t > now - self.cfg.window_s
        ]
        for s in touched:
            self._cooldown_until[s] = now + self.cfg.cooldown_s
        done = dict(action)
        done.update(clock=round(now, 3), version=rec["version"])
        self.actions.append(done)
        self.last_action = done
        self._m_actions.inc(op=op)
        self.flight.record_marker(f"autoscale_{op}", **done)
        self._persist()
        return done

    # -- observability -----------------------------------------------------

    def status(self) -> dict:
        """The `fleet status` autoscaler block: per-shard imbalance /
        queue-depth / SLO snapshot, last action + cooldown state, and
        the actions-this-window budget."""
        now = self._now
        buckets_owned = self._buckets_owned()
        total = self._window_total
        nodes_owned = self._nodes_owned()
        nodes_total = sum(
            nodes_owned.get(s, 0) for s in buckets_owned
        )
        ratios = imbalance_ratios(
            self._window_binds, sorted(buckets_owned), nodes_owned
        )
        shards = {}
        for s in sorted(buckets_owned):
            w = self._window_binds.get(s, 0)
            shards[str(s)] = {
                "window_binds": w,
                "share": round(w / total, 4) if total else 0.0,
                "imbalance_ratio": round(ratios[s], 4),
                "node_share": (
                    round(nodes_owned.get(s, 0) / nodes_total, 4)
                    if nodes_total
                    else 0.0
                ),
                "buckets": buckets_owned[s],
                "nodes": nodes_owned.get(s, 0),
                "slo_p99_ms": round(self._p99_ms(s), 3),
                "cooldown_remaining_s": round(
                    max(0.0, self._cooldown_until.get(s, 0.0) - now), 3
                ),
                "unreachable_holdoff_s": round(
                    max(0.0, self._unreachable_until.get(s, 0.0) - now), 3
                ),
            }
        used = sum(
            1 for t in self._action_times if t > now - self.cfg.window_s
        )
        return {
            "clock": round(now, 3),
            "shards": shards,
            "queue_depth": len(self.router.queue),
            "window_decisions": total,
            "last_action": self.last_action,
            "actions_total": len(self.actions),
            "deferrals": dict(sorted(self.deferrals.items())),
            "budget": {
                "window_s": self.cfg.window_s,
                "max_actions_per_window": self.cfg.max_actions_per_window,
                "used_in_window": used,
                "remaining": max(
                    0, self.cfg.max_actions_per_window - used
                ),
            },
            "config": asdict(self.cfg),
        }

    def _persist(self) -> None:
        """Atomically mirror the status block to ``state_path`` (the
        `fleet status`/`fleet autoscale` CLI surface; no fsync — this is
        an observability mirror, not scheduling truth)."""
        if not self.state_path:
            return
        doc = self.status()
        tmp = f"{self.state_path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
        os.replace(tmp, self.state_path)
