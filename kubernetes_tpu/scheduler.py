"""The scheduler: the driving loop over queue → device pass → bind.

The batched equivalent of ScheduleOne (pkg/scheduler/schedule_one.go:65):
instead of popping one pod, running the framework's extension points over a
goroutine pool, and binding asynchronously, we pop a batch in QueueSort order,
run the compiled device pass (filter+score+select+commit for every pod in the
batch in one dispatch), then apply the resulting assignments to the host cache
(the assume step — the device already committed them to its state) and hand
unschedulable pods back to the queue."""

from __future__ import annotations

import time
from dataclasses import dataclass, field

import jax
import numpy as np

from .api import types as t
from .cache import Cache
from .engine.features import build_pod_batch
from .engine.pass_ import PassCache
from .framework.config import DEFAULT_PROFILE, Profile
from .intern import InternTable
from .ops.common import registered_subset
from .preemption import PreemptionEvaluator
from .queue import Event, QueuedPodInfo, SchedulingQueue
from .snapshot import SnapshotBuilder


@dataclass
class ScheduleOutcome:
    pod: t.Pod
    node_name: str | None  # None → unschedulable this round
    score: int = 0
    feasible_nodes: int = 0
    nominated_node: str | None = None  # set when preemption picked victims
    victims: int = 0


@dataclass
class SchedulerMetrics:
    """Counters mirroring the reference's core series
    (pkg/scheduler/metrics/metrics.go:138 schedule_attempts_total etc.)."""

    schedule_attempts: int = 0
    scheduled: int = 0
    unschedulable: int = 0
    preemptions: int = 0
    deferred: int = 0  # chunk-conflict deferrals resolved by the strict tail
    batches: int = 0
    device_time_s: float = 0.0
    featurize_time_s: float = 0.0
    first_scheduled_ts: float = 0.0
    last_scheduled_ts: float = 0.0
    throughput_samples: list = field(default_factory=list)
    # Per-pod e2e scheduling latency (enqueue → bind), the analog of
    # pod_scheduling_sli_duration_seconds (metrics/metrics.go:225).
    e2e_latency_samples: list = field(default_factory=list)


class TPUScheduler:
    def __init__(
        self,
        profile: Profile = DEFAULT_PROFILE,
        batch_size: int = 256,
        queue: SchedulingQueue | None = None,
        enable_preemption: bool = True,
        mesh=None,
        chunk_size: int = 1,
    ):
        # Restrict to plugins whose vectorized ops are registered (a no-op
        # once the op inventory is complete; prevents KeyError mid-build-out).
        self.profile = registered_subset(profile)
        self.batch_size = batch_size
        # chunk_size=1 → strictly sequential-equivalent scan (parity mode);
        # >1 → C pods per device step with conflict-deferral + a strict tail
        # pass for the deferred readers (engine/pass_.py module docstring).
        assert batch_size % chunk_size == 0, "batch_size must be a chunk multiple"
        self.chunk_size = chunk_size
        # Strict tail batches are padded to this fixed shape (one compile).
        self.tail_size = min(batch_size, 256)
        self.interns = InternTable()
        self.builder = SnapshotBuilder(self.interns)
        self.cache = Cache(self.builder)
        self.queue = queue or SchedulingQueue()
        self.passes = PassCache()
        self.metrics = SchedulerMetrics()
        self.preemption = PreemptionEvaluator(self) if enable_preemption else None
        # Gang scheduling (the out-of-tree coscheduling plugin's PodGroup):
        # group name → PodGroup; bound-member counts for quorum checks.
        self.pod_groups: dict[str, t.PodGroup] = {}
        self.gang_bound: dict[str, int] = {}
        if mesh is not None:
            # Multi-chip: node axis sharded over the mesh (parallel/mesh.py);
            # XLA inserts the ICI collectives for the cross-shard reductions.
            self.builder.set_mesh(mesh)
        self._cycle = 0
        # Shapes of the last scheduled batch (for warm_tail precompilation).
        self._last_batch_meta: tuple | None = None
        # Pre-intern the hot topology keys so node rows materialize them.
        for key in ("kubernetes.io/hostname", "topology.kubernetes.io/zone",
                    "topology.kubernetes.io/region"):
            self.builder.ensure_topo_key(key)

    def warm_tail(self) -> None:
        """Pre-compile the strict tail pass (chunk=1) with an all-invalid
        batch so a mid-run deferral doesn't pay XLA compilation inside a
        measured window.  No-op when nothing has been scheduled yet or in
        strict mode."""
        if self.chunk_size == 1 or self._last_batch_meta is None:
            return
        shapes, active = self._last_batch_meta
        ts = self.tail_size
        sub = {
            k: np.zeros((ts,) + shape[1:], dtype) for k, (shape, dtype) in shapes.items()
        }
        sub["valid"] = np.zeros(ts, np.bool_)
        inv = self.builder.batch_invariants()
        state = self.builder.state()
        strict = self.passes.get(
            self.profile, self.builder.schema, self.builder.res_col, active, 1
        )
        # All-invalid batch: commits nothing; discard the (identical) state.
        strict(state, sub, inv, np.uint32(0))

    # -- cluster events (the informer surface, eventhandlers.go:341) ---------

    def add_node(self, node: t.Node) -> None:
        self.cache.add_node(node)
        # Replay a CSINode that arrived before its Node (informer races).
        csinode = self.builder.volumes.csinodes.get(node.name)
        if csinode is not None:
            self.builder.set_csinode_limits(self.cache.row_of(node.name), csinode)
        self.queue.on_event(Event.NODE_ADD)

    def update_node(self, node: t.Node) -> None:
        self.cache.update_node(node)
        self.queue.on_event(Event.NODE_UPDATE)

    def remove_node(self, name: str) -> None:
        self.cache.remove_node(name)

    def add_pod(self, pod: t.Pod) -> None:
        """Unassigned pods enter the queue; assigned pods enter the cache
        (eventhandlers.go:126 addPodToSchedulingQueue / :203 addPodToCache)."""
        if pod.spec.node_name:
            self.cache.add_pod(pod)
            self.queue.on_event(Event.POD_ADD)
        else:
            self.queue.add(pod)

    def delete_pod(self, uid: str) -> None:
        if uid in self.cache.pods:
            self.cache.remove_pod(uid)
            self.queue.on_event(Event.POD_DELETE)
        else:
            self.queue.delete(uid)

    def add_pod_group(self, group: t.PodGroup) -> None:
        """Register a gang (coscheduling-style PodGroup: all-or-nothing
        below minMember)."""
        self.pod_groups[group.name] = group
        self.queue.on_event(Event.POD_ADD)

    # -- volume objects (PV/PVC/StorageClass/CSINode informers) --------------

    def add_pv(self, pv: t.PersistentVolume) -> None:
        self.builder.volumes.add_pv(pv)
        self.queue.on_event(Event.PV_ADD)

    def add_pvc(self, pvc: t.PersistentVolumeClaim) -> None:
        self.builder.volumes.add_pvc(pvc)
        self.queue.on_event(Event.PVC_ADD)

    def add_storage_class(self, sc: t.StorageClass) -> None:
        self.builder.volumes.add_class(sc)
        self.queue.on_event(Event.PVC_ADD)

    def add_csinode(self, csinode: t.CSINode) -> None:
        self.builder.volumes.add_csinode(csinode)
        rec = self.cache.nodes.get(csinode.name)
        if rec is not None:
            self.builder.set_csinode_limits(rec.row, csinode)
        self.queue.on_event(Event.NODE_UPDATE)

    # -- scheduling ------------------------------------------------------------

    def schedule_batch(self) -> list[ScheduleOutcome]:
        """Pop up to batch_size pods and schedule them in one device pass."""
        infos = self.queue.pop_batch(self.batch_size)
        if not infos:
            return []
        return self._schedule_infos(infos)

    def _schedule_infos(self, infos: list[QueuedPodInfo]) -> list[ScheduleOutcome]:
        pods = [qp.pod for qp in infos]
        t0 = time.perf_counter()
        # Featurize first: it may grow vocab/schema (forcing a rebuild below).
        # Always pad to the full batch size: one batch shape → one XLA program
        # (a short tail batch costs a few idle scan steps, ~µs; a second
        # compiled shape costs tens of seconds).
        batch, deltas, active = build_pod_batch(
            pods, self.builder, self.profile, self.batch_size
        )
        # Batch invariants (interned term → topo slot) may grow TK/DV: build
        # them after featurization, before the state flush.
        inv = self.builder.batch_invariants()
        t1 = time.perf_counter()
        state = self.builder.state()
        run = self.passes.get(
            self.profile, self.builder.schema, self.builder.res_col, active,
            self.chunk_size,
        )
        new_state, result = run(state, batch, inv, np.uint32(self._cycle))
        # One host round trip for all result arrays (the tunnel to the device
        # has high per-transfer latency; never sync field-by-field).
        picks, scores, feas = jax.device_get((result.picks, result.scores, result.feasible_counts))
        self._cycle += len(infos)
        # Strict tail: chunk-deferred pods (pick == -2) re-run through the
        # sequential-equivalent chunk=1 pass against the committed state, in
        # original order, until none remain (a deferred pod never defers
        # again there).  The tail REORDERS commits after later chunks, so the
        # deferred pods are RE-FEATURIZED against the now-complete term/group
        # vocabularies — a pod's original features only matched the terms
        # interned before it, which is sound solely under batch-order commits.
        deferred = [i for i in range(len(infos)) if picks[i] == -2]
        if deferred:
            picks, scores, feas = picks.copy(), scores.copy(), feas.copy()
            strict = self.passes.get(
                self.profile, self.builder.schema, self.builder.res_col, active, 1
            )
            ts = self.tail_size
            for lo in range(0, len(deferred), ts):
                idx = deferred[lo : lo + ts]
                sub, sub_deltas, _ = build_pod_batch(
                    [infos[i].pod for i in idx], self.builder, self.profile,
                    ts, force_active=active,
                )
                for j, i in enumerate(idx):
                    deltas[i] = sub_deltas[j]
                # Per-pod bucket dims (own terms, devices) are padded to the
                # sub-batch max; pad up to the original batch's shapes so the
                # compiled tail sees one shape set.
                from .ops.common import FEATURE_FILLS

                for key2, arr in sub.items():
                    tgt = batch[key2].shape[1:]
                    if arr.shape[1:] != tgt:
                        padw = [(0, 0)] + [
                            (0, tg - cur) for cur, tg in zip(arr.shape[1:], tgt)
                        ]
                        sub[key2] = np.pad(
                            arr, padw, constant_values=FEATURE_FILLS.get(key2, 0)
                        )
                new_state, res = strict(new_state, sub, inv, np.uint32(self._cycle))
                p2, s2, f2 = jax.device_get(
                    (res.picks, res.scores, res.feasible_counts)
                )
                self._cycle += len(idx)
                picks[idx], scores[idx], feas[idx] = (
                    p2[: len(idx)], s2[: len(idx)], f2[: len(idx)],
                )
            self.metrics.deferred += len(deferred)
        t2 = time.perf_counter()
        self._last_batch_meta = (
            {k: (v.shape, np.asarray(v).dtype) for k, v in batch.items()},
            active,
        )
        self.builder.absorb_device_state(new_state)

        outcomes: list[ScheduleOutcome] = []
        now = time.monotonic()
        m = self.metrics
        m.batches += 1
        m.featurize_time_s += t1 - t0
        m.device_time_s += t2 - t1
        failed: list[tuple[int, QueuedPodInfo, ScheduleOutcome]] = []
        # Phase 1 — assume every pick (cache.go:361 AssumePod; the device
        # already committed the deltas in-scan).
        placed: list[tuple[int, QueuedPodInfo, str]] = []
        for i, qp in enumerate(infos):
            m.schedule_attempts += 1
            row = int(picks[i])
            if row >= 0:
                node_name = self.cache.node_name_at_row(row)
                assert node_name is not None, f"pick={row} maps to no node"
                self.cache.assume_pod(qp.pod, node_name, device_already=True, delta=deltas[i])
                placed.append((i, qp, node_name))
            else:
                failed.append((i, qp, None))

        # Phase 2 — Permit: gang quorum (the coscheduling plugin's Permit
        # gate, which runs BEFORE PreBind so rollback never has to unbind
        # volumes).  Gangs below minMember forget all their assumed members.
        rollback: set[str] = set()
        if self.pod_groups:
            gang_placed: dict[str, int] = {}
            for _i, qp, _n in placed:
                g = qp.pod.spec.pod_group
                if g:
                    gang_placed[g] = gang_placed.get(g, 0) + 1
            for g, count in gang_placed.items():
                pg = self.pod_groups.get(g)
                if pg is None:
                    continue
                if self.gang_bound.get(g, 0) + count < pg.min_member:
                    rollback.add(g)
        for i, qp, node_name in placed:
            g = qp.pod.spec.pod_group
            if g in rollback:
                self.cache.forget_pod(qp.pod.uid)
                m.unschedulable += 1
                outcomes.append(ScheduleOutcome(qp.pod, None, 0, int(feas[i])))
                # Wake on new pod arrivals (more gang members) only.
                self.queue.add_unschedulable(qp, {"GangScheduling"})
                continue
            # Phase 3 — PreBind (VolumeBinding PreBind, volume_binding.go:521):
            # bind delayed claims on the chosen node.  A pod that lost a
            # same-batch PV race is forgotten and retried — the
            # assume/forget protocol (cache.go:404 ForgetPod).
            if any(v.pvc for v in qp.pod.spec.volumes):
                node = self.cache.nodes[node_name].node
                if not self.builder.volumes.bind_pod_volumes(qp.pod, node):
                    self.cache.forget_pod(qp.pod.uid)
                    self.queue.add_backoff(qp)
                    m.unschedulable += 1
                    outcomes.append(ScheduleOutcome(qp.pod, None, 0, int(feas[i])))
                    continue
            qp.pod.spec.node_name = node_name
            self.cache.finish_binding(qp.pod.uid)
            self.queue.done(qp.pod.uid)
            if qp.pod.spec.pod_group:
                self.gang_bound[qp.pod.spec.pod_group] = (
                    self.gang_bound.get(qp.pod.spec.pod_group, 0) + 1
                )
            if m.scheduled == 0:
                m.first_scheduled_ts = now
            m.scheduled += 1
            m.last_scheduled_ts = now
            m.e2e_latency_samples.append(now - qp.initial_attempt_timestamp)
            outcomes.append(
                ScheduleOutcome(qp.pod, node_name, int(scores[i]), int(feas[i]))
            )
        failed2 = []
        for i, qp, _ in failed:
            outcome = ScheduleOutcome(qp.pod, None, 0, int(feas[i]))
            m.unschedulable += 1
            outcomes.append(outcome)
            failed2.append((i, qp, outcome))
        failed = failed2

        # PostFilter: one batched preemption pass for every failure
        # (schedule_one.go:196 RunPostFilterPlugins → DefaultPreemption).
        results = [None] * len(failed)
        if failed and self.preemption is not None:
            rows = {
                key: [np.asarray(arr)[i] for i, _, _ in failed]
                for key, arr in batch.items()
                if key != "valid"
            }
            results = self.preemption.preempt_batch(
                [qp.pod for _, qp, _ in failed], rows, active, inv
            )
        any_victims = False
        for (_, qp, outcome), res in zip(failed, results):
            if res is not None:
                m.preemptions += 1
                outcome.nominated_node = res.node_name
                outcome.victims = len(res.victims)
                any_victims = any_victims or bool(res.victims)
                # The reference waits for the victims' graceful deletion
                # (requeue on their delete events); in-process deletion is
                # synchronous, so the nominated pod can retry immediately.
                self.queue.add(qp.pod)
            else:
                # Without per-plugin diagnosis (the fast path), requeue waits
                # on any event the profile's filters care about.
                self.queue.add_unschedulable(qp, set(self.profile.filters))
        if any_victims:
            self.queue.on_event(Event.POD_DELETE)
        return outcomes

    def schedule_all_pending(
        self, max_rounds: int = 10_000, wait_backoff: bool = False
    ) -> list[ScheduleOutcome]:
        """Drain the active queue (benchmark driver).  With ``wait_backoff``
        the loop also sleeps through backoff expiries (so preempted pods get
        their retry) until only unschedulable/gated pods remain."""
        all_outcomes: list[ScheduleOutcome] = []
        for _ in range(max_rounds):
            out = self.schedule_batch()
            if not out:
                if wait_backoff and self.queue.sleep_until_backoff():
                    continue
                break
            all_outcomes.extend(out)
        return all_outcomes

